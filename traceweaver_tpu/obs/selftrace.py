"""Self-tracing: the pipeline emits its own journey as Jaeger JSON.

TraceWeaver's claim is trace reconstruction without instrumenting the
application. This module closes the loop on the reconstructor itself:
every window's journey through the serving pipeline — ingest → seal →
pack → dispatch → (compaction fetch → redispatch) → decode → emit,
plus any supervisor ladder rungs (retry/bisect/xla/host/quarantine) —
is emitted as spans in the SAME Jaeger-JSON shape the ingest layer
parses (``{"data": [{traceID, spans, processes}]}``), so the pipeline's
own telemetry can be POSTed back into a serve tenant (or loaded by the
batch ingest) and reconstructed BY THE SOLVER ITSELF — the acceptance
round trip in tests/test_obs.py.

Topology (per window, one trace): a root *server* span in service
``tw-window`` covering the whole journey, and per recorded stage one
*client* span in ``tw-window`` calling a *server* span in service
``tw-<stage>``. A one-level fan-out, not a chain, because the real
stage intervals are sequential — nesting them would fake containment;
fanning them out under a root that spans min..max keeps parent⊇child
containment true by construction (the Alibaba-mode validator's
invariant), and the reconstruction problem it induces — one incoming
root per window, one outgoing candidate set per stage endpoint — is
exactly the service-problem shape the fleet solves all day.

Trace context is carried HOST-SIDE: the window key travels on
``FleetItem.trace_key`` through the fleet's pack thread, dispatch
flows, and decode workers (``pg["trace_keys"]`` on the dispatch
ticket), so spans emitted from any worker thread land on the right
window's trace. The tracer itself is lock-guarded; ``active()`` returns
None when no tracer is installed, which is the production default — one
global read per hook site.

Ingest compatibility: fix mode ``SELFTRACE_FIX`` (6) in
``ingest/jaeger.py`` maps to the root operation :data:`ROOT_OP` with no
repair shims and no Alibaba remapping — ``serve --fix 6`` makes a
tenant that ingests the pipeline's own spans.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

#: the FIX mode ingest/jaeger.py maps to self-trace payloads
SELFTRACE_FIX = 6
#: required root-span operation name under SELFTRACE_FIX
ROOT_OP = "tw:window"
#: the root span's service (the window's "frontend")
ROOT_SERVICE = "tw-window"

#: canonical stage names, pipeline order (extra stages — ladder rungs —
#: are legal; this is the documentation/order reference)
STAGES = ("ingest", "seal", "pack", "dispatch", "compact-fetch",
          "redispatch", "decode", "emit")


def now_us() -> float:
    """Wall-clock microseconds (the self-trace event-time base: stage
    spans are about when the PIPELINE did the work, so event time and
    processing time coincide)."""
    return time.time() * 1e6


class PipelineTracer:
    """Collects per-window stage spans; builds the Jaeger payload."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> dict(first_us, end_us, stages=[(stage, t0, t1)])
        self._windows: Dict[str, Dict] = {}

    # -- recording --------------------------------------------------------
    def touch(self, key: str, t_us: Optional[float] = None) -> None:
        """First sight of a window (ingest start). Idempotent — only the
        first touch sets the clock."""
        key = str(key)
        with self._lock:
            if key not in self._windows:
                self._windows[key] = dict(
                    first_us=t_us if t_us is not None else now_us(),
                    end_us=None, stages=[])

    def stage(self, key: str, stage: str, t0_us: float,
              t1_us: Optional[float] = None) -> None:
        """Record one stage interval for a window (microseconds, wall).
        Unknown windows are created on the fly (batch callers have no
        ingest/seal phase)."""
        key = str(key)
        if t1_us is None:
            t1_us = now_us()
        t1_us = max(float(t1_us), float(t0_us))
        with self._lock:
            win = self._windows.get(key)
            if win is None:
                win = dict(first_us=float(t0_us), end_us=None, stages=[])
                self._windows[key] = win
            win["stages"].append((str(stage), float(t0_us), float(t1_us)))

    def seal(self, key: str, t_us: Optional[float] = None) -> None:
        """Window sealed: closes the ``ingest`` stage (first touch →
        now) and records the ``seal`` instant."""
        t1 = t_us if t_us is not None else now_us()
        self.touch(key, t1)
        with self._lock:
            first = self._windows[str(key)]["first_us"]
        self.stage(key, "ingest", first, t1)
        self.stage(key, "seal", t1, t1 + 1.0)

    def finish(self, key: str, t_us: Optional[float] = None) -> None:
        """Window emitted: records the ``emit`` instant and closes the
        root span's interval."""
        t1 = t_us if t_us is not None else now_us()
        self.stage(key, "emit", t1, t1 + 1.0)
        with self._lock:
            self._windows[str(key)]["end_us"] = t1 + 1.0

    # -- payload ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._windows)

    def payload(self) -> Dict:
        """The collected journeys as one Jaeger-JSON payload (the exact
        shape ``ingest.jaeger.parse_trace_payload`` takes, fix mode
        ``SELFTRACE_FIX``). Windows with no recorded stages are skipped;
        containment (root ⊇ every stage span, client ⊇ its server span)
        holds by construction."""
        with self._lock:
            windows = {k: (dict(v, stages=list(v["stages"])))
                       for k, v in self._windows.items()}
        data = []
        for key in sorted(windows):
            win = windows[key]
            if not win["stages"]:
                continue
            data.append(self._trace_json(key, win))
        return {"data": data}

    @staticmethod
    def _trace_json(key: str, win: Dict) -> Dict:
        trace_id = "twtrace-" + "".join(
            ch if ch.isalnum() or ch in "._-" else "-" for ch in key)
        # merge repeated stages (a window whose items ride N dispatch
        # groups packs N times; a retried dispatch re-enters) into ONE
        # span per stage name spanning first..last occurrence: the
        # journey stays one candidate per endpoint per window — the
        # well-posed reconstruction problem — while occurrence counts
        # live on the ladder counters/event sink, not the trace shape
        merged: Dict[str, List[float]] = {}
        order: List[str] = []
        for stage, t0, t1 in win["stages"]:
            if stage not in merged:
                merged[stage] = [t0, t1]
                order.append(stage)
            else:
                merged[stage][0] = min(merged[stage][0], t0)
                merged[stage][1] = max(merged[stage][1], t1)
        stages: List[Tuple[str, float, float]] = [
            (s, merged[s][0], merged[s][1]) for s in order]
        lo = min(t0 for _, t0, _ in stages)
        hi = max(t1 for _, _, t1 in stages)
        root_t0 = min(win["first_us"], lo) - 2.0
        root_t1 = (win["end_us"] if win["end_us"] is not None else hi) + 2.0
        root_t1 = max(root_t1, hi + 2.0)

        def span(sid, start, dur, op, refs, pid, kind):
            return dict(
                traceID=trace_id, spanID=sid,
                startTime=float(start), duration=float(max(dur, 1.0)),
                operationName=op,
                references=[{"traceID": trace_id, "spanID": r}
                            for r in refs],
                processID=pid,
                tags=[{"key": "span.kind", "value": kind}])

        spans = [span("root", root_t0, root_t1 - root_t0, ROOT_OP, [],
                      "p-window", "server")]
        processes = {"p-window": {"serviceName": ROOT_SERVICE}}
        for i, (stage, t0, t1) in enumerate(stages):
            pid = "p-" + stage
            processes[pid] = {"serviceName": "tw-" + stage}
            # the client wrapper strictly contains its server span, and
            # the root (padded ±2 µs) strictly contains the client
            spans.append(span(f"c{i}", t0 - 1.0, (t1 - t0) + 2.0,
                              "call-" + stage, ["root"], "p-window",
                              "client"))
            spans.append(span(f"s{i}", t0, t1 - t0, stage, [f"c{i}"],
                              pid, "server"))
        return dict(traceID=trace_id, spans=spans, processes=processes)

    def write(self, path: str) -> int:
        """Write the payload as JSON; returns the trace count."""
        import json
        import os

        payload = self.payload()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, sort_keys=True)
        return len(payload["data"])


_ACTIVE: Optional[PipelineTracer] = None


def install(tracer: Optional[PipelineTracer]) -> Optional[PipelineTracer]:
    """Install (or clear, with None) the process-wide tracer. Returns
    the previous one so scopes can restore it."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = tracer
    return prev


def active() -> Optional[PipelineTracer]:
    return _ACTIVE
