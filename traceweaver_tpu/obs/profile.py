"""JAX profiler hooks behind the ``TW_PROFILE`` knob.

Three pieces, all inert by default:

- :func:`annotate` — a context manager that wraps a host-side stage in
  a ``jax.profiler.TraceAnnotation`` when ``TW_PROFILE=1``, so the
  fleet's pack/dispatch/decode stages show up as named spans on the
  xplane trace the bench already collects. With the knob off (the
  default) it is a null context and jax is never imported here.
- :func:`device_memory_families` — scrape-time gauge families over
  ``device.memory_stats()`` (bytes in use / limit per device), merged
  into ``/metrics`` when ``TW_PROFILE=1``; devices/backends without the
  hook report nothing rather than raising mid-scrape.
- :func:`profile_data_available` — the feature check for
  ``jax.profiler.ProfileData``, which this environment's jax version
  does not export. Profile-parsing helpers (``bench._parse_profile``)
  gate on it and return None, and the bench test skips cleanly instead
  of erroring (the long-standing environmental failure, ISSUE 9
  satellite).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Tuple

from traceweaver_tpu.runtime import knobs as _knobs


def enabled() -> bool:
    """``TW_PROFILE`` (typed registry read, call time — the knob can
    flip between two solves without a reimport)."""
    return _knobs.get_bool("TW_PROFILE")


def profile_data_available() -> bool:
    """Can this jax deserialize xplane traces in-process? (Some jax
    versions do not export ``jax.profiler.ProfileData``.)"""
    try:
        from jax.profiler import ProfileData  # noqa: F401
    except Exception:  # ImportError, or a broken jax install
        return False
    return True


@contextmanager
def annotate(name: str):
    """Named profiler span around a host-side stage (``TW_PROFILE=1``);
    a null context otherwise. Never raises: a backend whose profiler
    lacks TraceAnnotation degrades to the null context."""
    if not enabled():
        yield
        return
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # no jax / no TraceAnnotation on this backend
        yield
        return
    with ctx:
        yield


def device_memory_families() -> List[Tuple[str, str, str,
                                           List[Tuple[Dict[str, str],
                                                      float]]]]:
    """Collector-style gauge families of per-device memory stats
    (``TW_PROFILE=1``; empty otherwise, and empty on backends whose
    devices expose no ``memory_stats``)."""
    if not enabled():
        return []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    samples: List[Tuple[Dict[str, str], float]] = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        label_dev = f"{dev.platform}:{dev.id}"
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                samples.append(({"device": label_dev, "kind": key},
                                float(stats[key])))
    if not samples:
        return []
    return [("tw_device_memory_bytes", "gauge",
             "per-device memory stats (TW_PROFILE=1; device.memory_stats)",
             samples)]
