"""Reconstruction-quality telemetry: plan-derived confidence, calibration
inputs, and a ground-truth-free drift gauge (ISSUE 10, docs/OBSERVABILITY.md
"Quality telemetry").

TraceWeaver's output is *inferred*: every emitted trace is a statistical
assignment that is right only regime-dependently (PAPER.md concedes 0.36
exact-match on high-fan-out services). PR 9 made the *pipeline*
observable; this module makes the *reconstruction quality* observable —
every span's confidence is reduced from the solver's own plan outputs,
summarized onto every emitted trace (``tw.confidence``), scraped as
per-tenant histograms (``tw_trace_confidence``), and watched for
distribution shift without any ground truth (PSI drift gauge).

Two confidence tiers, both reduced HOST-SIDE from the packed solver
block (:mod:`traceweaver_tpu.algorithms.packed_layout`):

- **base** (always available, zero device change — the default device
  programs stay byte-identical): the OT-overrode-argmax flag
  (``CH_NOT_BEST``), the feasible-candidate count (``CH_FEAS``), and the
  plan's top-k SUPPORT — how many candidate columns kept plan mass
  above ``MIN_TOPK_MASS`` (non-``-1`` top-k entries). Support is a
  direct transport-plan quantity: a one-hot plan row has support 1.
  ``conf = (0.5 if overridden else 1.0) / sqrt(max support over
  endpoints)``.
- **device** (``TW_CONF_DEVICE=1`` — one extra compiled program
  variant, then zero recompiles): the quantized top1-top2 row score
  margin and the entropy of the row's entropic-OT conditional
  ``softmax(S/eps)``, exported as two trailing int32 channels.
  ``conf = (0.5 if overridden else 1.0) * (1 - exp(-margin_min))``,
  with the margin reduced over endpoints by min (the weakest link: a
  trace is exactly right only if EVERY endpoint is).

Unlike :mod:`traceweaver_tpu.obs.registry`/``events`` (import-light,
stdlib only), this module imports numpy at module scope — it is consumed
only by solver-side code (fleet decode, stream emission) where numpy is
already resident; the ``cli events``/``lint`` fast paths never import it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from traceweaver_tpu.algorithms import packed_layout as _layout
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs as _knobs

#: confidence is a probability-like score in [0, 1]; bucket edges chosen
#: so the low tail (the traces an operator should distrust) is resolved
CONF_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.9, 0.99, 1.0)

_OBS = _get_registry()
_OBS_TRACE_CONF = _OBS.histogram(
    "tw_trace_confidence",
    "per-emitted-trace reconstruction confidence (min over the trace's "
    "solved spans; docs/OBSERVABILITY.md Quality telemetry)",
    labels=("tenant",), buckets=CONF_BUCKETS)
_OBS_LOW_CONF = _OBS.counter(
    "tw_low_confidence_traces_total",
    "emitted traces whose confidence fell below TW_CONF_LOW",
    labels=("tenant",))
_OBS_DRIFT = _OBS.gauge(
    "tw_confidence_drift_psi",
    "PSI shift statistic of the rolling per-service confidence "
    "distribution vs its frozen reference window (ground-truth-free "
    "drift signal)",
    labels=("key",))
_OBS_DRIFT_MATURE = _OBS.gauge(
    "tw_confidence_drift_mature",
    "1 once the rolling confidence window behind tw_confidence_drift_psi "
    "is fully populated, 0 while the PSI is estimated from a thin "
    "window (sampling noise, not drift — the adapt ladder ignores "
    "immature PSI, and a dashboard should too: CAMPAIGN_r18's "
    "psi=6.17 excursion was an immature chaos-phase window)",
    labels=("key",))


def conf_enabled() -> bool:
    """``TW_CONFIDENCE=0`` kills the whole quality path (no per-span
    reductions, no ``tw.confidence`` on emitted records). Read at call
    time like every knob."""
    return _knobs.get_bool("TW_CONFIDENCE")


def conf_device_enabled() -> bool:
    """``TW_CONF_DEVICE=1`` opts the fleet dispatches into the
    confidence program variant (margin/entropy channels). A static jit
    arg: one compile for the new variant, zero recompiles after."""
    return _knobs.get_bool("TW_CONF_DEVICE")


def low_threshold() -> float:
    """``TW_CONF_LOW``: traces at or below this confidence count as
    low-confidence (counter + query surface default)."""
    return _knobs.get_float("TW_CONF_LOW")


# ---------------------------------------------------------------------------
# per-span reductions over a packed window batch (host side, vectorized)
# ---------------------------------------------------------------------------

def _window_maps(windows: Sequence[Tuple[int, int]]):
    w_of = np.concatenate(
        [np.full(hi - lo, b) for b, (lo, hi) in enumerate(windows)])
    i_of = np.concatenate([np.arange(hi - lo) for lo, hi in windows])
    pos = np.concatenate([np.arange(lo, hi) for lo, hi in windows])
    return w_of, i_of, pos


def new_span_arrays(n_in: int, device: bool = False) -> Dict[str, np.ndarray]:
    """Preallocated per-span quality arrays a caller scatters batches
    into (:func:`scatter_confidence`) before :func:`finish_confidence`."""
    out: Dict[str, np.ndarray] = dict(
        not_best=np.zeros(n_in, dtype=bool),
        cands=np.ones(n_in, dtype=np.int64),
        support=np.ones(n_in, dtype=np.int32),
    )
    if device:
        out["margin"] = np.zeros(n_in, dtype=np.float64)
        out["entropy"] = np.zeros(n_in, dtype=np.float64)
    return out


def scatter_confidence(windows: Sequence[Tuple[int, int]],
                       not_best: np.ndarray, feas: np.ndarray,
                       topk_cols: np.ndarray,
                       arrs: Dict[str, np.ndarray],
                       margin_q: Optional[np.ndarray] = None,
                       entropy_q: Optional[np.ndarray] = None) -> None:
    """Scatter one packed batch's per-span quality reductions into
    ``arrs`` (in place, at the windows' span positions). Vectorized over
    the packed index — decode sits on the dispatch pipeline's critical
    path, so per-span Python here would gate the solve exactly like the
    pack loops the columnar path killed.

    Endpoint reductions are weakest-link by construction — a span is
    exactly right only if EVERY endpoint is: override = any, candidate
    count = product, support = max, margin = min, entropy = max.
    """
    if not windows:
        return
    w_of, i_of, pos = _window_maps(windows)
    arrs["not_best"][pos] = not_best[w_of, :, i_of].any(axis=1)
    arrs["cands"][pos] = np.maximum(
        feas[w_of, :, i_of], 1).astype(np.int64).prod(axis=1)
    # plan support: top-k entries below MIN_TOPK_MASS come back -1, so
    # the non-negative count per row IS the plan's credible-alternative
    # count for that endpoint
    tk = topk_cols[w_of, :, i_of, :]                     # [n, E, K]
    arrs["support"][pos] = np.maximum((tk >= 0).sum(axis=2), 1).max(axis=1)
    if margin_q is not None:
        scale = _layout.CONF_SCALE
        arrs["margin"][pos] = margin_q[w_of, :, i_of].min(axis=1) / scale
        arrs["entropy"][pos] = entropy_q[w_of, :, i_of].max(axis=1) / scale


def finish_confidence(arrs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    arrs["conf"] = confidence_scores(arrs)
    return arrs


def span_confidence_arrays(windows: Sequence[Tuple[int, int]],
                           block: np.ndarray, n_in: int,
                           device: bool = False) -> Dict[str, np.ndarray]:
    """Per-span quality arrays reduced from one packed window batch.

    ``block`` is the ``[B, E, W, C]`` packed solver output
    (:mod:`traceweaver_tpu.algorithms.packed_layout`); ``windows`` are the
    batch's [lo, hi) index pairs into the item's sorted incoming spans
    (they tile [0, n_in)). Returns ``{"not_best", "cands", "support",
    "conf"[, "margin", "entropy"]}`` arrays of length ``n_in``.
    """
    ch = _layout.split_packed(block, confidence=device)
    arrs = new_span_arrays(n_in, device=device)
    scatter_confidence(windows, ch["not_best"], ch["feas"],
                       ch["topk_cols"], arrs,
                       margin_q=ch.get("margin_q"),
                       entropy_q=ch.get("entropy_q"))
    return finish_confidence(arrs)


def confidence_scores(arrs: Dict[str, np.ndarray]) -> np.ndarray:
    """Map the per-span quality arrays to one score in [0, 1].

    Monotone by construction in every input the solver exports: an OT
    override halves it; more credible plan alternatives (base tier) or a
    thinner top1-top2 margin (device tier) shrink it. The *absolute*
    value is a ranking score — the scorecard's confidence-decile
    calibration table (``metrics/accuracy.py``) is what ties it to
    accuracy, per regime, with ground truth.
    """
    base = np.where(arrs["not_best"], 0.5, 1.0)
    if arrs.get("margin") is not None:
        conf = base * (1.0 - np.exp(-np.maximum(arrs["margin"], 0.0)))
    else:
        conf = base / np.sqrt(np.maximum(arrs["support"], 1))
    return np.clip(conf, 0.0, 1.0)


def confidence_records(in_ids: Sequence, arrs: Dict[str, np.ndarray],
                       ) -> Dict[object, Dict]:
    """``{span id: record}`` for one solved item. Records are plain
    JSON-serializable dicts (they ride emitted-trace records and tenant
    checkpoints)."""
    n = len(in_ids)
    conf = arrs["conf"]
    recs = {}
    has_margin = arrs.get("margin") is not None
    for j in range(n):
        rec = dict(conf=round(float(conf[j]), 4),
                   not_best=bool(arrs["not_best"][j]),
                   cands=int(arrs["cands"][j]),
                   support=int(arrs["support"][j]))
        if has_margin:
            rec["margin"] = round(float(arrs["margin"][j]), 3)
            rec["entropy"] = round(float(arrs["entropy"][j]), 3)
        recs[in_ids[j]] = rec
    return recs


def zero_confidence() -> Dict:
    """The quarantined-window record: a fully failed (all-NA) window has
    zero reconstruction confidence by definition — culprit queries must
    be able to exclude it."""
    return dict(conf=0.0, not_best=True, cands=0, support=0)


# ---------------------------------------------------------------------------
# trace / window summaries (the `tw.confidence` surface)
# ---------------------------------------------------------------------------

def trace_confidence(span_ids: Sequence, conf_by_span: Dict) -> Optional[Dict]:
    """``tw.confidence`` summary of one stitched trace: min (a trace is
    right only if every span is) and mean over its SOLVED spans. None
    when none of the trace's spans carry a record (e.g. a single-span
    trace with nothing to reconstruct)."""
    vals = [conf_by_span[sid]["conf"] for sid in span_ids
            if sid in conf_by_span]
    if not vals:
        return None
    return dict(conf=round(min(vals), 4),
                mean=round(sum(vals) / len(vals), 4),
                n_scored=len(vals))


def window_confidence_summary(conf_by_span: Dict,
                              low: Optional[float] = None) -> Dict:
    """``tw.confidence`` summary of one emitted window's solved spans."""
    if low is None:
        low = low_threshold()
    vals = [r["conf"] for r in conf_by_span.values()]
    if not vals:
        return dict(n=0)
    return dict(
        n=len(vals),
        min=round(min(vals), 4),
        mean=round(sum(vals) / len(vals), 4),
        low=int(sum(v <= low for v in vals)),
        overridden=int(sum(r["not_best"] for r in conf_by_span.values())),
    )


def observe_trace(conf: float, tenant: str) -> bool:
    """Land one emitted trace's confidence on the scrape surface
    (histogram + low counter). Returns whether it counted as low."""
    _OBS_TRACE_CONF.observe(conf, tenant=tenant)
    is_low = conf <= low_threshold()
    if is_low:
        _OBS_LOW_CONF.inc(1.0, tenant=tenant)
    return is_low


# ---------------------------------------------------------------------------
# ground-truth-free drift: PSI over the rolling confidence distribution
# ---------------------------------------------------------------------------

#: PSI bin edges over [0, 1] (right-closed; the last edge catches 1.0)
PSI_EDGES = (0.2, 0.4, 0.6, 0.8, 1.0000001)
_PSI_SMOOTH = 1e-4


def psi(ref_counts: Sequence[float], cur_counts: Sequence[float]) -> float:
    """Population-stability index between two binned distributions:
    ``sum (p_cur - p_ref) * ln(p_cur / p_ref)`` with epsilon smoothing
    (the standard ground-truth-free shift statistic; >0.1 = drifting,
    >0.25 = shifted)."""
    ref_n = max(1.0, float(sum(ref_counts)))
    cur_n = max(1.0, float(sum(cur_counts)))
    total = 0.0
    for r, c in zip(ref_counts, cur_counts):
        p_ref = max(r / ref_n, _PSI_SMOOTH)
        p_cur = max(c / cur_n, _PSI_SMOOTH)
        total += (p_cur - p_ref) * math.log(p_cur / p_ref)
    return total


def _bin_counts(values: Sequence[float]) -> List[float]:
    counts = [0.0] * len(PSI_EDGES)
    for v in values:
        for i, edge in enumerate(PSI_EDGES):
            if v <= edge:
                counts[i] += 1.0
                break
    return counts


class ConfidenceDrift:
    """Rolling per-key confidence-distribution watcher.

    The first ``window`` observations per key freeze as the REFERENCE
    distribution; after that, the most recent ``window`` observations
    form the rolling current distribution and every update recomputes
    the PSI between the two. The statistic is exported as
    ``tw_confidence_drift_psi{key=...}`` and a crossing of the alert
    threshold lands ONE structured event (kind ``confidence_drift``) in
    the ``TW_EVENTS`` sink per excursion — re-armed only after the PSI
    falls back under the threshold, so a sustained shift cannot flood
    the log.

    Ground-truth-free by construction: it watches the solver's own
    confidence outputs, so a regime change in the traffic (new overlap
    pattern, a service turning high-fan-out) shows up as drift even
    though nothing can grade the assignments online.
    """

    def __init__(self, window: Optional[int] = None,
                 threshold: Optional[float] = None) -> None:
        self.window = (window if window is not None
                       else _knobs.get_int("TW_CONF_DRIFT_WINDOW"))
        self.threshold = (threshold if threshold is not None
                          else _knobs.get_float("TW_CONF_DRIFT_PSI"))
        self._ref: Dict[str, List[float]] = {}      # frozen bin counts
        self._ref_fill: Dict[str, List[float]] = {}  # values until frozen
        self._cur: Dict[str, List[float]] = {}      # rolling values
        self._alerted: Dict[str, bool] = {}
        self.alerts = 0

    def update(self, key: str, values: Sequence[float]) -> Optional[float]:
        """Fold one window's confidence values for ``key``; returns the
        current PSI once the reference is frozen, else None."""
        if not values:
            return self.last_psi(key)
        fill = self._ref_fill.get(key)
        if key not in self._ref:
            if fill is None:
                fill = self._ref_fill[key] = []
            fill.extend(float(v) for v in values)
            if len(fill) >= self.window:
                self._ref[key] = _bin_counts(fill[:self.window])
                values = fill[self.window:]
                del self._ref_fill[key]
            else:
                return None
        cur = self._cur.setdefault(key, [])
        cur.extend(float(v) for v in values)
        del cur[:-self.window]
        if not cur:
            return None
        stat = psi(self._ref[key], _bin_counts(cur))
        _OBS_DRIFT.set(stat, key=key)
        # exported alongside the PSI so a scrape can tell a real shift
        # from a thin-window excursion without knowing the window size
        _OBS_DRIFT_MATURE.set(1.0 if self.mature(key) else 0.0, key=key)
        if stat > self.threshold and not self._alerted.get(key):
            self._alerted[key] = True
            self.alerts += 1
            _events.emit("confidence_drift", "shift", key=key,
                         psi=round(stat, 4), threshold=self.threshold,
                         window=self.window)
        elif stat <= self.threshold:
            self._alerted[key] = False
        return stat

    def last_psi(self, key: str) -> Optional[float]:
        cur = self._cur.get(key)
        if key not in self._ref or not cur:
            return None
        return psi(self._ref[key], _bin_counts(cur))

    def in_excursion(self, key: str) -> bool:
        """Is ``key``'s alert currently armed (last PSI above the
        threshold, not yet re-armed)? Consumers that suspend
        amortization while a shift is in progress — the stream
        service's plan-cache gate — read this: during an excursion the
        per-window refit must keep re-teaching the carried statistics
        until the PSI falls back under the threshold."""
        return bool(self._alerted.get(key))

    def mature(self, key: str) -> bool:
        """Is the rolling current window for ``key`` fully populated?
        Right after the reference freezes, the rolling distribution is
        estimated from a handful of values and its PSI is sampling
        noise, not drift — the GAUGE still exports it (an operator can
        weigh it), but a CONSUMER that acts on excursions (the
        adaptation controller) must wait for a full window or it will
        actuate on noise and burn its hysteresis cooldown before any
        real shift arrives."""
        return (key in self._ref
                and len(self._cur.get(key, ())) >= self.window)

    # -- checkpoint plumbing (stream/serve state rides pickles) ----------
    def state(self) -> Dict:
        return dict(window=self.window, threshold=self.threshold,
                    ref=self._ref, ref_fill=self._ref_fill,
                    cur=self._cur, alerted=self._alerted,
                    alerts=self.alerts)

    @classmethod
    def from_state(cls, state: Dict) -> "ConfidenceDrift":
        d = cls(window=state["window"], threshold=state["threshold"])
        d._ref = state["ref"]
        d._ref_fill = state["ref_fill"]
        d._cur = state["cur"]
        d._alerted = state["alerted"]
        d.alerts = state["alerts"]
        return d
