"""Prometheus text exposition over the obs registry.

Two consumers (docs/OBSERVABILITY.md, scrape quickstart):

- the serve HTTP server mounts ``GET /metrics`` directly
  (:mod:`traceweaver_tpu.serve.http`), merging the process registry
  with the tenancy layer's scrape-time collector so the exposed
  per-tenant counters are the ``/api/v1/stats`` ledger verbatim;
- batch/stream CLI runs have no HTTP server, so
  :func:`start_metrics_server` runs a stdlib sidecar exporter
  (``--metrics-port`` / ``TW_METRICS_PORT``) on its own daemon thread —
  zero new dependencies, same text format.

Format: Prometheus text exposition 0.0.4 (``# HELP``/``# TYPE`` then
one ``name{labels} value`` line per sample; label values escaped per
the spec). Parsers are line-oriented, so the renderer sorts families
and samples for stable, diffable scrapes.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional

from traceweaver_tpu.obs.registry import MetricsRegistry, get_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_value(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def render_metrics(registry: Optional[MetricsRegistry] = None,
                   extra: Iterable = ()) -> str:
    """Render the registry (plus ``extra`` collector-style families —
    ``(name, kind, help, [(labels, value), ...])`` tuples) as the
    Prometheus text format."""
    registry = registry if registry is not None else get_registry()
    lines = []
    families = list(registry.collect()) + list(extra)
    for name, kind, help_text, samples in families:
        if help_text:
            lines.append("# HELP %s %s"
                         % (name, help_text.replace("\n", " ")))
        lines.append("# TYPE %s %s" % (name, kind))
        for labels, value in samples:
            labels = dict(labels)
            sample_name = labels.pop("__name__", name)
            if labels:
                body = ",".join('%s="%s"' % (k, _escape_label(v))
                                for k, v in sorted(labels.items()))
                lines.append("%s{%s} %s"
                             % (sample_name, body, _fmt_value(value)))
            else:
                lines.append("%s %s" % (sample_name, _fmt_value(value)))
    return "\n".join(lines) + "\n"


class _ExporterHandler(BaseHTTPRequestHandler):
    server_version = "traceweaver-metrics/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — scrapes are chatty
        pass

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            body = b"try /metrics\n"
            self.send_response(404)
        else:
            srv = self.server  # type: ignore[assignment]
            extra = srv.extra_fn() if srv.extra_fn is not None else ()
            body = render_metrics(srv.registry, extra).encode("utf-8")
            self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsExporter(ThreadingHTTPServer):
    """Sidecar ``/metrics`` server bound to one registry."""

    daemon_threads = True

    def __init__(self, registry: MetricsRegistry, host: str, port: int,
                 extra_fn=None) -> None:
        self.registry = registry
        self.extra_fn = extra_fn
        super().__init__((host, port), _ExporterHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def start_metrics_server(port: int, host: str = "127.0.0.1",
                         registry: Optional[MetricsRegistry] = None,
                         extra_fn=None) -> MetricsExporter:
    """Bind and serve ``/metrics`` on a daemon thread (port 0 =
    ephemeral, the test mode). Returns the server; call ``shutdown()``
    +``server_close()`` to stop it."""
    exporter = MetricsExporter(
        registry if registry is not None else get_registry(),
        host, port, extra_fn=extra_fn)
    thread = threading.Thread(target=exporter.serve_forever,
                              kwargs=dict(poll_interval=0.2),
                              name="tw-metrics-exporter", daemon=True)
    thread.start()
    return exporter
