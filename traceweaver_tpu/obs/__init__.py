"""Self-tracing telemetry subsystem (ISSUE 9, docs/OBSERVABILITY.md).

- :mod:`traceweaver_tpu.obs.registry` — typed, thread-safe metrics
  registry (counters/gauges/histograms with label sets) every legacy
  ledger mirrors into;
- :mod:`traceweaver_tpu.obs.exposition` — Prometheus text rendering,
  the serve server's ``GET /metrics``, and the CLI sidecar exporter;
- :mod:`traceweaver_tpu.obs.selftrace` — the pipeline's own journey as
  Jaeger-JSON spans the solver can reconstruct;
- :mod:`traceweaver_tpu.obs.events` — structured JSONL event sink
  (fault-ladder rungs, injections) + the ``cli events`` tail;
- :mod:`traceweaver_tpu.obs.profile` — ``TW_PROFILE`` jax.profiler
  annotations, device-memory gauges, and the ProfileData feature check.

The package is import-light: nothing here imports jax or numpy at
module scope, so hot modules (``algorithms/fleet.py``) can mirror into
the registry for free.
"""

from traceweaver_tpu.obs.registry import (  # noqa: F401
    MetricError,
    MetricsRegistry,
    get_registry,
)
