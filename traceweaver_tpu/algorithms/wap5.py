"""WAP5 statistical baseline (SOSP'05 lineage).

Per-endpoint delay distributions are learnt from the nearest preceding
server span; each client span then picks its most likely parent by an
exponential log-pdf, with a ``magic_delay × mean`` spontaneous cutoff, each
parent used at most once. Output is parent→children oriented and padded with
("NA","NA") (reference: src/trace_reconstructor/ports/python/algorithms/
wap5.py:271-351).
"""

from __future__ import annotations

import statistics

import scipy.stats

from traceweaver_tpu.spans import NA


class WAP5:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes
        self.distribution_values = {}
        self.large_delay = None
        self.magic_delay = 4
        self.all_assignments = {}
        self._already_picked = {}

    # -- distribution learning (wap5.py:271-288) --------------------------
    def _build_distributions(self, incoming_spans, outgoing_spans, out_ep):
        spans = sorted(incoming_spans + outgoing_spans, key=lambda s: s.start_mus)
        for i, span in enumerate(spans):
            if span.span_kind != "client":
                continue
            sent_mus = span.start_mus
            parent = None
            for preceding in reversed(spans[:i]):
                if sent_mus - preceding.start_mus > self.large_delay:
                    break
                if preceding.span_kind == "server":
                    parent = preceding
                    break
            if parent is not None:
                self.distribution_values.setdefault(out_ep, []).append(
                    sent_mus - parent.start_mus
                )

    @staticmethod
    def _logpdf(t, mean):
        return scipy.stats.expon.logpdf(t, scale=mean)

    # -- parent scoring (wap5.py:295-327) ---------------------------------
    def _score_parents(self, incoming_spans, outgoing_spans, out_ep):
        spans = sorted(incoming_spans + outgoing_spans, key=lambda s: s.start_mus)
        for span in spans:
            self._already_picked[span.GetId()] = False

        mean = statistics.mean(self.distribution_values[out_ep])
        for i, span in enumerate(spans):
            if span.span_kind != "client":
                continue
            sent_mus = span.start_mus
            candidates = []
            for preceding in reversed(spans[:i]):
                if sent_mus - preceding.start_mus > self.magic_delay * mean:
                    candidates.append(
                        ("Spontaneous", self._logpdf(self.magic_delay * mean, mean))
                    )
                    break
                if preceding.span_kind == "server" and not self._already_picked[preceding.GetId()]:
                    candidates.append(
                        (preceding, self._logpdf(sent_mus - preceding.start_mus, mean))
                    )
                    self._already_picked[preceding.GetId()] = True
            candidates.sort(key=lambda x: x[1])
            if candidates and candidates[-1][0] != "Spontaneous":
                parent = candidates[-1][0]
                self.all_assignments.setdefault(out_ep, {}).setdefault(
                    parent.GetId(), []
                ).append(span.GetId())

    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments):
        incoming = [s for part in in_span_partitions.values() for s in part]
        self.large_delay = max(s.duration_mus for s in incoming)

        for out_ep, out_spans in out_span_partitions.items():
            self._build_distributions(incoming, out_spans, out_ep)
            self._score_parents(incoming, out_spans, out_ep)

        for out_ep in out_span_partitions:
            self.all_assignments.setdefault(out_ep, {})
            for in_span in incoming:
                if in_span.GetId() not in self.all_assignments[out_ep]:
                    self.all_assignments[out_ep][in_span.GetId()] = [NA]
        return self.all_assignments
