"""Fleet solve: every service's windows in one device dispatch.

The reference exploits multi-service workloads only through a host thread
pool — one ``FindAssignments`` call per service, concurrency from Python
threads (reference executor.py:1015-1026). On TPU that model leaves the
chip idle: each per-service solve is its own device program, and through
the sandbox's remote-device tunnel every dispatch costs ~100 ms of round
trip, so an 8-service workload pays ~8 round trips of pure latency.

This module is the TPU-native alternative (SURVEY.md §2.8 "services
become a batch dimension"): window batches of services are padded to
shared ``[B, E, W, M]`` shape classes, each window tagged with
``param_idx`` — the row of its service's DAG-structure/distribution
tables — and each class rides ONE jitted program
(:func:`traceweaver_tpu.algorithms.weaver_tpu.solve_em_fleet`), including
both EM passes and the batched BIC-GMM refit between them. Services with
similar window geometry share a class; geometry outliers get their own
dispatch rather than inflate everyone's padding (the merge budget is
backend-aware — padding is nearly-free VPU headroom on TPU, real
core-seconds on the CPU stand-in). Dispatch count drops from O(services)
to O(shape classes), typically 1-2.

Dynamism (cache-hit services with skip budget > 0, reference
exp2/run_experiment.sh:128-158) rides the fleet too: those services form
single-pass dispatch groups with bootstrap distributions and water-filled
per-window skip-cap tensors, exactly the per-service dynamism
configuration fused. The true-skips oracle ships its forced rows as
per-window force-skip tensors. Only methods that need the host in the
loop (KDE score mode, single-iteration parallel mode, the true-dist
oracle, missing DAGs) fall back to the per-service :class:`WeaverTPU`
path.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from traceweaver_tpu.algorithms import packed_layout as _layout
from traceweaver_tpu.algorithms.skips import water_fill_skip_caps
from traceweaver_tpu.algorithms.weaver_tpu import (
    DEFAULT_MAX_WINDOW,
    WeaverTPU,
    _bucket,
    _pack_problem_devcols,
    candidate_ranges,
    columnar_enabled,
    in_columns,
    out_columns,
    pack_problem,
    perfect_cut_windows,
    perfect_cut_windows_cols,
    plan_find_assignments,
    refit_fleet_params,
    scatter_window_span_stats,
    solve_em_fleet,
    solve_windows_fleet,
)
from traceweaver_tpu.ops import devcols as _devcols
from traceweaver_tpu.runtime import aot as _aot
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs import profile as _profile
from traceweaver_tpu.obs import quality as _quality
from traceweaver_tpu.obs import selftrace as _selftrace
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.ops.precision import (
    precision_from_env,
    score_itemsize,
    validate_precision,
)
from traceweaver_tpu.runtime import faults as _faults
from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.spans import NA

# fleet single-dispatch budget, denominated in f32 elements for knob
# back-compat (TW_FLEET_BUDGET): live bytes of the [B, E, W, M] score
# block (the dominant allocation) are bounded by 4x this. Past it the
# padded single program would stress HBM; fall back to per-service
# dispatches instead. Group costs are counted in BYTES at the score
# precision (ops/precision.py), so a TW_PRECISION=bf16 solve fits ~2x
# the windows per dispatch and ~2x the pipeline depth under one budget.
#
# None = read TW_FLEET_BUDGET from the registry at CALL time (an env
# change between two solves takes effect without reimport —
# tests/test_analysis.py pins this); tests monkeypatch this attribute to
# force a budget directly.
FLEET_BUDGET_ELEMS: Optional[int] = None


def _fleet_budget_elems() -> int:
    if FLEET_BUDGET_ELEMS is not None:
        return FLEET_BUDGET_ELEMS
    return _knobs.get_int("TW_FLEET_BUDGET")


def _fleet_budget_bytes() -> int:
    return _fleet_budget_elems() * 4

# window-axis keys of a packed fleet batch, dispatch argument order
_BATCH_KEYS = ("in_start", "in_end", "in_valid", "out_start", "out_end",
               "out_valid", "skip_cap", "force_skip")

# the only window-axis tensors that still ship H2D under the
# device-resident path (TW_DEVCOLS): the per-window skip capacities and
# forced-skip rows. Everything else is assembled on device from the
# resident column rings (ops/devcols.py).
_DEVCOLS_BATCH_KEYS = ("skip_cap", "force_skip")

# per-problem param tables, dispatch argument order (after the batch keys)
_TABLE_KEYS = ("pred_mask", "root_mask", "is_last",
               "edge_wt", "edge_mu", "edge_sd",
               "in_wt", "in_mu", "in_sd",
               "ret_wt", "ret_mu", "ret_sd")


def _compaction_warm() -> int:
    """Warm sweep count before convergence compaction redispatches
    (``TW_SWEEP_WARM``, default 2 — sweep 0 plus one verification sweep,
    which certifies the large fraction of windows whose Gauss-Seidel
    assignments are already a fixed point after the forward pass).
    Declared in :mod:`traceweaver_tpu.runtime.knobs`: an unparseable
    value raises instead of silently running the default."""
    return _knobs.get_int("TW_SWEEP_WARM")


def _compaction_on() -> bool:
    """``TW_COMPACT=0`` kills convergence compaction (single fused
    dispatch per group, the pre-compaction shape)."""
    return _knobs.get_bool("TW_COMPACT")


def _pipeline_on() -> bool:
    """``TW_PIPELINE=0`` kills the pipelined dispatcher: groups pack,
    dispatch, and decode strictly sequentially on the calling thread
    (the pre-pipeline flow, kept as the bit-identical reference path and
    as the kill switch)."""
    return _knobs.get_bool("TW_PIPELINE")


def _decode_workers() -> int:
    """Worker count of the pipeline's flow pool (``TW_DECODE_WORKERS``,
    default 2). Each worker drives one group's dispatch -> compaction
    round trips -> output fetch -> decode, so this bounds how many
    groups can overlap their host-side work with other groups' device
    execution (the live-element budget bounds depth independently)."""
    return _knobs.get_int("TW_DECODE_WORKERS")


def _retry_max() -> int:
    """Bounded redispatch retries before the supervisor's ladder bisects
    (``TW_RETRY_MAX``, default 2)."""
    return _knobs.get_int("TW_RETRY_MAX")


def _retry_backoff_s() -> float:
    """Base of the exponential retry backoff, seconds
    (``TW_RETRY_BACKOFF_S``, default 0.02 — attempt k sleeps
    ``base * 2**k``; transient device faults such as OOM-under-contention
    or a relay flake clear on their own, so retries must not hammer)."""
    return _knobs.get_float("TW_RETRY_BACKOFF_S")


def _fault_check(site: str, st: "_Stats") -> None:
    """Deterministic fault-injection hook (``TW_FAULTS``), ledgered.
    With no active plan this is one cached-module call returning
    immediately — the production no-fault path stays bit-identical."""
    if _faults.active() is None:
        return
    try:
        _faults.maybe_fail(site)
    except _faults.FaultError:
        st.add("faults_injected")
        st.add("faults_injected_" + site)
        raise


# obs registry mirrors (docs/OBSERVABILITY.md): every _Stats update
# ALSO lands in the process metrics registry so `GET /metrics` sees the
# fleet ledger with labels. The legacy dict stays authoritative for
# bench/executor field names; the bench `telemetry_snapshot` field
# proves the two agree (registry counter deltas == the solve's dict).
_OBS = _get_registry()
_OBS_LEDGER = _OBS.counter(
    "tw_fleet_ledger_total",
    "fleet solve ledger mirror (one series per _Stats counter key)",
    labels=("key",))
_OBS_GAUGE = _OBS.gauge(
    "tw_fleet_gauge",
    "fleet high-water marks (_Stats.record_max mirror)",
    labels=("key",))
_OBS_LADDER = _OBS.counter(
    "tw_fault_ladder_events_total",
    "solve-supervisor degradation-ladder rungs walked",
    labels=("key", "rung"))
_OBS_TENANT = _OBS.counter(
    "tw_tenant_windows_total",
    "per-tenant fleet window buckets (packed/redispatched/decoded)",
    labels=("key", "tenant"))
_OBS_DISPATCH_S = _OBS.histogram(
    "tw_dispatch_seconds",
    "per-group fleet dispatch launch time (host side)")


class _Stats:
    """Lock-guarded accumulator over the caller's stats dict.

    Under the pipelined dispatcher the pack thread, the dispatch/decode
    flow workers, and the per-service fallback pool all mutate the same
    dict; a bare ``stats[k] = stats.get(k, 0) + v`` read-modify-write
    would race and silently drop counts, so every update goes through
    one locked helper. ``d is None`` (caller passed no stats) makes the
    dict half a no-op; the obs-registry mirror runs either way, so the
    scrape surface never has blind spots (twlint TW007 enforces that no
    new counter grows outside this path).

    The serve dispatch ring leans on the same shape from the outside:
    each concurrent ``solve_fleet`` call (one per in-flight ticket)
    gets its OWN local stats dict — and therefore its own ``_Stats``
    instance and lock — so ticket dispatches never contend here; the
    per-ticket dicts are folded into the service ledger under the
    service lock at complete (serve/tenancy.py ``_merge_stats``)."""

    def __init__(self, d: Optional[Dict[str, float]]):
        self.d = d
        self._lock = threading.Lock()

    def add(self, key: str, val: float = 1.0) -> None:
        _OBS_LEDGER.inc(val, key=key)
        if self.d is None:
            return
        with self._lock:
            self.d[key] = self.d.get(key, 0.0) + val

    def record_max(self, key: str, val: float) -> None:
        _OBS_GAUGE.set_max(val, key=key)
        if self.d is None:
            return
        with self._lock:
            self.d[key] = max(self.d.get(key, 0.0), val)

    def merge(self, other: Dict[str, float]) -> None:
        for k, v in other.items():
            _OBS_LEDGER.inc(v, key=k)
        if self.d is None:
            return
        with self._lock:
            for k, v in other.items():
                self.d[k] = self.d.get(k, 0.0) + v

    def note(self, key: str, event: str) -> None:
        """Append to an ORDERED event list under ``key`` (the supervisor's
        degradation-ladder audit trail — ``fault_ladder``). List-valued,
        unlike every counter, so consumers that aggregate numerically
        must skip it; it serializes to JSON like the rest of the dict.
        Each event also mirrors to the labelled ladder counter and, when
        an event sink is installed (``TW_EVENTS``), to the structured
        JSONL log — the durable, timestamped copy of this list."""
        _OBS_LADDER.inc(1.0, key=key, rung=event)
        _events.emit(key, event)
        if self.d is None:
            return
        with self._lock:
            self.d.setdefault(key, []).append(event)

    def bucket(self, key: str, subkey: str, val: float = 1.0) -> None:
        """Accumulate into a nested ``{subkey: count}`` dict under
        ``key`` — the per-tenant ledger (``tenant_windows_packed`` etc.).
        Dict-valued like ``note``'s lists, so numeric aggregators skip
        it; only written when the serve layer actually tags items with
        tenants, so no-tenant callers' stats dicts are unchanged."""
        _OBS_TENANT.inc(val, key=key, tenant=subkey)
        if self.d is None:
            return
        with self._lock:
            d = self.d.setdefault(key, {})
            d[subkey] = d.get(subkey, 0.0) + val


def _as_stats(stats) -> _Stats:
    return stats if isinstance(stats, _Stats) else _Stats(stats)


def _trace_stage(keys, stage: str, w0_us: float,
                 w1_us: Optional[float] = None) -> None:
    """Record one pipeline stage on every window trace in ``keys``
    (obs/selftrace.py). ``keys`` is the group's host-side trace context
    — carried on the dispatch ticket so pack thread, flow workers, and
    decode workers all stamp the same windows. One global read and out
    when no tracer is installed (the production default)."""
    tr = _selftrace.active()
    if tr is None or not keys:
        return
    for key in keys:
        tr.stage(key, stage, w0_us, w1_us)


def _note_aot(st: "_Stats", shape: Optional[str]) -> None:
    """Per-solve AOT-escape ledger: a dispatched shape outside the
    precompiled lattice lands in the ordered ``aot_misses`` event list
    (runtime/aot.py names it; the horizon is tuned from this). No-op
    when no warmup armed the lattice, or on a lattice hit."""
    if shape:
        st.note("aot_misses", shape)


def _copy_async(out) -> None:
    """Start an async D2H transfer of a device handle (no-op for host
    arrays and backends without the hook)."""
    try:
        out.copy_to_host_async()
    except AttributeError:  # plain np.ndarray under some backends/flows
        pass


def _fetch(handle, st: _Stats, flow_wait=None, flag_fetch: bool = False):
    """Blocking device fetch: billed to ``wait_s`` (the device-execution
    proxy stage) and to the D2H byte ledger ``d2h_bytes_fetched``. Flag
    fetches additionally land in ``d2h_bytes_flags``, making the
    compaction contract — O(B) bytes to learn the convergence set, not
    the whole packed block — auditable from stats alone. ``flow_wait``
    (a 1-element list) accumulates this flow's blocking time so the
    dispatcher can subtract it from its launch-time accounting without
    reading the shared dict back."""
    _fault_check("fetch", st)
    t0 = time.perf_counter()
    out = np.asarray(handle)
    dt = time.perf_counter() - t0
    st.add("wait_s", dt)
    if flow_wait is not None:
        # twlint: disable=TW007 — flow-local wait aggregator (a 1-element
        # list returned to the dispatcher), not a ledger counter; the
        # telemetry copy is the st.add("wait_s") mirror above
        flow_wait[0] += dt
    st.add("d2h_bytes_fetched", float(out.nbytes))
    if flag_fetch:
        st.add("d2h_bytes_flags", float(out.nbytes))
    return out


def _fetch_flags(flags, st: _Stats, flow_wait, mesh=None):
    """Convergence-flag fetch for one dispatch group, coalesced.

    Single device: one blocking fetch of the ``[B]`` bool array (O(B)
    bytes — the compaction contract). On a mesh the flags come back
    SHARDED, and a naive host read fans into one D2H round trip per
    shard; instead the shards are gathered onto the mesh's first device
    (ICI, device-side) and the host pays ONE transfer — billed under
    ``d2h_bytes_flags`` exactly like the single-device path, with
    ``d2h_flag_fetches`` counting fetches (one per dispatch group, not
    per shard) so the fan-in stays auditable from stats alone."""
    if mesh is not None and int(mesh.devices.size) > 1:
        from traceweaver_tpu.parallel.mesh import coalesce_to_device0

        flags = coalesce_to_device0(flags, mesh)
    st.add("d2h_flag_fetches", 1.0)
    return _fetch(flags, st, flow_wait, flag_fetch=True)


class FleetItem:
    """One service's solve request (the FindAssignments argument set)."""

    def __init__(self, svc, in_span_partitions, out_span_partitions,
                 true_assignments, dag=None,
                 method="MaxScoreBatchSubsetWithSkips", store=None,
                 warm_dists=None, tenant=None, in_cols=None, out_cols=None,
                 trace_key=None, plan_key=None):
        self.svc = svc
        self.in_span_partitions = in_span_partitions
        self.out_span_partitions = out_span_partitions
        self.true_assignments = true_assignments
        self.dag = dag
        self.method = method
        # optional TraceStore for the per-service fallback path (its host
        # EM refit reads the global span table); unused by the fused path
        self.store = store
        # optional carried {edge key -> EdgeDist} (streaming warm start):
        # replaces the plan's cold fit and collapses the solve to a single
        # pass — the on-device EM refit is what the carried statistics
        # already are (stream/state.py CarriedState)
        self.warm_dists = warm_dists
        # optional tenant id (the serve layer's shared-fleet tenancy,
        # traceweaver_tpu/serve): a host-side id column carried through
        # pack -> compaction -> decode so per-tenant window counts,
        # straggler redispatches, and quarantines are attributable from
        # the stats ledger alone. None (the default, and the only value
        # every pre-serve caller produces) keeps the ledger and the
        # dispatched programs byte-identical — the column never ships to
        # the device.
        self.tenant = tenant
        # optional pre-built SpanArray columns over the SORTED partitions
        # (in: (start, end) order; out: per-endpoint ascending-start) —
        # the stream micro-batch builder hands windows over as column
        # slices so the fleet pack never re-walks span objects. Absent
        # (batch callers), _prepare converts once at the solve boundary.
        self.in_cols = in_cols
        self.out_cols = out_cols
        # optional self-trace window key (obs/selftrace.py): the host-side
        # trace context that follows this item's windows through the pack
        # thread, dispatch flows, and decode workers so the pipeline's own
        # journey spans land on the right window's trace. None (the
        # default) with no tracer installed costs one global read per
        # hook site.
        self.trace_key = trace_key
        # optional plan-cache identity (algorithms/plancache.py). Service
        # names repeat across call graphs in campaign corpora, so callers
        # that solve several graphs against ONE cache must disambiguate
        # (the campaign runner keys "store:svc"); None falls back to svc.
        self.plan_key = plan_key


def _plan_key(item: FleetItem) -> str:
    return item.plan_key if item.plan_key is not None else item.svc


def _prepare(item: FleetItem, solver: WeaverTPU,
             cached_dists=None):
    """Host preamble of FindAssignments for one item (sort, topo order,
    skip budget, distributions). Returns None when the item needs a code
    path the fleet does not cover (no DAG, KDE scoring, true-dist oracle).

    Dynamism (skip budget > 0 — the cache-hit workloads, reference
    exp2/run_experiment.sh:128-158) stays IN the fleet: those services get
    the per-service path's bootstrap distributions and a single-pass plan
    (``n_passes=1``, no EM refit — identical to ``iterations = 1`` in
    :meth:`WeaverTPU.FindAssignments`), with their water-filled skip caps
    carried as per-window tensors in the fused dispatch."""
    if item.dag is None or solver.score_mode != "mixture":
        return None
    if item.method not in ("MaxScoreBatchSubsetWithSkips",
                           "MaxScoreBatchSubsetWithTrueSkips"):
        return None
    in_ep, in_spans = next(iter(item.in_span_partitions.items()))
    in_spans = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
    out_eps = solver._topo_out_eps(item.out_span_partitions, item.dag)
    # the SAME plan the per-service entry point computes (one definition,
    # weaver_tpu.plan_find_assignments — the paths cannot drift); the
    # true-skips oracle's forced rows ride the dispatch as per-window
    # force-skip tensors (the device solver input, weaver_tpu.py:94)
    plan = plan_find_assignments(
        item.in_span_partitions, item.out_span_partitions, out_eps,
        item.dag, item.true_assignments, score_mode=solver.score_mode,
        true_skips=(item.method == "MaxScoreBatchSubsetWithTrueSkips"),
        # the fit is dead computation when warm/cached dists override it
        # below — same plan otherwise (budgets, dynamism, iterations)
        skip_fit=(item.warm_dists is not None or cached_dists is not None),
    )
    dists, n_passes = plan["dists"], plan["iterations"]
    if item.warm_dists is not None:
        # streaming warm start: carried per-edge statistics from earlier
        # windows replace both the cold fit and the refit pass; the item
        # joins the single-pass dispatch groups (unseen edges fall back
        # to pack_problem's near-flat wide Gaussian)
        dists, n_passes = item.warm_dists, 1
    elif cached_dists is not None:
        # plan-cache hit (algorithms/plancache.py): the previous round's
        # fitted plan — a cold fit or the decoded on-device refit tables —
        # replaces the fit AND the refit pass, exactly the warm contract
        dists, n_passes = cached_dists, 1
    # columnar handoff (TW_COLUMNAR, default): reuse the item's pre-built
    # columns (stream/serve hand their sorted window slices over) or
    # convert ONCE here — downstream windowing/ranges/pack is array work
    in_cols = out_cols = None
    if columnar_enabled():
        in_cols = (item.in_cols
                   if item.in_cols is not None
                   and len(item.in_cols) == len(in_spans)
                   else in_columns(in_spans))
        out_cols = (item.out_cols
                    if item.out_cols is not None
                    and all(ep in item.out_cols for ep in out_eps)
                    else out_columns(item.out_span_partitions, out_eps))
    return dict(in_ep=in_ep, in_spans=in_spans, out_eps=out_eps,
                skip_budget=plan["skip_budget"], dists=dists,
                n_in=plan["n_in"], n_passes=n_passes,
                force_skip_ids=plan["force_skip_ids"],
                in_cols=in_cols, out_cols=out_cols)


def _raw_cells(item: FleetItem, max_window: int) -> float:
    """Padded-compute-cell count for an item solved OUTSIDE a fused
    dispatch (host-in-the-loop fallbacks), from its raw partitions — the
    same ``n_windows * W * M * E * n_passes`` model the fused plan
    records, so mixed fused/fallback workloads attribute wall-clock on
    one scale. The pass count mirrors ``WeaverTPU.FindAssignments``:
    one pass under dynamism or the true-dist oracle, two otherwise."""
    in_spans = sorted(next(iter(item.in_span_partitions.values())),
                      key=lambda s: (s.start_mus, s.end_mus))
    out_eps = list(item.out_span_partitions)
    windows = perfect_cut_windows(in_spans, max_window)
    out_starts_np = {
        ep: np.array(sorted(float(s.start_mus)
                            for s in item.out_span_partitions[ep]))
        for ep in out_eps
    }
    ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np)
    w_b = _bucket(max(hi - lo for lo, hi in windows))
    m_b = _bucket(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)))
    n_in = len(in_spans)
    dynamism = any(n_in - len(item.out_span_partitions[ep]) > 0
                   for ep in out_eps)
    n_passes = 1 if (dynamism
                     or item.method == "MaxScoreBatchSubsetWithTrueDist") \
        else 2
    return float(len(windows) * w_b * m_b * max(1, len(out_eps)) * n_passes)


def _run_fallback(entries, results, all_spans, all_processes,
                  solver_kwargs, stats, confidences=None) -> None:
    """Per-service solves for items the fused dispatch cannot carry.

    Dispatches overlap through a thread pool (the reference's own
    ThreadPool-over-services model, executor.py:1015-1026) and each
    solver's stage stats merge into the caller's dict — a mixed workload
    keeps both the overlap and the accounting it had on the pre-fleet
    bench path. ``confidences`` (the caller's per-item quality slots,
    obs/quality.py) receives each solver's own per-span records, so
    fallback-path windows carry ``tw.confidence`` exactly like fused
    ones."""
    st = _as_stats(stats)

    def run(entry):
        i, item = entry
        algo = WeaverTPU(
            item.store.all_spans if item.store else all_spans,
            item.store.all_processes if item.store else all_processes,
            **solver_kwargs)
        # oracle methods carry their flag through the fallback too
        # (the same method-name -> kwarg mapping runtime/executor.py does)
        kwargs = {}
        if item.method == "MaxScoreBatchSubsetWithTrueSkips":
            kwargs["true_skips"] = True
        elif item.method == "MaxScoreBatchSubsetWithTrueDist":
            kwargs["true_dist"] = True
        out = algo.FindAssignments(
            item.method, item.svc, item.in_span_partitions,
            item.out_span_partitions, False, [], item.true_assignments,
            item.dag, **kwargs,
        )
        return i, out, algo.stats, algo.per_span_confidence

    with ThreadPoolExecutor(max_workers=max(1, len(entries))) as pool:
        for i, out, solver_stats, conf in pool.map(run, entries):
            results[i] = out
            if confidences is not None:
                confidences[i] = conf
            st.merge(solver_stats)


def solve_fleet(
    items: List[FleetItem],
    all_spans=None,
    all_processes=None,
    max_window: int = DEFAULT_MAX_WINDOW,
    epsilon: float = 1.0,
    n_sinkhorn: int = 40,
    n_sweeps: int = 5,
    sinkhorn_tol: float = 1e-3,
    mesh=None,
    stats: Optional[Dict[str, float]] = None,
    item_cells: Optional[List[float]] = None,
    precision: Optional[str] = None,
    quarantined: Optional[List[int]] = None,
    confidences: Optional[List[Optional[Dict]]] = None,
    plan_cache=None,
) -> List[Tuple]:
    """Solve every item, fusing eligible ones into one device dispatch.

    Dispatch groups ride a bounded multi-stage pipeline by default
    (:func:`_solve_groups_pipelined`): a pack thread builds group N+1's
    tensors while group N executes on the device, each group's
    dispatch/compaction/decode flow runs on a small worker pool
    (``TW_DECODE_WORKERS``), and the ``TW_FLEET_BUDGET`` byte budget
    bounds the live in-flight blocks (the pipeline depth limit). The
    pipeline reorders
    WORK only, never output — results are bit-identical and in input
    order; ``TW_PIPELINE=0`` restores the strictly serial flow.

    ``mesh`` (a ``jax.sharding.Mesh``) shards each dispatch group's
    window-batch axis across the mesh devices under XLA SPMD — the
    multi-chip form of the production path (the same window-axis
    sharding :class:`WeaverTPU` uses per service, applied to the fused
    program; the refit's cross-shard window gather lowers to XLA
    collectives automatically). Convergence compaction applies there
    too, with the redispatch bucketed per shard
    (:func:`traceweaver_tpu.parallel.mesh.bucket_rows_per_shard`).

    ``item_cells`` (when given, a list the caller sized to ``len(items)``)
    receives each item's padded-compute-cell count at its own shape class
    (``n_windows * W * M * E``) — the quantity the device spends time on,
    used by callers to attribute one dispatch's wall-clock to services
    (runtime executor and the parity harness share this model).

    ``precision`` (``"f32"``/``"bf16"``, default = ``TW_PRECISION``) is
    the score-block storage precision for every fused dispatch and the
    per-service fallback alike; the live-dispatch budget and the pipeline
    depth limit account in bytes at this precision.

    Every dispatch group runs under the solve SUPERVISOR: a transient
    device failure (``XlaRuntimeError``, ``RESOURCE_EXHAUSTED``, or an
    injected ``TW_FAULTS`` fault) walks an explicit degradation ladder —
    bounded retry with exponential backoff, bisection of the group to
    isolate the offending service, a fused-Pallas-free XLA redispatch,
    the per-service host fallback — and only a singleton that exhausts
    every rung is QUARANTINED: its slot gets an all-NA result, its index
    is appended to ``quarantined`` (when the caller passes a list), and
    the whole walk is ledgered in ``stats`` (``fault_retries``,
    ``fault_bisections``, ``fault_xla_fallbacks``,
    ``fault_host_fallbacks``, ``fault_quarantined``, plus the ordered
    ``fault_ladder`` event list). Non-transient errors (bugs) propagate
    unchanged. See docs/ROBUSTNESS.md.

    ``confidences`` (when given, a list the caller sized to
    ``len(items)``) receives each item's per-span reconstruction-quality
    records (``{in span id: {conf, not_best, cands, support, ...}}`` —
    :mod:`traceweaver_tpu.obs.quality`), reduced host-side from the SAME
    packed block the decode already fetched. Quarantined items get
    zero-confidence records (a fully failed window must be excludable
    from culprit queries). ``TW_CONF_DEVICE=1`` additionally dispatches
    the confidence program variant, whose quantized margin/entropy
    channels sharpen the score; at default settings the device programs
    are byte-identical to the pre-quality ones.

    ``plan_cache`` (an :class:`traceweaver_tpu.algorithms.plancache.PlanCache`)
    amortizes the host plan fit across repeated solves of the same
    services: hits skip the per-item distribution fit AND collapse the
    two-pass EM to a single warm pass (the ``warm_dists`` contract);
    misses are admitted back — single-pass items from their prepared
    dists, two-pass items from the decoded on-device refit tables
    (:func:`traceweaver_tpu.algorithms.weaver_tpu.dists_from_tables`),
    so the next solve starts where this one's EM ended. Host plan time
    is ledgered under ``plan_fit_s`` either way. Items carrying
    ``warm_dists`` bypass the cache entirely (the stream layer owns its
    own carried state).

    Returns one FindAssignments-style 6-tuple per item, in order:
    ``(all_assignments, all_topk, not_best_count, n_spans,
    per_span_candidates, cnt_unassigned)``.
    """
    # the fused path shards any mesh size (rows pad to a multiple); the
    # per-service fallback solver requires a power-of-two mesh, so a
    # non-pow2 mesh degrades fallback items to single-device rather than
    # crashing the whole mixed solve on WeaverTPU's assert
    n_mesh = int(mesh.devices.size) if mesh is not None else 1
    fallback_mesh = mesh if n_mesh & (n_mesh - 1) == 0 else None
    precision = validate_precision(
        precision if precision is not None else precision_from_env())
    solver_kwargs = dict(max_window=max_window, epsilon=epsilon,
                         n_sinkhorn=n_sinkhorn, n_sweeps=n_sweeps,
                         sinkhorn_tol=sinkhorn_tol, mesh=fallback_mesh,
                         precision=precision)
    solver = WeaverTPU(all_spans, all_processes, **solver_kwargs)
    results: List[Optional[Tuple]] = [None] * len(items)
    st = _as_stats(stats)

    prepared = []
    fallback_entries = []
    t_plan = time.perf_counter()
    for i, item in enumerate(items):
        cached = (plan_cache.lookup(_plan_key(item))
                  if plan_cache is not None and item.warm_dists is None
                  else None)
        prep = _prepare(item, solver, cached_dists=cached)
        if prep is None:
            # host-in-the-loop configuration: per-service path
            fallback_entries.append((i, item))
            if item_cells is not None:
                item_cells[i] = _raw_cells(item, max_window)
        else:
            if (plan_cache is not None and cached is None
                    and item.warm_dists is None and prep["n_passes"] == 1):
                # single-pass miss (dynamism): there is no refit to admit
                # later, so the bootstrap fit that just ran IS the plan
                plan_cache.admit(_plan_key(item), prep["dists"])
            prepared.append((i, item, prep))
    st.add("plan_fit_s", time.perf_counter() - t_plan)
    if fallback_entries:
        _run_fallback(fallback_entries, results, all_spans, all_processes,
                      solver_kwargs, st, confidences=confidences)
    if not prepared:
        return results  # type: ignore[return-value]

    # --- per-item window plan + shape class ------------------------------
    t0 = time.perf_counter()
    plans = []
    for i, item, prep in prepared:
        in_spans, out_eps = prep["in_spans"], prep["out_eps"]
        in_cols, out_cols = prep["in_cols"], prep["out_cols"]
        if in_cols is not None:
            # columnar: windowing + ranges from the partition columns
            windows = perfect_cut_windows_cols(in_cols, max_window)
            out_starts_np = {ep: out_cols[ep].start for ep in out_eps}
        else:
            windows = perfect_cut_windows(in_spans, max_window)
            out_starts_np = {
                ep: np.array(sorted(float(s.start_mus)
                                    for s in item.out_span_partitions[ep]))
                for ep in out_eps
            }
        ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np,
                                  in_cols=in_cols)
        skip_caps = water_fill_skip_caps(
            windows, ranges, len(in_spans),
            [len(item.out_span_partitions[ep]) for ep in out_eps])
        w_b = _bucket(max(hi - lo for lo, hi in windows))
        m_b = _bucket(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)))
        if item_cells is not None:
            item_cells[i] = (len(windows) * w_b * m_b
                             * max(1, len(out_eps)) * prep["n_passes"])
        plans.append((i, item, prep, windows, ranges, skip_caps, w_b, m_b))
    st.add("pack_s", time.perf_counter() - t0)

    # --- group services into dispatch shape classes ----------------------
    # One fused program per class. Services with very different window
    # geometry must not share one padded shape: hotel_load150's search
    # (724 windows of 8x8x2) padded to its frontend's 32x32x3 pays 24x
    # its own compute in padding. Small classes merge upward while the
    # extra padded area stays under a budget that reflects the backend:
    # on TPU padded cells are nearly-free VPU work and a saved dispatch
    # is ~100 ms of tunnel latency (merge aggressively); on the CPU
    # stand-in padded cells are real core-seconds (merge conservatively).
    merge_env = _knobs.get_int("TW_FLEET_MERGE")
    if merge_env is not None:
        merge_budget = merge_env  # 0 = never merge shape classes
    else:
        import jax

        merge_budget = (1 << 24) if jax.default_backend() in ("tpu", "axon") \
            else (1 << 20)

    def shape_cost(group):
        w = max(p[6] for p in group)
        m = max(p[7] for p in group)
        e = max(len(p[2]["out_eps"]) for p in group)
        return sum(len(p[3]) for p in group) * w * m * e

    # class key includes the endpoint-count bucket: an E=12 service fused
    # with an E=1 service would pay 12x endpoint padding on the score
    # block and E^2 growth on the refit rows — exactly the padding class
    # the merge budget exists to arbitrate, so E outliers must start in
    # their own class and only merge if shape_cost approves. The pass
    # count splits classes too: single-pass (dynamism) and two-pass
    # (fused EM) services run different device programs and cannot share
    # a dispatch.
    classes: Dict[Tuple[int, int, int, int], List] = {}
    for plan in plans:
        e_b = _bucket(len(plan[2]["out_eps"]), minimum=1)
        classes.setdefault(
            (plan[2]["n_passes"], plan[6], plan[7], e_b), []).append(plan)
    ordered = sorted(classes, key=lambda k: (k[0], k[1] * k[2] * k[3]))
    groups: List[List] = []
    carry: List = []
    for idx, key in enumerate(ordered):
        wins = carry + classes[key]
        if idx + 1 < len(ordered) and ordered[idx + 1][0] == key[0]:
            nxt = wins + classes[ordered[idx + 1]]
            extra = shape_cost(nxt) - shape_cost(wins) \
                - shape_cost(classes[ordered[idx + 1]])
            if extra <= merge_budget:
                carry = wins
                continue
        groups.append(wins)
        carry = []
    if carry:
        groups.append(carry)

    # --- budget + dispatch per group -------------------------------------
    # TW_CONF_DEVICE opts every fused dispatch into the confidence
    # program variant (quantized margin/entropy channels appended to the
    # packed block — packed_layout.py). A static jit arg, so the default
    # False keeps the dispatched programs byte-identical to the
    # pre-quality ones, and an enabled steady state recompiles nothing.
    conf_device = _quality.conf_device_enabled()
    # device-resident span columns (TW_DEVCOLS, ops/devcols.py): window
    # tensors become on-device gathers from per-service HBM rings, with
    # only index arrays + skip/force tensors shipped per dispatch. Rides
    # the columnar host path's SpanArray columns, single-device only
    # (the mesh path re-places host tensors per shard); the flag travels
    # in hypers_common so the supervisor's bisect re-packs inherit it.
    hypers_common = dict(epsilon=epsilon, n_sinkhorn=n_sinkhorn,
                         n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
                         precision=precision, confidence=conf_device,
                         devcols=(_devcols.devcols_enabled()
                                  and columnar_enabled() and mesh is None),
                         # host-only (like devcols): the dispatcher admits
                         # two-pass refit tables back into the plan cache;
                         # never forwarded to a device program
                         plan_cache=plan_cache)
    itemsize = score_itemsize(precision)
    # supervisor context: what the degradation ladder needs to route a
    # failing singleton to the per-service host fallback, where it
    # records quarantined item indices for the caller (the stream service
    # dead-letters the owning windows from this list), and the caller's
    # per-item confidence slots the decode fills
    ctx = dict(all_spans=all_spans, all_processes=all_processes,
               solver_kwargs=solver_kwargs,
               quarantined=quarantined if quarantined is not None else [],
               confidences=confidences)
    specs: List[_GroupSpec] = []
    for group in groups:
        spec = _make_spec(group, itemsize)
        if spec.cost > _fleet_budget_bytes():
            # padded group block would stress HBM: per-service dispatches.
            # The counter accumulates — a mixed workload can trip the
            # budget on several groups and the ledger must say how many.
            _run_fallback([(p[0], p[1]) for p in group], results,
                          all_spans, all_processes, solver_kwargs, st,
                          confidences=confidences)
            st.add("fleet_fallback_budget", 1.0)
            continue
        # depth-limit observability (bytes): the largest single admission
        # and the total the budget must amortize (budget < total => the
        # pipeline gate/serial drain actually engaged on this workload)
        st.record_max("fleet_group_cost_max", float(spec.cost))
        st.add("fleet_group_cost_total", float(spec.cost))
        specs.append(spec)
    if not specs:
        return results  # type: ignore[return-value]

    from traceweaver_tpu.runtime.jax_cache import compile_counters, counters_delta

    # recompiles are the shape-class regression signal: a warm steady
    # state dispatches with zero compiles, so any nonzero delta here is a
    # new program variant (bench surfaces these per run). Snapshotted
    # around the WHOLE dispatch phase — per-dispatch deltas would double
    # count under the pipeline's concurrent flows.
    counters_before = compile_counters()
    # mesh dispatches carry cross-replica collectives (the sweep's global
    # convergence reduce, the fused refit's cross-shard gather), and XLA's
    # rendezvous matches participants by per-device SUBMISSION order —
    # two host threads racing their sharded launches onto the same
    # devices interleave run ids and deadlock the whole mesh (observed
    # live on the campaign's 2-device CPU probe). Sharded groups
    # therefore always launch from the single serial flow; the pipeline's
    # pack/dispatch overlap is a single-device optimization.
    if _pipeline_on() and mesh is None:
        _solve_groups_pipelined(specs, solver, results, st, hypers_common,
                                mesh, ctx)
    else:
        if mesh is not None and _pipeline_on() and len(specs) > 1:
            st.add("mesh_serialized_groups", float(len(specs)))
        _solve_groups_serial(specs, solver, results, st, hypers_common,
                             mesh, ctx)
    for key, val in counters_delta(counters_before).items():
        if val:
            st.add(key, val)
    return results  # type: ignore[return-value]


class _GroupSpec:
    """One shape-class dispatch group plus its padded geometry and budget
    cost (live BYTES while its blocks are in flight, dtype-aware at the
    score precision — the unit the pipeline depth limit is denominated
    in)."""

    __slots__ = ("group", "W_pad", "M_pad", "E_pad", "bmax", "n_passes",
                 "cost")

    def __init__(self, group, W_pad, M_pad, E_pad, bmax, n_passes, cost):
        self.group = group
        self.W_pad = W_pad
        self.M_pad = M_pad
        self.E_pad = E_pad
        self.bmax = bmax
        self.n_passes = n_passes
        self.cost = cost


def _make_spec(group: List, itemsize: int) -> _GroupSpec:
    """Padded geometry + byte cost of one dispatch group. One definition
    shared by the initial shape-class grouping and the supervisor's
    bisection rung, so a bisected half is budgeted and padded by exactly
    the rules the full group was (per-plan W/M buckets are already
    powers of two, so halves cannot mint unbucketed shapes)."""
    W_pad = max(p[6] for p in group)
    M_pad = max(p[7] for p in group)
    E_pad = max(len(p[2]["out_eps"]) for p in group)
    n_passes = group[0][2]["n_passes"]  # uniform within a class
    n_windows_total = sum(len(p[3]) for p in group)
    bmax = max(len(p[3]) for p in group)
    P = len(group)
    # Ne family rows per service in the fused refit (in/edge/return)
    Ne = E_pad + E_pad * E_pad + E_pad
    score_elems = n_windows_total * E_pad * W_pad * M_pad
    # the fused refit gathers each service's window rows: [P*Ne, Bmax*W]
    # (single-pass dynamism groups never refit)
    refit_elems = P * Ne * bmax * W_pad if n_passes == 2 else 0
    # cost in BYTES, dtype-aware: score blocks at the configured
    # precision's itemsize (bf16 = half), the refit samples always
    # f32 (GMM EM stays full-precision)
    cost = score_elems * itemsize + refit_elems * 4
    return _GroupSpec(group, W_pad, M_pad, E_pad, bmax, n_passes, cost)


# ---------------------------------------------------------------------------
# Solve supervisor: retry -> bisect -> XLA -> host fallback -> quarantine
# ---------------------------------------------------------------------------

def _attempt_group(solver, pg, spec, results, st, hypers_common, mesh,
                   ctx=None):
    """One supervised dispatch+decode attempt of a packed group — the
    unit every ladder rung retries. ``pg`` stays host-side NumPy, so a
    failed attempt's donated device buffers never poison the retry:
    every attempt places fresh device copies."""
    _fault_check("dispatch", st)
    pend = _dispatch_packed(pg, spec, st, hypers_common, mesh)
    _decode_group(solver, pend, results, st, ctx=ctx)


def _enter_ladder(err, solver, pg, spec, results, st, hypers_common, mesh,
                  ctx):
    """Classify a group failure: transient faults walk the degradation
    ladder; anything else (a bug) propagates unchanged."""
    if not _faults.is_transient_fault(err):
        raise err
    st.add("fault_dispatch_errors")
    _degrade_group(err, solver, pg, spec, results, st, hypers_common, mesh,
                   ctx)


def _degrade_group(err, solver, pg, spec, results, st, hypers_common, mesh,
                   ctx):
    """Walk the explicit degradation ladder for one failed dispatch group.

    1. **retry** — up to ``TW_RETRY_MAX`` redispatches with exponential
       backoff (``TW_RETRY_BACKOFF_S``): transient faults (OOM under
       contention, relay flake, injected ``TW_FAULTS`` draws) usually
       clear here, at full fidelity.
    2. **bisect** — split the group in half and re-enter the ladder per
       half: a single poisoned service must not take its whole shape
       class down. Halves re-pack through :func:`_make_spec` /
       :func:`_pack_group`, so their shapes stay power-of-two bucketed.
    3. **xla** — a surviving singleton redispatches with the fused
       Pallas kernel pinned off (``pallas=False`` static arg): a
       Mosaic/kernel-specific failure gets the plain XLA program, which
       is algorithm-identical (tests/test_fused_kernel.py).
    4. **host** — the per-service host fallback (:func:`_run_fallback`,
       the reference's own per-service path).
    5. **quarantine** — the item's slot gets an all-NA result, its index
       lands in ``ctx["quarantined"]``, and the poison window is the
       CONSUMER's problem (the stream service dead-letters it; batch
       callers see the counted all-NA result) — never a silent drop.

    Every step is ledgered: counters per rung plus the ordered
    ``fault_ladder`` event list."""
    retry_max = _retry_max()
    backoff = _retry_backoff_s()
    # ladder rungs stamp the affected windows' self-traces too, so a
    # reconstructed pipeline trace shows WHERE a window's time went when
    # the supervisor engaged (tw-retry/tw-bisect/... stage services)
    rung_keys = sorted({p[1].trace_key for p in spec.group
                        if p[1].trace_key is not None})

    def _maybe_rebuild(e: BaseException) -> None:
        # ring-invalidate-and-rebuild rung: a devcols-site fault means
        # the resident arenas can no longer be trusted, and unlike the
        # transient faults the retry/bisect rungs were built for, a
        # poisoned ring would corrupt every later dispatch that gathers
        # from it — rebuild from the host mirrors BEFORE retrying
        dc = pg.get("devcols_items")
        if dc and _is_devcols_fault(e):
            _rebuild_rings([r for it in dc
                            for r in (it["ring_in"], it["ring_out"])], st)

    _maybe_rebuild(err)
    for attempt in range(retry_max):
        if backoff > 0:
            time.sleep(backoff * (2 ** attempt))
        st.add("fault_retries")
        st.note("fault_ladder", "retry")
        _trace_stage(rung_keys, "retry", _selftrace.now_us())
        try:
            _attempt_group(solver, pg, spec, results, st, hypers_common,
                           mesh, ctx)
            st.add("fault_recovered_retry")
            return
        except Exception as e:  # noqa: BLE001 — classified below
            if not _faults.is_transient_fault(e):
                raise
            err = e
            _maybe_rebuild(err)

    if len(spec.group) > 1:
        # bisect: isolate the offender instead of failing the class
        st.add("fault_bisections")
        st.note("fault_ladder", "bisect")
        _trace_stage(rung_keys, "bisect", _selftrace.now_us())
        mid = len(spec.group) // 2
        itemsize = score_itemsize(hypers_common.get("precision", "f32"))
        for half in (spec.group[:mid], spec.group[mid:]):
            half_spec = _make_spec(half, itemsize)
            half_pg = _pack_group(half_spec, hypers_common, st)
            try:
                _attempt_group(solver, half_pg, half_spec, results, st,
                               hypers_common, mesh, ctx)
            except Exception as e:  # noqa: BLE001
                _enter_ladder(e, solver, half_pg, half_spec, results, st,
                              hypers_common, mesh, ctx)
        return

    # --- singleton rungs -------------------------------------------------
    st.add("fault_xla_fallbacks")
    st.note("fault_ladder", "xla")
    _trace_stage(rung_keys, "xla-fallback", _selftrace.now_us())
    try:
        _attempt_group(solver, pg, spec, results, st,
                       {**hypers_common, "pallas": False}, mesh, ctx)
        return
    except Exception as e:  # noqa: BLE001
        if not _faults.is_transient_fault(e):
            raise
        err = e

    plan = spec.group[0]
    st.add("fault_host_fallbacks")
    st.note("fault_ladder", "host")
    _trace_stage(rung_keys, "host-fallback", _selftrace.now_us())
    try:
        _fault_check("host", st)
        _run_fallback([(plan[0], plan[1])], results, ctx["all_spans"],
                      ctx["all_processes"], ctx["solver_kwargs"], st,
                      confidences=ctx.get("confidences"))
        if results[plan[0]] is not None:
            return
    except Exception as e:  # noqa: BLE001
        if not _faults.is_transient_fault(e):
            raise
        err = e

    st.add("fault_quarantined")
    st.note("fault_ladder", "quarantine")
    _trace_stage(rung_keys, "quarantine", _selftrace.now_us())
    results[plan[0]] = _quarantine_result(plan)
    if ctx.get("confidences") is not None:
        # a quarantined window's reconstruction is all-NA: zero
        # confidence by definition, so culprit queries can exclude it
        ctx["confidences"][plan[0]] = {
            s.GetId(): _quality.zero_confidence()
            for s in plan[2]["in_spans"]}
    ctx["quarantined"].append(plan[0])


def _quarantine_result(plan) -> Tuple:
    """The poison-window result: a structurally valid FindAssignments
    6-tuple with every incoming span unassigned (NA at every endpoint),
    so batch consumers grade it as what it is — a fully failed window —
    instead of crashing on a missing slot. ``cnt_unassigned`` equals the
    span count, which is also the conservation quantity the stream's
    dead-letter accounting checks."""
    prep = plan[2]
    out_eps = prep["out_eps"]
    in_ids = [s.GetId() for s in prep["in_spans"]]
    all_assignments = {ep: {iid: NA for iid in in_ids} for ep in out_eps}
    all_topk = {ep: {iid: [] for iid in in_ids} for ep in out_eps}
    return (all_assignments, all_topk, 0, prep["n_in"],
            {iid: 0 for iid in in_ids}, len(in_ids))


def _solve_groups_serial(specs, solver, results, st, hypers_common, mesh,
                         ctx):
    """The ``TW_PIPELINE=0`` reference flow: pack -> dispatch strictly in
    order on the calling thread, decoding (and draining the live-element
    budget) exactly as the pre-pipeline dispatcher did. Failures enter
    the degradation ladder per group; the happy path is byte-identical
    to the unsupervised flow."""
    pending = []
    total_live = 0

    def finish(entry):
        spec, pg, pend = entry
        try:
            _decode_group(solver, pend, results, st, ctx=ctx)
        except Exception as e:  # noqa: BLE001
            _enter_ladder(e, solver, pg, spec, results, st, hypers_common,
                          mesh, ctx)

    for spec in specs:
        if total_live + spec.cost > _fleet_budget_bytes():
            # keep every live dispatch under one budget: drain first
            for entry in pending:
                finish(entry)
            pending = []
            total_live = 0
        total_live += spec.cost
        pg = _pack_group(spec, hypers_common, st)
        try:
            _fault_check("dispatch", st)
            pend = _dispatch_packed(pg, spec, st, hypers_common, mesh)
        except Exception as e:  # noqa: BLE001
            # a launch-time failure: this group degrades synchronously
            _enter_ladder(e, solver, pg, spec, results, st, hypers_common,
                          mesh, ctx)
            continue
        pending.append((spec, pg, pend))
    for entry in pending:
        finish(entry)


def _solve_groups_pipelined(specs, solver, results, st, hypers_common,
                            mesh, ctx):
    """Bounded multi-stage pipeline over the dispatch groups.

    - a single pack thread builds group N+1's host tensors while group N
      executes on the device (``pack_s`` no longer serializes against
      ``wait_s`` on the main thread);
    - each group's dispatch -> compaction round trips -> output fetch ->
      decode flow runs on a small worker pool (``TW_DECODE_WORKERS``),
      so one group's host-side flag gather or decode never idles the
      device: other flows' dispatches keep it fed (the event-driven
      warm->gather->redispatch requirement);
    - the live-dispatch bound (``TW_FLEET_BUDGET``, counted in BYTES at
      the score precision) is the pipeline depth limit: the gate blocks
      before admitting a group that would push the in-flight byte total
      past one budget — a bf16 solve's groups cost half, so the same
      budget admits ~2x the depth.

    Only WORK is reordered, never output: every flow writes its items'
    input-order ``results`` slots and runs byte-for-byte the serial
    path's math (tests/test_pipeline.py pins pipelined == TW_PIPELINE=0,
    compacted two-pass EM and budget-drain paths included).
    """
    gate = threading.Condition()
    live = {"elems": 0, "flows": 0}
    st.add("pipeline_groups", float(len(specs)))

    def flow(pg, spec):
        try:
            try:
                _attempt_group(solver, pg, spec, results, st, hypers_common,
                               mesh, ctx)
            except Exception as e:  # noqa: BLE001 — transient faults
                # degrade on THIS flow worker (the ladder's retries and
                # sub-dispatches keep riding the pool, so other flows'
                # device work still overlaps); non-transient errors
                # re-raise and propagate through fut.result() below
                _enter_ladder(e, solver, pg, spec, results, st,
                              hypers_common, mesh, ctx)
        finally:
            with gate:
                live["elems"] -= spec.cost
                live["flows"] -= 1
                gate.notify_all()

    pack_pool = ThreadPoolExecutor(max_workers=1,
                                   thread_name_prefix="tw-fleet-pack")
    flow_pool = ThreadPoolExecutor(max_workers=_decode_workers(),
                                   thread_name_prefix="tw-fleet-flow")
    try:
        pack_futs = [pack_pool.submit(_pack_group, spec, hypers_common, st)
                     for spec in specs]
        flow_futs = []
        for spec, fut in zip(specs, pack_futs):
            pg = fut.result()
            with gate:
                # depth limit: admit the group only when its blocks fit
                # the live-element budget (a lone over-budget group was
                # already routed to the per-service fallback upstream)
                while live["elems"] > 0 and \
                        live["elems"] + spec.cost > _fleet_budget_bytes():
                    gate.wait()
                # twlint: disable=TW007 — admission-gate state under the
                # gate condition lock, not telemetry; the observable copy
                # is the pipeline_depth record_max mirror below
                live["elems"] += spec.cost
                # twlint: disable=TW007 — same: gate state, mirrored below
                live["flows"] += 1
                st.record_max("pipeline_depth", float(live["flows"]))
            flow_futs.append(flow_pool.submit(flow, pg, spec))
        for fut in flow_futs:
            fut.result()  # propagate flow errors to the caller
    finally:
        pack_pool.shutdown(wait=True)
        flow_pool.shutdown(wait=True)


def _rebuild_rings(rings, st: _Stats) -> None:
    """The supervisor's ring-invalidate-and-rebuild rung: each faulted
    ring's device buffer is reconstructed from its host mirror (slot
    assignments preserved, so in-flight index arrays stay valid —
    :meth:`traceweaver_tpu.ops.devcols.ColumnRing.rebuild`), the
    re-shipped arena is billed to ``h2d_bytes_ring`` (a rebuild must
    never look free), and the rung lands in the ladder event list, the
    labelled ladder counter, and the ``TW_EVENTS`` sink like every
    other supervisor rung."""
    seen = {}
    for ring in rings:
        seen[id(ring)] = ring
    for ring in seen.values():
        st.add("h2d_bytes_ring", float(ring.rebuild()))
    if seen:
        st.add("devcols_ring_rebuilds", float(len(seen)))
        st.note("fault_ladder", "ring-rebuild")


def _is_devcols_fault(err: BaseException) -> bool:
    """Did this failure originate at the injector's ``devcols`` site?
    Only those faults implicate ring contents — a dispatch/fetch fault
    walks the plain ladder without re-shipping arenas (and without
    perturbing the pinned ladder ledgers of non-devcols chaos runs)."""
    return isinstance(err, _faults.FaultError) and "'devcols'" in str(err)


def _resolve_group_devcols(group, st: _Stats):
    """Resolve every item of a dispatch group onto its device-resident
    column rings (``TW_DEVCOLS``): per item, the in partition and each
    endpoint's out slice map to live ring slots, appending only spans
    not already resident (the H2D saving — ``h2d_bytes_ring`` counts
    what actually shipped). Returns one
    ``(in_slots, out_slots, ring_in, ring_out)`` tuple per item, or
    None when ANY partition cannot ride the resident path
    (non-integral timestamps, window origins outside the rings' int32
    epoch span, partitions larger than a ring) — the whole group then
    falls back to the host packer, counted in ``devcols_fallbacks``;
    mixed-path groups would make the parity contract unauditable."""
    store = _devcols.get_store()
    resolved = []
    for i, item, prep, windows, ranges, skip_caps, _, _ in group:
        in_cols = prep.get("in_cols")
        out_cols = prep.get("out_cols")
        if in_cols is None or out_cols is None or not windows:
            return None
        ring_in = store.ring(item.tenant, item.svc, "in")
        ring_out = store.ring(item.tenant, item.svc, "out")
        scope = (item.tenant, item.svc)
        try:
            # fault site "devcols", ring-append flavor: a failed append
            # leaves the donated device buffer in an unknown state, and
            # a poisoned ring would corrupt every LATER dispatch that
            # gathers from it — so the recovery is not a bare retry but
            # the ring-invalidate-and-rebuild rung (host mirror → fresh
            # device buffer, slots preserved), counted and evented,
            # before the resolve proceeds
            _fault_check("devcols", st)
        except _faults.FaultError:
            _rebuild_rings((ring_in, ring_out), st)
        in_slots = ring_in.resolve(in_cols, ledger=st.add, scope=scope)
        if in_slots is None:
            return None
        out_slots = {}
        for ep in prep["out_eps"]:
            slots = ring_out.resolve(out_cols[ep], endpoint=ep,
                                     ledger=st.add, scope=scope)
            if slots is None:
                return None
            out_slots[ep] = slots
        # window origins must be representable relative to BOTH rings'
        # epochs (the assembly subtracts them on device in int32)
        origins = in_cols.start[[lo for lo, _ in windows]]
        for ring in (ring_in, ring_out):
            if ring.epoch is None:
                return None
            rel = origins - ring.epoch
            if np.any(np.abs(rel) >= _devcols._INT32_SPAN):
                return None
        resolved.append((in_slots, out_slots, ring_in, ring_out))
    return resolved


def _pack_group(spec: _GroupSpec, hypers_common, st: _Stats):
    """Host packing of one shape-class group (pure NumPy — safe on the
    pipeline's pack thread): concatenated window tensors, stacked param
    tables, the refit row maps, and the analytic op accounting.

    Under ``TW_DEVCOLS`` the pack thread feeds INDEX ARRAYS, not
    tensors: each item packs through
    :func:`~traceweaver_tpu.algorithms.weaver_tpu._pack_problem_devcols`
    (ring-slot maps over the resident device columns) and only the
    skip/force tensors concatenate host-side; the dispatch assembles
    the window tensors on device. A group whose partitions cannot ride
    the resident path falls back to the host packer wholesale
    (``devcols_fallbacks``)."""
    group = spec.group
    W_pad, M_pad, E_pad, bmax = spec.W_pad, spec.M_pad, spec.E_pad, spec.bmax
    n_passes = spec.n_passes
    t0 = time.perf_counter()
    w0 = _selftrace.now_us()
    arrays_cat: Dict[str, List[np.ndarray]] = {}
    param_rows: Dict[str, List[np.ndarray]] = {k: [] for k in _TABLE_KEYS}
    per_item_pack = []
    param_idx = []
    # tenancy id column (serve layer): per-window tenant indices into a
    # group-local table, carried HOST-SIDE alongside the packed batch —
    # pack tags it, compaction attributes straggler redispatches with it,
    # decode attributes decoded windows with it. It never ships to the
    # device, so the dispatched programs (and the no-tenant ledger) stay
    # byte-identical to the pre-tenancy path.
    tenant_table = sorted({item.tenant for _, item, *_ in group
                           if item.tenant is not None})
    tenant_of = {t: ti for ti, t in enumerate(tenant_table)}
    tenant_idx: List[int] = []
    use_devcols = bool(hypers_common.get("devcols"))
    devcols_items: List[Dict] = []
    dc_resolved = None
    if use_devcols:
        dc_resolved = _resolve_group_devcols(group, st)
        if dc_resolved is None:
            st.add("devcols_fallbacks")
            use_devcols = False
    batch_keys = _DEVCOLS_BATCH_KEYS if use_devcols else _BATCH_KEYS
    for p, (i, item, prep, windows, ranges, skip_caps, _, _) in enumerate(group):
        if use_devcols:
            in_slots, out_slots, ring_in, ring_out = dc_resolved[p]
            packed = _pack_problem_devcols(
                prep["in_spans"], item.out_span_partitions, prep["out_eps"],
                prep["dists"], prep["in_ep"], item.dag,
                in_slots, out_slots, ring_in, ring_out,
                force_skip_ids=prep["force_skip_ids"],
                parallel=False, windows=windows,
                pad_w=W_pad, pad_m=M_pad, pad_e=E_pad,
                ranges=ranges, skip_caps=skip_caps,
                in_cols=prep.get("in_cols"), out_cols=prep.get("out_cols"),
            )
        else:
            packed = pack_problem(
                prep["in_spans"], item.out_span_partitions, prep["out_eps"],
                prep["dists"], prep["in_ep"], item.dag,
                force_skip_ids=prep["force_skip_ids"],
                parallel=False, windows=windows,
                pad_w=W_pad, pad_m=M_pad, pad_e=E_pad,
                ranges=ranges, skip_caps=skip_caps,
                in_cols=prep.get("in_cols"), out_cols=prep.get("out_cols"),
            )
        a = packed.arrays
        n_w = len(windows)
        for key in batch_keys:
            # drop pack_problem's power-of-two B padding: the fleet batch
            # is exact, and decode indexes out_ids by original row b which
            # is preserved under row slicing
            arrays_cat.setdefault(key, []).append(a[key][:n_w])
        if use_devcols:
            dc = packed.devcols
            devcols_items.append(dict(
                n_w=n_w, ring_in=dc["ring_in"], ring_out=dc["ring_out"],
                in_idx=dc["in_idx"][:n_w], out_idx=dc["out_idx"][:n_w],
                origin_in=dc["origin_in"][:n_w],
                origin_out=dc["origin_out"][:n_w]))
        # keep the id maps consistent with the sliced row count
        # (_decode sizes its gather table from the assign rows it is given)
        packed.truncate_rows(n_w)
        for key in param_rows:
            param_rows[key].append(a[key])
        param_idx.extend([p] * n_w)
        tenant_idx.extend([tenant_of.get(item.tenant, -1)] * n_w)
        if item.tenant is not None:
            st.bucket("tenant_windows_packed", item.tenant, float(n_w))
        per_item_pack.append((i, item, prep, packed, n_w))

    batch = {k: np.concatenate(v, axis=0) for k, v in arrays_cat.items()}
    params = {k: np.stack(v, axis=0) for k, v in param_rows.items()}
    pidx = np.asarray(param_idx, dtype=np.int32)
    # static neighbour bounds over the whole group (fleet max in/out
    # degree, power-of-two bucketed): the score build gathers only real
    # DAG edges instead of evaluating all E_pad per endpoint
    pm_all = params["pred_mask"]
    _mp = _bucket(max(1, int(pm_all.sum(axis=2).max(initial=0))), minimum=1)
    _ms = _bucket(max(1, int(pm_all.sum(axis=1).max(initial=0))), minimum=1)
    # each service's contiguous window-row block, for the gathered refit
    P = len(per_item_pack)
    n_windows_total = len(param_idx)
    window_rows = np.zeros((P, bmax), dtype=np.int32)
    window_valid = np.zeros((P, bmax), dtype=bool)
    row0 = 0
    for p, (_, _, _, _, n_w) in enumerate(per_item_pack):
        window_rows[p, :n_w] = np.arange(row0, row0 + n_w, dtype=np.int32)
        window_valid[p, :n_w] = True
        row0 += n_w
    # self-trace context for this group: every distinct window key whose
    # item rides this dispatch (carried on the ticket below — the decode
    # worker that finishes the flow stamps the same keys)
    trace_keys = sorted({item.trace_key for _, item, *_ in group
                         if item.trace_key is not None})
    _trace_stage(trace_keys, "pack", w0)
    st.add("pack_s", time.perf_counter() - t0)
    st.add("fleet_dispatches", 1.0)
    st.add("fleet_services", float(len(per_item_pack)))
    if st.d is not None:
        # analytic op accounting (UPPER BOUND — sweep and Sinkhorn loops
        # exit early on convergence), same model as WeaverTPU._solve_once
        n_sweeps = hypers_common["n_sweeps"]
        n_sinkhorn = hypers_common["n_sinkhorn"]
        itemsize = score_itemsize(hypers_common.get("precision", "f32"))
        K = params["in_wt"].shape[2]
        cells = (n_windows_total * E_pad * W_pad * M_pad
                 * n_sweeps * n_passes)
        st.add("flops_est", cells * (
            8.0 * K * (min(_mp, E_pad) + min(_ms, E_pad) + 2)
            + 6.0 * 2 * n_sinkhorn
            + 8.0 * max(1, W_pad.bit_length())
        ))
        # score-block HBM traffic at the configured precision's itemsize
        # (bf16 halves it); the Pallas term keeps the f32 plan write
        st.add("bytes_est_xla", cells * float(itemsize) * 2 * n_sinkhorn)
        st.add("bytes_est_pallas", cells * (float(itemsize) + 2 * 4.0))
        if n_passes == 2:
            # counts fused EM dispatches (the grouping may produce several)
            st.add("fused_em_applied", 1.0)
        else:
            st.add("fleet_dynamism_dispatches", 1.0)
    return dict(batch=batch, params=params, pidx=pidx,
                window_rows=window_rows, window_valid=window_valid,
                per_item_pack=per_item_pack, max_preds=_mp, max_succs=_ms,
                tenant_table=tenant_table,
                tenant_col=np.asarray(tenant_idx, dtype=np.int32),
                trace_keys=trace_keys, n_rows=n_windows_total,
                devcols_items=devcols_items if use_devcols else None)


def _dispatch_packed(pg, spec: _GroupSpec, st: _Stats, hypers_common,
                     mesh=None):
    """Launch one packed group's device program(s) and return its pending
    ``(per_item_pack, out)`` decode ticket.

    ``out`` is an async device handle for the single-dispatch flows and
    an already-merged host array for the compacted multi-dispatch flow.
    Convergence compaction (host in the loop): the vmapped sweep
    while_loop runs EVERY window until the slowest one's Gauss-Seidel
    assignments stabilize — converged windows' updates are select-masked
    into no-ops but still burn VPU cycles. So each solve pass runs as
    (1) a warm dispatch capped at TW_SWEEP_WARM sweeps, (2) a host-side
    gather of the windows whose convergence flag (its own [B] bool
    output, fetched ALONE — O(B) bytes) is still false, bucketed
    per shard to a power of two (the existing shape-class discipline, so
    redispatch batch sizes cannot multiply compiled variants), (3) a
    full-sweep redispatch of only those rows, scattered back over the
    warm output. Converged windows keep their warm output — the sweep
    loop's exactness argument (a reproducing sweep is a fixed point)
    makes that output bit-identical to what the full-budget run would
    have produced, and the redispatch reruns stragglers from sweep 0, so
    compaction is output-identical to the uncompacted dispatch by
    construction (tests/test_compaction.py pins this down). Two-pass
    (fused EM) groups split into warm/full pass 0 -> one refit dispatch
    (weaver_tpu.refit_fleet_params — the same refit solve_em_fleet runs
    in-graph) -> warm/full pass 1. With ``mesh``, the window-batch axis
    is padded to the mesh size and sharded (XLA SPMD); padded rows are
    invalid everywhere and decoded by nobody, and the compacted
    redispatch buckets its rows PER SHARD (mesh.bucket_rows_per_shard).
    """
    batch, params, pidx = pg["batch"], pg["params"], pg["pidx"]
    window_rows, window_valid = pg["window_rows"], pg["window_valid"]
    dc_items = pg.get("devcols_items")
    assemble = (_make_assembler(dc_items, batch, st)
                if dc_items is not None else None)
    if assemble is not None:
        # bounded compile lattice under continuous batching: the group's
        # service-count and refit-row-map axes pad to pow2 like every
        # other dispatch shape (all-invalid padding services)
        params, window_rows, window_valid = _pad_tables_pow2(
            params, window_rows, window_valid)
    # the host-side tenancy column rides the dispatch ticket so the
    # compacted flow can attribute straggler redispatches per tenant;
    # None whenever no item in the group is tenant-tagged (every
    # pre-serve caller), keeping this flow untouched
    tenant_table = pg.get("tenant_table") or None
    tenant_col = pg.get("tenant_col") if tenant_table else None
    n_passes = spec.n_passes
    n_sweeps = hypers_common["n_sweeps"]
    hypers = dict(epsilon=hypers_common["epsilon"],
                  n_sinkhorn=hypers_common["n_sinkhorn"],
                  sinkhorn_tol=hypers_common["sinkhorn_tol"],
                  precision=hypers_common.get("precision", "f32"),
                  # the supervisor's XLA rung pins the fused Pallas
                  # kernel off for a redispatch (a distinct static-arg
                  # program variant); the default True is the historical
                  # program and cache key
                  pallas=hypers_common.get("pallas", True),
                  # the quality-telemetry program variant (TW_CONF_DEVICE;
                  # packed_layout.py): default False = the historical
                  # packed block, byte-identical programs
                  confidence=hypers_common.get("confidence", False),
                  max_preds=pg["max_preds"], max_succs=pg["max_succs"])
    warm = _compaction_warm()
    use_compact = (_compaction_on() and warm < n_sweeps
                   and pg["n_rows"] > 1)
    if mesh is not None:
        # batch rows pad to the mesh size ON THE HOST and stay numpy here:
        # the compacted flow gathers redispatch rows from these host
        # tensors and places fresh sharded copies per dispatch (the
        # donated device buffers of an earlier dispatch cannot be reused).
        # The padded size is bucket_rows_per_shard — pow2 rows per shard
        # — not just a multiple of the mesh: raw row counts vary per
        # group, and an unbucketed mesh batch axis would mint one
        # compiled sharded program per count, putting the whole mesh
        # family outside any finite AOT lattice (runtime/aot.py
        # enumerates exactly these pow2-per-shard sizes)
        from traceweaver_tpu.parallel.mesh import (
            _pad_batch,
            bucket_rows_per_shard,
        )

        n_dev = int(mesh.devices.size)
        batch, true_b = _pad_batch(
            batch, bucket_rows_per_shard(pg["n_rows"], n_dev))
        pidx = np.concatenate(
            [pidx, np.zeros(batch["in_start"].shape[0] - true_b,
                            dtype=pidx.dtype)])
        if tenant_col is not None:
            # mesh padding rows belong to no tenant (-1): they are
            # all-invalid windows decoded by nobody, so they must not
            # surface in anyone's redispatch attribution either
            tenant_col = np.concatenate(
                [tenant_col,
                 np.full(batch["in_start"].shape[0] - true_b, -1,
                         dtype=tenant_col.dtype)])
    t0 = time.perf_counter()
    w0 = _selftrace.now_us()
    trace_keys = pg.get("trace_keys") or ()
    # this flow's blocking time (compacted intermediate fetches), so
    # dispatch_s below stays pure launch/host time even when several
    # flows bill wait_s to the shared dict concurrently
    flow_wait = [0.0]
    # plan-cache admission sink: the compacted two-pass flow surfaces its
    # between-pass refit tables here so the fitted plan the device just
    # computed is kept for the next solve (admitted below, after the
    # dispatch accounting closes — decode work, not launch time)
    plan_cache = hypers_common.get("plan_cache")
    refit_sink = [] if (plan_cache is not None and n_passes == 2) else None
    if use_compact:
        out = _solve_group_compacted(
            batch, pidx, params, _tables_of(params), window_rows,
            window_valid, n_passes, n_sweeps, warm, hypers, st,
            mesh=mesh, flow_wait=flow_wait,
            tenant_col=tenant_col, tenant_table=tenant_table,
            trace_keys=trace_keys, assemble=assemble,
            refit_sink=refit_sink)
    elif assemble is not None:
        # device-resident path: window tensors are assembled on device
        # from the rings; only index arrays + skip/force shipped. The
        # batch-row axis pads to a power of two with all-invalid rows
        # (decoded by nobody, converge instantly) so the continuous-
        # batching scheduler's varying admission counts dispatch against
        # a BOUNDED shape lattice — steady state mints zero compiles
        # (tests/test_continuous.py pins it)
        with _profile.annotate("tw:fleet:dispatch"):
            pad_b = _bucket(pg["n_rows"], minimum=1) - pg["n_rows"]
            common = assemble(None, pad_b) + (_pad_pidx(pidx, pad_b),)
            if n_passes == 2:
                _note_aot(st, _aot.note_fleet(
                    "solve_em_fleet", common, _tables_of(params), n_sweeps,
                    hypers, window_rows=window_rows))
                out, _ = solve_em_fleet(
                    *common, window_rows, window_valid, *_tables_of(params),
                    n_sweeps=n_sweeps, **hypers,
                )
            else:
                _note_aot(st, _aot.note_fleet(
                    "solve_windows_fleet", common, _tables_of(params),
                    n_sweeps, hypers))
                out, _ = solve_windows_fleet(
                    *common, *_tables_of(params), n_sweeps=n_sweeps,
                    **hypers,
                )
    else:
        with _profile.annotate("tw:fleet:dispatch"):
            if mesh is not None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec

                from traceweaver_tpu.parallel.mesh import put_sharded

                # put_sharded: window-axis keys sharded, everything else
                # (param tables, window_rows/valid) replicated
                placed = put_sharded(
                    {**batch, **params,
                     "window_rows": window_rows,
                     "window_valid": window_valid},
                    mesh)
                batch = {k: placed[k] for k in batch}
                params = {k: placed[k] for k in params}
                window_rows = placed["window_rows"]
                window_valid = placed["window_valid"]
                pidx = jax.device_put(
                    pidx,
                    NamedSharding(mesh, PartitionSpec(mesh.axis_names[0])))
            _bill_shipped(st, batch)
            common = tuple(batch[k] for k in _BATCH_KEYS) + (pidx,)
            if n_passes == 2:
                _note_aot(st, _aot.note_fleet(
                    "solve_em_fleet", common, _tables_of(params), n_sweeps,
                    hypers, window_rows=window_rows, mesh=mesh))
                out, _ = solve_em_fleet(
                    *common, window_rows, window_valid, *_tables_of(params),
                    n_sweeps=n_sweeps, **hypers,
                )
            else:
                _note_aot(st, _aot.note_fleet(
                    "solve_windows_fleet", common, _tables_of(params),
                    n_sweeps, hypers, mesh=mesh))
                out, _ = solve_windows_fleet(
                    *common, *_tables_of(params), n_sweeps=n_sweeps,
                    **hypers,
                )
    dispatch_s = time.perf_counter() - t0 - flow_wait[0]
    st.add("dispatch_s", dispatch_s)
    _OBS_DISPATCH_S.observe(dispatch_s)
    _trace_stage(trace_keys, "dispatch", w0)
    _copy_async(out)
    if refit_sink:
        # two-pass admission: decode the refit tables the device already
        # computed back into per-service dists and keep them — the next
        # solve's cache hit repacks them bit-exactly and runs single-pass
        # (= this solve's pass 1). Billed to plan_fit_s: this is residual
        # host planning riding the flow worker, not launch time. Under
        # the pipeline it overlaps the next group's pack/dispatch — the
        # overlapped-residual-planning contract.
        t_admit = time.perf_counter()
        tables9 = tuple(np.asarray(t) for t in refit_sink[0])
        from traceweaver_tpu.algorithms.weaver_tpu import dists_from_tables
        for p, (_, item, prep, _, _) in enumerate(pg["per_item_pack"]):
            if item.warm_dists is not None:
                continue
            plan_cache.admit(
                _plan_key(item),
                dists_from_tables(prep["out_eps"], prep["in_ep"],
                                  *(t[p] for t in tables9)))
        st.add("plan_fit_s", time.perf_counter() - t_admit)
    # the decode ticket carries the program-variant flag so the decode
    # worker splits the packed channels by the layout the dispatch used
    return pg["per_item_pack"], out, hypers.get("confidence", False)


def _tables_of(params: Dict) -> Tuple:
    return tuple(params[k] for k in _TABLE_KEYS)


def _pad_tables_pow2(params: Dict, window_rows: np.ndarray,
                     window_valid: np.ndarray):
    """Pow2-pad the per-service table axes of one packed group: the
    stacked param tables' ``P`` axis (services in the group) and the
    refit row map's ``Bmax`` axis (max windows per service). Under
    continuous batching the admission scheduler hands the fleet
    arbitrary tenant subsets, so P and Bmax vary per dispatch — without
    padding, every distinct count is a fresh compiled program and the
    steady state never stops compiling. Padding services follow the
    all-invalid convention of ``pack_problem``'s ``pad_e`` endpoints:
    false masks, zero weights, unit σ; no window row ever points at
    them (only pow2 batch padding rows do, and those are all-invalid)."""
    P, bmax = window_rows.shape
    P_pad = _bucket(P, minimum=1)
    bmax_pad = _bucket(bmax, minimum=1)
    if P_pad == P and bmax_pad == bmax:
        return params, window_rows, window_valid
    out = {}
    for k, a in params.items():
        pad = np.zeros((P_pad - P,) + a.shape[1:], dtype=a.dtype)
        if k.endswith("_sd"):
            pad = np.ones_like(pad)
        out[k] = np.concatenate([a, pad]) if P_pad > P else a
    wr = np.zeros((P_pad, bmax_pad), dtype=window_rows.dtype)
    wv = np.zeros((P_pad, bmax_pad), dtype=bool)
    wr[:P, :bmax] = window_rows
    wv[:P, :bmax] = window_valid
    return out, wr, wv


def _pad_pidx(pidx: np.ndarray, pad: int) -> np.ndarray:
    """Zero-extend the param-index column for pow2 batch-row padding
    (padding rows are all-invalid windows; the row-0 tables they point
    at never see a valid span)."""
    if not pad:
        return pidx
    return np.concatenate([pidx, np.zeros(pad, dtype=pidx.dtype)])


def _bill_shipped(st: _Stats, arrs: Dict) -> None:
    """H2D byte ledger, shipped side: every host window tensor placed on
    device for a dispatch (fresh copies per attempt/pass — each
    placement is real tunnel traffic and bills again). The resident
    path's counterpart ledgers are ``h2d_bytes_ring`` (column appends)
    and ``h2d_bytes_index`` (gather index arrays), so a ``TW_DEVCOLS``
    solve can never silently claim zero traffic while still shipping."""
    st.add("h2d_bytes_shipped",
           float(sum(np.asarray(arrs[k]).nbytes
                     for k in _BATCH_KEYS if k in arrs)))


def _make_assembler(dc_items: List[Dict], batch: Dict, st: _Stats):
    """Build the device-assembly closure for one packed group
    (``TW_DEVCOLS``): ``assemble(active, pad)`` returns the eight
    window tensors of ``_BATCH_KEYS`` order for the given row subset
    (``active=None`` = all rows, ascending indices otherwise) plus
    ``pad`` trailing all-invalid rows — the drop-in replacement for
    host-tensor placement at every dispatch site (warm, compacted
    redispatch, retry). The rings are global per-partition arenas, so
    the WHOLE group assembles in ONE jitted gather over host-built
    index arrays (row selection/padding is NumPy — no eager device op
    ever sees a data-dependent shape, which is what keeps the steady
    state at zero compiles). Each call gathers FRESH device tensors, so
    donated buffers of a failed attempt can never poison a retry, and
    ships only int32 index arrays (``h2d_bytes_index``) plus the small
    skip/force tensors (``h2d_bytes_shipped``)."""
    ring_in = dc_items[0]["ring_in"]
    ring_out = dc_items[0]["ring_out"]
    cat = (lambda key: dc_items[0][key] if len(dc_items) == 1
           else np.concatenate([it[key] for it in dc_items]))
    in_idx, out_idx = cat("in_idx"), cat("out_idx")
    origin_in, origin_out = cat("origin_in"), cat("origin_out")

    def assemble(active: Optional[np.ndarray], pad: int) -> Tuple:
        # fault site "devcols", resident-assembly flavor: raised here it
        # surfaces from the dispatch attempt and enters the supervisor
        # ladder, whose first move for a devcols fault is the
        # ring-invalidate-and-rebuild rung (_degrade_group) — every
        # retry then re-gathers from a rebuilt, trusted arena
        _fault_check("devcols", st)

        def rows(arr, fill):
            a = arr if active is None else arr[active]
            if pad:
                a = np.concatenate(
                    [a, np.full((pad,) + a.shape[1:], fill,
                                dtype=a.dtype)])
            return a

        si, so = rows(in_idx, -1), rows(out_idx, -1)
        oi, oo = rows(origin_in, 0), rows(origin_out, 0)
        st.add("h2d_bytes_index",
               float(si.nbytes + so.nbytes + oi.nbytes + oo.nbytes))
        _note_aot(st, _aot.note_assemble(int(ring_in.cap), si, so))
        outs = _devcols.assemble_resident(ring_in, ring_out,
                                          si, so, oi, oo)
        skip_cap = rows(batch["skip_cap"], 0)
        force_skip = rows(batch["force_skip"], False)
        st.add("h2d_bytes_shipped",
               float(skip_cap.nbytes + force_skip.nbytes))
        return tuple(outs) + (skip_cap, force_skip)

    # true (un-padded) row count, for callers that pad the batch-row
    # axis to pow2 and must slice per-row outputs (convergence flags)
    # back to the real windows
    assemble.n_rows = int(in_idx.shape[0])
    return assemble


def _compacted_pass(batch, pidx, tables, n_sweeps, warm, hypers, stats,
                    mesh=None, flow_wait=None, tenant_col=None,
                    tenant_table=None, trace_keys=(), assemble=None):
    """One solve pass as warm dispatch + compacted full redispatch.

    Returns the packed [B, E, W, 3+topk] output as a host array,
    bit-identical to a single ``n_sweeps`` dispatch of the same batch
    (see the compaction comment on :func:`_dispatch_packed`).

    The host never blocks on the packed warm block just to LEARN the
    convergence set: the flags ride their own ``[B]`` bool device array
    (the packed-output split, ``weaver_tpu._pack_solver_outputs``) and
    are fetched alone — B bytes instead of the whole
    ``[B, E, W, 3+topk]`` block — while the warm block streams D2H
    asynchronously, overlapping the gather and the redispatch compute
    (the ``copy-start`` D2H cost the r05 profile billed at parity with
    the sweep loops themselves).

    With ``mesh``, inputs stay host-side NumPy and every dispatch places
    fresh sharded copies; the redispatch batch is bucketed PER SHARD
    (:func:`traceweaver_tpu.parallel.mesh.bucket_rows_per_shard`) so
    multi-chip runs compact too — each device receives a power-of-two
    row count and the total divides evenly across the mesh. Per-window
    outputs are sharding-independent (the solve is a vmap over windows),
    so 1- and N-device compacted runs stay identical."""
    st = _as_stats(stats)
    n_shards = int(mesh.devices.size) if mesh is not None else 1

    def place(arrs, pidx_np):
        _bill_shipped(st, arrs)
        if mesh is None:
            return tuple(arrs[k] for k in _BATCH_KEYS) + (pidx_np,)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from traceweaver_tpu.parallel.mesh import put_sharded

        placed = put_sharded({k: arrs[k] for k in _BATCH_KEYS}, mesh)
        pj = jax.device_put(
            pidx_np, NamedSharding(mesh, PartitionSpec(mesh.axis_names[0])))
        return tuple(placed[k] for k in _BATCH_KEYS) + (pj,)

    tables_dev = tables
    if mesh is not None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        tables_dev = tuple(jax.device_put(np.asarray(t), rep)
                           for t in tables)

    with _profile.annotate("tw:fleet:warm-dispatch"):
        if assemble is not None:
            # pow2 batch-row padding (all-invalid rows): bounded shape
            # lattice under continuous batching, see _dispatch_packed
            pad0 = _bucket(assemble.n_rows, minimum=1) - assemble.n_rows
            warm_common = assemble(None, pad0) + (_pad_pidx(pidx, pad0),)
        else:
            warm_common = place(batch, pidx)
        _note_aot(st, _aot.note_fleet(
            "solve_windows_fleet", warm_common, tables_dev, warm,
            hypers, mesh=mesh))
        out_warm, flags = solve_windows_fleet(
            *warm_common, *tables_dev, n_sweeps=warm, **hypers)
    # the big warm block starts its D2H NOW — it overlaps the flag fetch,
    # the host gather, and the redispatch's device execution below
    _copy_async(out_warm)
    w0 = _selftrace.now_us()
    with _profile.annotate("tw:fleet:flag-fetch"):
        converged = _fetch_flags(flags, st, flow_wait,
                                 mesh=mesh).astype(bool)
    if assemble is not None:
        # drop the pow2 padding rows: all-invalid windows converge by
        # construction and must not inflate the compaction ledger (or
        # reach the redispatch row gather)
        converged = converged[:assemble.n_rows]
    _trace_stage(trace_keys, "compact-fetch", w0)
    active = np.flatnonzero(~converged)
    st.add("compact_windows_total", float(converged.shape[0]))
    st.add("compact_windows_redispatched", float(active.size))
    if tenant_col is not None and active.size:
        # tenancy attribution of the straggler set: which tenant's
        # windows are still burning redispatch cycles (the serve layer's
        # per-tenant cost ledger; -1 rows are untagged/mesh padding)
        ids, counts = np.unique(np.asarray(tenant_col)[active],
                                return_counts=True)
        for t_i, c in zip(ids, counts):
            if t_i >= 0:
                st.bucket("tenant_windows_redispatched",
                          tenant_table[int(t_i)], float(c))
    if active.size == 0:
        return _fetch(out_warm, st, flow_wait)

    from traceweaver_tpu.parallel.mesh import bucket_rows_per_shard

    b_pad = bucket_rows_per_shard(int(active.size), n_shards)
    pad = b_pad - int(active.size)
    pidx_active = np.asarray(pidx)[active]
    if pad:
        pidx_active = np.concatenate(
            [pidx_active, np.zeros(pad, dtype=pidx_active.dtype)])
    if assemble is not None:
        # resident path: re-gather the straggler rows from the rings on
        # device (the warm dispatch donated its assembled tensors; a
        # fresh assembly is index-array traffic only, never a re-ship
        # of the column data)
        redispatch_common = assemble(active, pad) + (pidx_active,)
    else:
        gathered = {}
        for k in _BATCH_KEYS:
            a = np.asarray(batch[k])[active]
            if pad:
                # padding rows are all-invalid windows: no valid spans or
                # columns, so they assign nothing and are decoded by nobody
                # (same convention as pack_problem's pad_b rows)
                a = np.concatenate(
                    [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)])
            gathered[k] = a
        redispatch_common = place(gathered, pidx_active)
    w0 = _selftrace.now_us()
    with _profile.annotate("tw:fleet:redispatch"):
        _note_aot(st, _aot.note_fleet(
            "solve_windows_fleet", redispatch_common, tables_dev,
            n_sweeps, hypers, mesh=mesh))
        out_full, _ = solve_windows_fleet(
            *redispatch_common, *tables_dev,
            n_sweeps=n_sweeps, **hypers)
    _trace_stage(trace_keys, "redispatch", w0)
    _copy_async(out_full)
    out = _fetch(out_warm, st, flow_wait).copy()
    out[active] = _fetch(out_full, st, flow_wait)[:active.size]
    return out


def _solve_group_compacted(batch, pidx, params, tables, window_rows,
                           window_valid, n_passes, n_sweeps, warm, hypers,
                           stats, mesh=None, flow_wait=None,
                           tenant_col=None, tenant_table=None,
                           trace_keys=(), assemble=None, refit_sink=None):
    """Compacted replacement for one fused group dispatch: per-pass
    warm/redispatch compaction, with the two-pass EM's on-device refit as
    its own dispatch between the passes (same refit program
    ``solve_em_fleet`` runs in-graph, so the flows cannot drift).
    ``batch`` stays host-side NumPy throughout — each dispatch places
    (and, mesh-less, uploads) fresh device copies, which is what makes
    the donated window tensors safe to regather for the redispatch.
    Under ``assemble`` (TW_DEVCOLS) there ARE no host window tensors:
    every dispatch, and the refit's sample extraction, re-gathers fresh
    device tensors from the resident rings instead."""
    st = _as_stats(stats)
    out0 = _compacted_pass(batch, pidx, tables, n_sweeps, warm, hypers, st,
                           mesh=mesh, flow_wait=flow_wait,
                           tenant_col=tenant_col, tenant_table=tenant_table,
                           trace_keys=trace_keys, assemble=assemble)
    if n_passes == 1:
        return out0
    if assemble is not None:
        # refit inputs straight off the rings (device tensors; the refit
        # program does not donate, so they survive the call), padded to
        # the same pow2 row count as out0 so the refit program's shapes
        # ride the bounded lattice too
        pad0 = _bucket(assemble.n_rows, minimum=1) - assemble.n_rows
        bi = dict(zip(_BATCH_KEYS, assemble(None, pad0)))
        pidx_refit = _pad_pidx(np.asarray(pidx), pad0)
    else:
        bi = batch
        pidx_refit = pidx
    assign_refit = out0[..., _layout.CH_ASSIGN].astype(np.int32)
    # the refit's inputs stay host NumPy on BOTH paths (the mesh flow
    # hands it the pre-placement tensors), so its compiled program is
    # the single-device one regardless of mesh — note with shards=1
    _note_aot(st, _aot.note_refit(assign_refit, window_rows,
                                  bi["out_start"]))
    new_tables = refit_fleet_params(
        assign_refit,
        bi["in_start"], bi["in_end"], bi["in_valid"],
        bi["out_start"], bi["out_end"], pidx_refit,
        window_rows, window_valid,
        params["pred_mask"], params["root_mask"],
        params["edge_wt"], params["edge_mu"], params["edge_sd"],
        params["in_wt"], params["in_mu"], params["in_sd"],
        params["ret_wt"], params["ret_mu"], params["ret_sd"])
    if mesh is not None:
        # pass 1 re-places everything itself; hand it host tables so the
        # replicated device_put starts from committed-free arrays — a
        # LEDGERED fetch (the refit tables are small, but the block on
        # the refit program's execution is real device wait)
        new_tables = tuple(_fetch(t, st, flow_wait) for t in new_tables)
    if refit_sink is not None:
        # plan-cache admission material: the dispatcher decodes these
        # AFTER its dispatch accounting closes (device handles are fine —
        # by then pass 1 has long since forced the refit's execution)
        refit_sink.append(new_tables)
    return _compacted_pass(batch, pidx, tables[:3] + tuple(new_tables),
                           n_sweeps, warm, hypers, st, mesh=mesh,
                           flow_wait=flow_wait,
                           tenant_col=tenant_col, tenant_table=tenant_table,
                           trace_keys=trace_keys, assemble=assemble)


def _decode_group(solver, pend, results, stats, ctx=None):
    """Fetch one group's packed output and decode it per service.

    Safe on a pipeline decode worker: every write lands in that group's
    own input-order ``results`` slots (and its own ``confidences``
    slots) and all counter updates go through the lock-guarded
    accumulator."""
    st = _as_stats(stats)
    per_item_pack, out, conf_device = pend
    confidences = (ctx or {}).get("confidences")
    conf_on = confidences is not None and _quality.conf_enabled()
    # the compacted flow already fetched + merged on the host; the
    # single-dispatch flows hand over an async device handle
    o = out if isinstance(out, np.ndarray) else _fetch(out, st)

    t0 = time.perf_counter()
    w0 = _selftrace.now_us()
    row = 0
    for i, item, prep, packed, n_w in per_item_pack:
        rows = o[row:row + n_w]
        row += n_w
        if item.tenant is not None:
            # tenancy column, decode end: packed == decoded per tenant is
            # the conservation check the serve tests assert from stats
            st.bucket("tenant_windows_decoded", item.tenant, float(n_w))
        ch = _layout.split_packed(rows, confidence=conf_device)
        assign = ch["assign"]
        not_best = ch["not_best"]
        feas = ch["feas"]
        topk_cols = ch["topk_cols"]
        out_eps = prep["out_eps"]
        in_ids = (prep["in_cols"].ids.tolist()
                  if prep.get("in_cols") is not None
                  else [s.GetId() for s in prep["in_spans"]])
        n_in = prep["n_in"]

        all_assignments = {ep: {} for ep in out_eps}
        all_topk = {ep: {} for ep in out_eps}
        solver._decode(packed, assign, topk_cols, all_assignments, all_topk)
        span_not_best = np.zeros(n_in, dtype=bool)
        span_cands = np.ones(n_in, dtype=np.int64)
        scatter_window_span_stats(packed.windows, not_best, feas,
                                  span_not_best, span_cands)
        if conf_on:
            # per-span quality reductions from the SAME fetched block —
            # no extra transfer, no device change (obs/quality.py); the
            # slot write is race-free like the results slot (input-order,
            # one writer per item)
            arrs = _quality.span_confidence_arrays(
                packed.windows, rows, n_in, device=conf_device)
            confidences[i] = _quality.confidence_records(in_ids, arrs)
        solver._resolve_cross_window_duplicates(
            all_assignments, all_topk, in_ids, prep["skip_budget"])
        cnt_unassigned = sum(
            1 for in_id in in_ids
            if any(all_assignments[ep][in_id] == NA for ep in out_eps)
        )
        results[i] = (
            all_assignments, all_topk, int(span_not_best.sum()), n_in,
            {in_ids[j]: int(span_cands[j]) for j in range(n_in)},
            cnt_unassigned,
        )
    _trace_stage(sorted({item.trace_key for _, item, *_ in per_item_pack
                         if item.trace_key is not None}), "decode", w0)
    st.add("decode_s", time.perf_counter() - t0)
