"""Fleet solve: every service's windows in one device dispatch.

The reference exploits multi-service workloads only through a host thread
pool — one ``FindAssignments`` call per service, concurrency from Python
threads (reference executor.py:1015-1026). On TPU that model leaves the
chip idle: each per-service solve is its own device program, and through
the sandbox's remote-device tunnel every dispatch costs ~100 ms of round
trip, so an 8-service workload pays ~8 round trips of pure latency.

This module is the TPU-native alternative (SURVEY.md §2.8 "services
become a batch dimension"): window batches of services are padded to
shared ``[B, E, W, M]`` shape classes, each window tagged with
``param_idx`` — the row of its service's DAG-structure/distribution
tables — and each class rides ONE jitted program
(:func:`traceweaver_tpu.algorithms.weaver_tpu.solve_em_fleet`), including
both EM passes and the batched BIC-GMM refit between them. Services with
similar window geometry share a class; geometry outliers get their own
dispatch rather than inflate everyone's padding (the merge budget is
backend-aware — padding is nearly-free VPU headroom on TPU, real
core-seconds on the CPU stand-in). Dispatch count drops from O(services)
to O(shape classes), typically 1-2.

Dynamism (cache-hit services with skip budget > 0, reference
exp2/run_experiment.sh:128-158) rides the fleet too: those services form
single-pass dispatch groups with bootstrap distributions and water-filled
per-window skip-cap tensors, exactly the per-service dynamism
configuration fused. The true-skips oracle ships its forced rows as
per-window force-skip tensors. Only methods that need the host in the
loop (KDE score mode, single-iteration parallel mode, the true-dist
oracle, missing DAGs) fall back to the per-service :class:`WeaverTPU`
path.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from traceweaver_tpu.algorithms.skips import water_fill_skip_caps
from traceweaver_tpu.algorithms.weaver_tpu import (
    DEFAULT_MAX_WINDOW,
    WeaverTPU,
    _bucket,
    candidate_ranges,
    pack_problem,
    perfect_cut_windows,
    plan_find_assignments,
    refit_fleet_params,
    solve_em_fleet,
    solve_windows_fleet,
)
from traceweaver_tpu.spans import NA

# fleet single-dispatch budget: live f32 elements of the [B, E, W, M]
# score block (the dominant allocation). Past this the padded single
# program would stress HBM; fall back to per-service dispatches instead.
FLEET_BUDGET_ELEMS = int(os.environ.get("TW_FLEET_BUDGET", 1 << 28))

# window-axis keys of a packed fleet batch, dispatch argument order
_BATCH_KEYS = ("in_start", "in_end", "in_valid", "out_start", "out_end",
               "out_valid", "skip_cap", "force_skip")


def _compaction_warm() -> int:
    """Warm sweep count before convergence compaction redispatches
    (``TW_SWEEP_WARM``, default 2 — sweep 0 plus one verification sweep,
    which certifies the large fraction of windows whose Gauss-Seidel
    assignments are already a fixed point after the forward pass)."""
    try:
        return max(1, int(os.environ.get("TW_SWEEP_WARM", "2")))
    except ValueError:
        return 2


def _compaction_on() -> bool:
    """``TW_COMPACT=0`` kills convergence compaction (single fused
    dispatch per group, the pre-compaction shape)."""
    return os.environ.get("TW_COMPACT", "1") not in ("0", "false", "")


class FleetItem:
    """One service's solve request (the FindAssignments argument set)."""

    def __init__(self, svc, in_span_partitions, out_span_partitions,
                 true_assignments, dag=None,
                 method="MaxScoreBatchSubsetWithSkips", store=None,
                 warm_dists=None):
        self.svc = svc
        self.in_span_partitions = in_span_partitions
        self.out_span_partitions = out_span_partitions
        self.true_assignments = true_assignments
        self.dag = dag
        self.method = method
        # optional TraceStore for the per-service fallback path (its host
        # EM refit reads the global span table); unused by the fused path
        self.store = store
        # optional carried {edge key -> EdgeDist} (streaming warm start):
        # replaces the plan's cold fit and collapses the solve to a single
        # pass — the on-device EM refit is what the carried statistics
        # already are (stream/state.py CarriedState)
        self.warm_dists = warm_dists


def _prepare(item: FleetItem, solver: WeaverTPU):
    """Host preamble of FindAssignments for one item (sort, topo order,
    skip budget, distributions). Returns None when the item needs a code
    path the fleet does not cover (no DAG, KDE scoring, true-dist oracle).

    Dynamism (skip budget > 0 — the cache-hit workloads, reference
    exp2/run_experiment.sh:128-158) stays IN the fleet: those services get
    the per-service path's bootstrap distributions and a single-pass plan
    (``n_passes=1``, no EM refit — identical to ``iterations = 1`` in
    :meth:`WeaverTPU.FindAssignments`), with their water-filled skip caps
    carried as per-window tensors in the fused dispatch."""
    if item.dag is None or solver.score_mode != "mixture":
        return None
    if item.method not in ("MaxScoreBatchSubsetWithSkips",
                           "MaxScoreBatchSubsetWithTrueSkips"):
        return None
    in_ep, in_spans = next(iter(item.in_span_partitions.items()))
    in_spans = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
    out_eps = solver._topo_out_eps(item.out_span_partitions, item.dag)
    # the SAME plan the per-service entry point computes (one definition,
    # weaver_tpu.plan_find_assignments — the paths cannot drift); the
    # true-skips oracle's forced rows ride the dispatch as per-window
    # force-skip tensors (the device solver input, weaver_tpu.py:94)
    plan = plan_find_assignments(
        item.in_span_partitions, item.out_span_partitions, out_eps,
        item.dag, item.true_assignments, score_mode=solver.score_mode,
        true_skips=(item.method == "MaxScoreBatchSubsetWithTrueSkips"),
    )
    dists, n_passes = plan["dists"], plan["iterations"]
    if item.warm_dists is not None:
        # streaming warm start: carried per-edge statistics from earlier
        # windows replace both the cold fit and the refit pass; the item
        # joins the single-pass dispatch groups (unseen edges fall back
        # to pack_problem's near-flat wide Gaussian)
        dists, n_passes = item.warm_dists, 1
    return dict(in_ep=in_ep, in_spans=in_spans, out_eps=out_eps,
                skip_budget=plan["skip_budget"], dists=dists,
                n_in=plan["n_in"], n_passes=n_passes,
                force_skip_ids=plan["force_skip_ids"])


def _raw_cells(item: FleetItem, max_window: int) -> float:
    """Padded-compute-cell count for an item solved OUTSIDE a fused
    dispatch (host-in-the-loop fallbacks), from its raw partitions — the
    same ``n_windows * W * M * E * n_passes`` model the fused plan
    records, so mixed fused/fallback workloads attribute wall-clock on
    one scale. The pass count mirrors ``WeaverTPU.FindAssignments``:
    one pass under dynamism or the true-dist oracle, two otherwise."""
    in_spans = sorted(next(iter(item.in_span_partitions.values())),
                      key=lambda s: (s.start_mus, s.end_mus))
    out_eps = list(item.out_span_partitions)
    windows = perfect_cut_windows(in_spans, max_window)
    out_starts_np = {
        ep: np.array(sorted(float(s.start_mus)
                            for s in item.out_span_partitions[ep]))
        for ep in out_eps
    }
    ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np)
    w_b = _bucket(max(hi - lo for lo, hi in windows))
    m_b = _bucket(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)))
    n_in = len(in_spans)
    dynamism = any(n_in - len(item.out_span_partitions[ep]) > 0
                   for ep in out_eps)
    n_passes = 1 if (dynamism
                     or item.method == "MaxScoreBatchSubsetWithTrueDist") \
        else 2
    return float(len(windows) * w_b * m_b * max(1, len(out_eps)) * n_passes)


def _run_fallback(entries, results, all_spans, all_processes,
                  solver_kwargs, stats) -> None:
    """Per-service solves for items the fused dispatch cannot carry.

    Dispatches overlap through a thread pool (the reference's own
    ThreadPool-over-services model, executor.py:1015-1026) and each
    solver's stage stats merge into the caller's dict — a mixed workload
    keeps both the overlap and the accounting it had on the pre-fleet
    bench path."""
    from concurrent.futures import ThreadPoolExecutor

    def run(entry):
        i, item = entry
        algo = WeaverTPU(
            item.store.all_spans if item.store else all_spans,
            item.store.all_processes if item.store else all_processes,
            **solver_kwargs)
        # oracle methods carry their flag through the fallback too
        # (the same method-name -> kwarg mapping runtime/executor.py does)
        kwargs = {}
        if item.method == "MaxScoreBatchSubsetWithTrueSkips":
            kwargs["true_skips"] = True
        elif item.method == "MaxScoreBatchSubsetWithTrueDist":
            kwargs["true_dist"] = True
        out = algo.FindAssignments(
            item.method, item.svc, item.in_span_partitions,
            item.out_span_partitions, False, [], item.true_assignments,
            item.dag, **kwargs,
        )
        return i, out, algo.stats

    with ThreadPoolExecutor(max_workers=max(1, len(entries))) as pool:
        for i, out, solver_stats in pool.map(run, entries):
            results[i] = out
            if stats is not None:
                for k, v in solver_stats.items():
                    stats[k] = stats.get(k, 0.0) + v


def solve_fleet(
    items: List[FleetItem],
    all_spans=None,
    all_processes=None,
    max_window: int = DEFAULT_MAX_WINDOW,
    epsilon: float = 1.0,
    n_sinkhorn: int = 40,
    n_sweeps: int = 5,
    sinkhorn_tol: float = 1e-3,
    mesh=None,
    stats: Optional[Dict[str, float]] = None,
    item_cells: Optional[List[float]] = None,
) -> List[Tuple]:
    """Solve every item, fusing eligible ones into one device dispatch.

    ``mesh`` (a ``jax.sharding.Mesh``) shards each dispatch group's
    window-batch axis across the mesh devices under XLA SPMD — the
    multi-chip form of the production path (the same window-axis
    sharding :class:`WeaverTPU` uses per service, applied to the fused
    program; the refit's cross-shard window gather lowers to XLA
    collectives automatically).

    ``item_cells`` (when given, a list the caller sized to ``len(items)``)
    receives each item's padded-compute-cell count at its own shape class
    (``n_windows * W * M * E``) — the quantity the device spends time on,
    used by callers to attribute one dispatch's wall-clock to services
    (runtime executor and the parity harness share this model).

    Returns one FindAssignments-style 6-tuple per item, in order:
    ``(all_assignments, all_topk, not_best_count, n_spans,
    per_span_candidates, cnt_unassigned)``.
    """
    # the fused path shards any mesh size (rows pad to a multiple); the
    # per-service fallback solver requires a power-of-two mesh, so a
    # non-pow2 mesh degrades fallback items to single-device rather than
    # crashing the whole mixed solve on WeaverTPU's assert
    n_mesh = int(mesh.devices.size) if mesh is not None else 1
    fallback_mesh = mesh if n_mesh & (n_mesh - 1) == 0 else None
    solver_kwargs = dict(max_window=max_window, epsilon=epsilon,
                         n_sinkhorn=n_sinkhorn, n_sweeps=n_sweeps,
                         sinkhorn_tol=sinkhorn_tol, mesh=fallback_mesh)
    solver = WeaverTPU(all_spans, all_processes, **solver_kwargs)
    results: List[Optional[Tuple]] = [None] * len(items)

    prepared = []
    fallback_entries = []
    for i, item in enumerate(items):
        prep = _prepare(item, solver)
        if prep is None:
            # host-in-the-loop configuration: per-service path
            fallback_entries.append((i, item))
            if item_cells is not None:
                item_cells[i] = _raw_cells(item, max_window)
        else:
            prepared.append((i, item, prep))
    if fallback_entries:
        _run_fallback(fallback_entries, results, all_spans, all_processes,
                      solver_kwargs, stats)
    if not prepared:
        return results  # type: ignore[return-value]

    # --- per-item window plan + shape class ------------------------------
    t0 = time.perf_counter()
    plans = []
    for i, item, prep in prepared:
        in_spans, out_eps = prep["in_spans"], prep["out_eps"]
        windows = perfect_cut_windows(in_spans, max_window)
        out_starts_np = {
            ep: np.array(sorted(float(s.start_mus)
                                for s in item.out_span_partitions[ep]))
            for ep in out_eps
        }
        ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np)
        skip_caps = water_fill_skip_caps(
            windows, ranges, len(in_spans),
            [len(item.out_span_partitions[ep]) for ep in out_eps])
        w_b = _bucket(max(hi - lo for lo, hi in windows))
        m_b = _bucket(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)))
        if item_cells is not None:
            item_cells[i] = (len(windows) * w_b * m_b
                             * max(1, len(out_eps)) * prep["n_passes"])
        plans.append((i, item, prep, windows, ranges, skip_caps, w_b, m_b))
    if stats is not None:
        stats["pack_s"] = stats.get("pack_s", 0.0) + time.perf_counter() - t0

    # --- group services into dispatch shape classes ----------------------
    # One fused program per class. Services with very different window
    # geometry must not share one padded shape: hotel_load150's search
    # (724 windows of 8x8x2) padded to its frontend's 32x32x3 pays 24x
    # its own compute in padding. Small classes merge upward while the
    # extra padded area stays under a budget that reflects the backend:
    # on TPU padded cells are nearly-free VPU work and a saved dispatch
    # is ~100 ms of tunnel latency (merge aggressively); on the CPU
    # stand-in padded cells are real core-seconds (merge conservatively).
    merge_env = os.environ.get("TW_FLEET_MERGE")
    if merge_env:
        merge_budget = int(merge_env)  # 0 = never merge shape classes
    else:
        import jax

        merge_budget = (1 << 24) if jax.default_backend() in ("tpu", "axon") \
            else (1 << 20)

    def shape_cost(group):
        w = max(p[6] for p in group)
        m = max(p[7] for p in group)
        e = max(len(p[2]["out_eps"]) for p in group)
        return sum(len(p[3]) for p in group) * w * m * e

    # class key includes the endpoint-count bucket: an E=12 service fused
    # with an E=1 service would pay 12x endpoint padding on the score
    # block and E^2 growth on the refit rows — exactly the padding class
    # the merge budget exists to arbitrate, so E outliers must start in
    # their own class and only merge if shape_cost approves. The pass
    # count splits classes too: single-pass (dynamism) and two-pass
    # (fused EM) services run different device programs and cannot share
    # a dispatch.
    classes: Dict[Tuple[int, int, int, int], List] = {}
    for plan in plans:
        e_b = _bucket(len(plan[2]["out_eps"]), minimum=1)
        classes.setdefault(
            (plan[2]["n_passes"], plan[6], plan[7], e_b), []).append(plan)
    ordered = sorted(classes, key=lambda k: (k[0], k[1] * k[2] * k[3]))
    groups: List[List] = []
    carry: List = []
    for idx, key in enumerate(ordered):
        wins = carry + classes[key]
        if idx + 1 < len(ordered) and ordered[idx + 1][0] == key[0]:
            nxt = wins + classes[ordered[idx + 1]]
            extra = shape_cost(nxt) - shape_cost(wins) \
                - shape_cost(classes[ordered[idx + 1]])
            if extra <= merge_budget:
                carry = wins
                continue
        groups.append(wins)
        carry = []
    if carry:
        groups.append(carry)

    # --- budget + dispatch per group -------------------------------------
    pending = []
    total_live = 0
    for group in groups:
        W_pad = max(p[6] for p in group)
        M_pad = max(p[7] for p in group)
        E_pad = max(len(p[2]["out_eps"]) for p in group)
        n_passes = group[0][2]["n_passes"]  # uniform within a class
        n_windows_total = sum(len(p[3]) for p in group)
        bmax = max(len(p[3]) for p in group)
        P = len(group)
        # Ne family rows per service in the fused refit (in/edge/return)
        Ne = E_pad + E_pad * E_pad + E_pad
        score_elems = n_windows_total * E_pad * W_pad * M_pad
        # the fused refit gathers each service's window rows: [P*Ne, Bmax*W]
        # (single-pass dynamism groups never refit)
        refit_elems = P * Ne * bmax * W_pad if n_passes == 2 else 0
        if score_elems + refit_elems > FLEET_BUDGET_ELEMS:
            # padded group block would stress HBM: per-service dispatches
            _run_fallback([(p[0], p[1]) for p in group], results,
                          all_spans, all_processes, solver_kwargs, stats)
            if stats is not None:
                stats["fleet_fallback_budget"] = 1.0
            continue
        if total_live + score_elems + refit_elems > FLEET_BUDGET_ELEMS:
            # keep every live dispatch under one budget: drain first
            for pend in pending:
                _decode_group(solver, pend, results, stats)
            pending = []
            total_live = 0
        total_live += score_elems + refit_elems
        pending.append(_dispatch_group(
            group, solver, stats, W_pad, M_pad, E_pad, bmax,
            epsilon=epsilon, n_sinkhorn=n_sinkhorn, n_sweeps=n_sweeps,
            sinkhorn_tol=sinkhorn_tol, mesh=mesh, n_passes=n_passes))
    for pend in pending:
        _decode_group(solver, pend, results, stats)
    return results  # type: ignore[return-value]


def _dispatch_group(group, solver, stats, W_pad, M_pad, E_pad, bmax,
                    epsilon, n_sinkhorn, n_sweeps, sinkhorn_tol,
                    mesh=None, n_passes=2):
    """Pack one shape-class group and launch its fused program
    (asynchronous — the returned handle is fetched by _decode_group):
    the two-pass EM program for static groups, the single-pass solve for
    dynamism groups (``n_passes=1``). With ``mesh``, the window-batch
    axis is padded to the mesh size and sharded (XLA SPMD); padded rows
    are invalid everywhere and decoded by nobody."""
    t0 = time.perf_counter()
    arrays_cat: Dict[str, List[np.ndarray]] = {}
    param_rows = {k: [] for k in (
        "pred_mask", "root_mask", "is_last",
        "edge_wt", "edge_mu", "edge_sd",
        "in_wt", "in_mu", "in_sd", "ret_wt", "ret_mu", "ret_sd")}
    per_item_pack = []
    param_idx = []
    for p, (i, item, prep, windows, ranges, skip_caps, _, _) in enumerate(group):
        packed = pack_problem(
            prep["in_spans"], item.out_span_partitions, prep["out_eps"],
            prep["dists"], prep["in_ep"], item.dag,
            force_skip_ids=prep["force_skip_ids"],
            parallel=False, windows=windows,
            pad_w=W_pad, pad_m=M_pad, pad_e=E_pad,
            ranges=ranges, skip_caps=skip_caps,
        )
        a = packed.arrays
        n_w = len(windows)
        for key in ("in_start", "in_end", "in_valid", "out_start",
                    "out_end", "out_valid", "skip_cap", "force_skip"):
            # drop pack_problem's power-of-two B padding: the fleet batch
            # is exact, and decode indexes out_ids by original row b which
            # is preserved under row slicing
            arrays_cat.setdefault(key, []).append(a[key][:n_w])
        # keep the id tables consistent with the sliced row count
        # (_decode sizes its gather table from the assign rows it is given)
        packed.out_ids = [col[:n_w * M_pad] for col in packed.out_ids]
        for key in param_rows:
            param_rows[key].append(a[key])
        param_idx.extend([p] * n_w)
        per_item_pack.append((i, item, prep, packed, n_w))

    batch = {k: np.concatenate(v, axis=0) for k, v in arrays_cat.items()}
    params = {k: np.stack(v, axis=0) for k, v in param_rows.items()}
    pidx = np.asarray(param_idx, dtype=np.int32)
    # static neighbour bounds over the whole group (fleet max in/out
    # degree, power-of-two bucketed): the score build gathers only real
    # DAG edges instead of evaluating all E_pad per endpoint
    pm_all = params["pred_mask"]
    _mp = _bucket(max(1, int(pm_all.sum(axis=2).max(initial=0))), minimum=1)
    _ms = _bucket(max(1, int(pm_all.sum(axis=1).max(initial=0))), minimum=1)
    # each service's contiguous window-row block, for the gathered refit
    P = len(per_item_pack)
    n_windows_total = len(param_idx)
    window_rows = np.zeros((P, bmax), dtype=np.int32)
    window_valid = np.zeros((P, bmax), dtype=bool)
    row0 = 0
    for p, (_, _, _, _, n_w) in enumerate(per_item_pack):
        window_rows[p, :n_w] = np.arange(row0, row0 + n_w, dtype=np.int32)
        window_valid[p, :n_w] = True
        row0 += n_w
    if stats is not None:
        stats["pack_s"] = stats.get("pack_s", 0.0) + time.perf_counter() - t0
        stats["fleet_dispatches"] = stats.get("fleet_dispatches", 0.0) + 1
        stats["fleet_services"] = (stats.get("fleet_services", 0.0)
                                   + float(len(per_item_pack)))
        # analytic op accounting (UPPER BOUND — sweep and Sinkhorn loops
        # exit early on convergence), same model as WeaverTPU._solve_once
        K = params["in_wt"].shape[2]
        cells = (n_windows_total * E_pad * W_pad * M_pad
                 * n_sweeps * n_passes)
        stats["flops_est"] = stats.get("flops_est", 0.0) + cells * (
            8.0 * K * (min(_mp, E_pad) + min(_ms, E_pad) + 2)
            + 6.0 * 2 * n_sinkhorn
            + 8.0 * max(1, W_pad.bit_length())
        )
        stats["bytes_est_xla"] = stats.get("bytes_est_xla", 0.0) + (
            cells * 4.0 * 2 * n_sinkhorn)
        stats["bytes_est_pallas"] = stats.get(
            "bytes_est_pallas", 0.0) + cells * 4.0 * 3
        if n_passes == 2:
            # counts fused EM dispatches (the grouping may produce several)
            stats["fused_em_applied"] = stats.get("fused_em_applied", 0.0) + 1.0
        else:
            stats["fleet_dynamism_dispatches"] = stats.get(
                "fleet_dynamism_dispatches", 0.0) + 1.0

    # --- device program(s) -----------------------------------------------
    # Convergence compaction (host in the loop, mesh-less path only): the
    # vmapped sweep while_loop runs EVERY window until the slowest one's
    # Gauss-Seidel assignments stabilize — converged windows' updates are
    # select-masked into no-ops but still burn VPU cycles. So each solve
    # pass runs as (1) a warm dispatch capped at TW_SWEEP_WARM sweeps,
    # (2) a host-side gather of the windows whose convergence flag
    # (packed channel 3) is still false, bucketed to a power-of-two batch
    # (the existing shape-class discipline, so redispatch batch sizes
    # cannot multiply compiled variants), (3) a full-sweep redispatch of
    # only those rows, scattered back over the warm output. Converged
    # windows keep their warm output — the sweep loop's exactness
    # argument (a reproducing sweep is a fixed point) makes that output
    # bit-identical to what the full-budget run would have produced, and
    # the redispatch reruns stragglers from sweep 0, so compaction is
    # output-identical to the uncompacted dispatch by construction
    # (tests/test_compaction.py pins this down). Two-pass (fused EM)
    # groups split into warm/full pass 0 -> one refit dispatch
    # (weaver_tpu.refit_fleet_params — the same refit solve_em_fleet runs
    # in-graph) -> warm/full pass 1.
    warm = _compaction_warm()
    use_compact = (_compaction_on() and mesh is None
                   and warm < n_sweeps and len(param_idx) > 1)
    if mesh is not None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from traceweaver_tpu.parallel.mesh import _pad_batch, put_sharded

        # padded rows are all-invalid windows of service 0: they assign
        # nothing, contribute no refit samples (window_rows/window_valid
        # index only real rows), and the per-item decode never reads them
        n_dev = int(mesh.devices.size)
        batch, true_b = _pad_batch(batch, n_dev)
        pidx = np.concatenate(
            [pidx, np.zeros(batch["in_start"].shape[0] - true_b,
                            dtype=pidx.dtype)])
        # put_sharded: window-axis keys sharded, everything else
        # (param tables, window_rows/valid) replicated
        placed = put_sharded(
            {**batch, **params,
             "window_rows": window_rows, "window_valid": window_valid},
            mesh)
        batch = {k: placed[k] for k in batch}
        params = {k: placed[k] for k in params}
        window_rows = placed["window_rows"]
        window_valid = placed["window_valid"]
        pidx = jax.device_put(
            pidx, NamedSharding(mesh, PartitionSpec(mesh.axis_names[0])))
    t0 = time.perf_counter()
    from traceweaver_tpu.runtime.jax_cache import compile_counters, counters_delta

    counters_before = compile_counters()
    common = (
        batch["in_start"], batch["in_end"], batch["in_valid"],
        batch["out_start"], batch["out_end"], batch["out_valid"],
        batch["skip_cap"], batch["force_skip"], pidx,
    )
    tables = (
        params["pred_mask"], params["root_mask"], params["is_last"],
        params["edge_wt"], params["edge_mu"], params["edge_sd"],
        params["in_wt"], params["in_mu"], params["in_sd"],
        params["ret_wt"], params["ret_mu"], params["ret_sd"],
    )
    hypers = dict(epsilon=epsilon, n_sinkhorn=n_sinkhorn,
                  sinkhorn_tol=sinkhorn_tol, max_preds=_mp, max_succs=_ms)
    wait_before = stats.get("wait_s", 0.0) if stats is not None else 0.0
    if use_compact:
        out = _solve_group_compacted(
            batch, pidx, params, tables, window_rows, window_valid,
            n_passes, n_sweeps, warm, hypers, stats)
    elif n_passes == 2:
        out = solve_em_fleet(
            *common, window_rows, window_valid, *tables,
            n_sweeps=n_sweeps, **hypers,
        )
    else:
        out = solve_windows_fleet(
            *common, *tables, n_sweeps=n_sweeps, **hypers,
        )
    if stats is not None:
        # the compacted flow blocks on its intermediate fetches, billed to
        # wait_s inside _compacted_pass — dispatch_s stays launch/host time
        flow_wait = stats.get("wait_s", 0.0) - wait_before
        stats["dispatch_s"] = (stats.get("dispatch_s", 0.0)
                               + time.perf_counter() - t0 - flow_wait)
        # recompiles are the shape-class regression signal: a warm steady
        # state dispatches with zero compiles, so any nonzero delta here
        # is a new program variant (bench surfaces these per run)
        for key, val in counters_delta(counters_before).items():
            if val:
                stats[key] = stats.get(key, 0.0) + val
    try:
        out.copy_to_host_async()
    except AttributeError:  # plain np.ndarray under some backends
        pass
    return per_item_pack, out


def _compacted_pass(batch, pidx, tables, n_sweeps, warm, hypers, stats):
    """One solve pass as warm dispatch + compacted full redispatch.

    Returns the packed [B, E, W, 4+topk] output, bit-identical to a
    single ``n_sweeps`` dispatch of the same batch (see the compaction
    comment in :func:`_dispatch_group`)."""
    def _fetch(handle):
        # blocking device fetch: accounted as wait_s (device-execution
        # proxy), same stage the async single-dispatch flow bills it to
        t0 = time.perf_counter()
        out = np.asarray(handle)
        if stats is not None:
            stats["wait_s"] = (stats.get("wait_s", 0.0)
                               + time.perf_counter() - t0)
        return out

    args = tuple(batch[k] for k in _BATCH_KEYS) + (pidx,)
    out_warm = _fetch(solve_windows_fleet(
        *args, *tables, n_sweeps=warm, **hypers))
    converged = out_warm[:, 0, 0, 3].astype(bool)
    active = np.flatnonzero(~converged)
    if stats is not None:
        stats["compact_windows_total"] = (
            stats.get("compact_windows_total", 0.0) + out_warm.shape[0])
        stats["compact_windows_redispatched"] = (
            stats.get("compact_windows_redispatched", 0.0) + active.size)
    if active.size == 0:
        return out_warm
    b_pad = _bucket(int(active.size), minimum=1)
    pad = b_pad - int(active.size)
    gathered = []
    for k in _BATCH_KEYS:
        a = batch[k][active]
        if pad:
            # padding rows are all-invalid windows: no valid spans or
            # columns, so they assign nothing and are decoded by nobody
            # (same convention as pack_problem's pad_b rows)
            a = np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)])
        gathered.append(a)
    pidx_active = np.asarray(pidx)[active]
    if pad:
        pidx_active = np.concatenate(
            [pidx_active, np.zeros(pad, dtype=pidx_active.dtype)])
    out_full = _fetch(solve_windows_fleet(
        *gathered, pidx_active, *tables, n_sweeps=n_sweeps, **hypers))
    out = out_warm.copy()
    out[active] = out_full[:active.size]
    return out


def _solve_group_compacted(batch, pidx, params, tables, window_rows,
                           window_valid, n_passes, n_sweeps, warm, hypers,
                           stats):
    """Compacted replacement for one fused group dispatch: per-pass
    warm/redispatch compaction, with the two-pass EM's on-device refit as
    its own dispatch between the passes (same refit program
    ``solve_em_fleet`` runs in-graph, so the flows cannot drift)."""
    out0 = _compacted_pass(batch, pidx, tables, n_sweeps, warm, hypers,
                           stats)
    if n_passes == 1:
        return out0
    new_tables = refit_fleet_params(
        out0[..., 0].astype(np.int32),
        batch["in_start"], batch["in_end"], batch["in_valid"],
        batch["out_start"], batch["out_end"], pidx,
        window_rows, window_valid,
        params["pred_mask"], params["root_mask"],
        params["edge_wt"], params["edge_mu"], params["edge_sd"],
        params["in_wt"], params["in_mu"], params["in_sd"],
        params["ret_wt"], params["ret_mu"], params["ret_sd"])
    return _compacted_pass(batch, pidx, tables[:3] + tuple(new_tables),
                           n_sweeps, warm, hypers, stats)


def _decode_group(solver, pend, results, stats):
    """Fetch one group's packed output and decode it per service."""
    per_item_pack, out = pend
    t0 = time.perf_counter()
    o = np.asarray(out)
    if stats is not None:
        stats["wait_s"] = stats.get("wait_s", 0.0) + time.perf_counter() - t0

    t0 = time.perf_counter()
    row = 0
    for i, item, prep, packed, n_w in per_item_pack:
        rows = o[row:row + n_w]
        row += n_w
        assign = rows[..., 0]
        not_best = rows[..., 1].astype(bool)
        feas = rows[..., 2]
        # rows[..., 3] is the sweep-convergence flag (already consumed by
        # the compaction redispatch inside _dispatch_group)
        topk_cols = rows[..., 4:]
        out_eps = prep["out_eps"]
        in_ids = [s.GetId() for s in prep["in_spans"]]
        n_in = prep["n_in"]

        all_assignments = {ep: {} for ep in out_eps}
        all_topk = {ep: {} for ep in out_eps}
        solver._decode(packed, assign, topk_cols, all_assignments, all_topk)
        span_not_best = np.zeros(n_in, dtype=bool)
        span_cands = np.ones(n_in, dtype=np.int64)
        for b, (lo, hi) in enumerate(packed.windows):
            for j in range(hi - lo):
                span_not_best[lo + j] = bool(not_best[b, :, j].any())
                span_cands[lo + j] = int(np.maximum(feas[b, :, j], 1).prod())
        solver._resolve_cross_window_duplicates(
            all_assignments, all_topk, in_ids, prep["skip_budget"])
        cnt_unassigned = sum(
            1 for in_id in in_ids
            if any(all_assignments[ep][in_id] == NA for ep in out_eps)
        )
        results[i] = (
            all_assignments, all_topk, int(span_not_best.sum()), n_in,
            {in_ids[j]: int(span_cands[j]) for j in range(n_in)},
            cnt_unassigned,
        )
    if stats is not None:
        stats["decode_s"] = (stats.get("decode_s", 0.0)
                             + time.perf_counter() - t0)
