"""Exact maximum-weight independent set (CPU oracle).

Branch-and-bound in the style of Mehrotra & Trick's column-generation
subproblem — the same algorithm family as the reference's license-free
fallback (reference traceweaver_v3.py:1305-1393 ``exact_MWIS``), standing in
for the Gurobi ILP (traceweaver_v3.py:1395-1419). Used to resolve
per-window conflicts among top-K candidate assignments in
:mod:`traceweaver_tpu.algorithms.weaver_exact`, and as the correctness
oracle the TPU solver is validated against on small windows.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Set, Tuple

EPS = 1e-9


def exact_mwis(adj: Dict[Hashable, Set[Hashable]],
               weight: Dict[Hashable, float]) -> Tuple[List[Hashable], float]:
    """Exact MWIS on an adjacency-set graph. Returns (nodes, total weight).

    Branch on the highest degree*weight node: either include it (dropping
    its neighbors) or exclude it, pruning branches whose optimistic bound
    (current score + sum of remaining weights) can't beat the incumbent.

    Nodes with non-positive weight are dropped upfront: removing such a node
    from any independent set keeps it independent without lowering the
    total, so none can belong to an optimal solution — and with all-positive
    weights the isolated-node inclusion and the optimistic bound are valid.
    """
    weight = {n: w for n, w in weight.items() if w > 0}
    adj = {n: {m for m in nbrs if m in weight}
           for n, nbrs in adj.items() if n in weight}
    best: Tuple[float, Tuple[Hashable, ...]] = (-float("inf"), ())

    def solve(nodes: Set[Hashable], score: float,
              chosen: Tuple[Hashable, ...]) -> None:
        nonlocal best
        ub = score + sum(weight[n] for n in nodes)
        if ub <= best[0] + EPS:
            return
        if not nodes:
            if score > best[0]:
                best = (score, chosen)
            return
        # isolated nodes are always taken
        isolated = [n for n in nodes if not (adj[n] & nodes)]
        if isolated:
            gain = sum(weight[n] for n in isolated)
            solve(nodes - set(isolated), score + gain,
                  chosen + tuple(isolated))
            return
        pivot = max(nodes, key=lambda n: len(adj[n] & nodes) * weight[n])
        # branch 1: include pivot
        solve(nodes - {pivot} - adj[pivot], score + weight[pivot],
              chosen + (pivot,))
        # branch 2: exclude pivot
        solve(nodes - {pivot}, score, chosen)

    solve(set(weight), 0.0, ())
    return list(best[1]), best[0]
