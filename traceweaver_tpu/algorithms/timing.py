"""Per-edge delay-distribution estimation (host side).

The solver scores a candidate (incoming span, outgoing span) pair by the
log-likelihood of the delay between the predecessor event and the outgoing
span's start under a per-call-graph-edge delay distribution. This module
learns those distributions, replicating the reference's estimators:

- :func:`batch_means_params` — order-statistics batch-means estimate of
  (mean, std) from two sorted event-time vectors (reference:
  traceweaver_v1.py:47-108 ``ComputeDistParams``);
- :func:`estimate_edge_params` — graph-aware application across the
  invocation DAG (reference: traceweaver_v3.py:580-646
  ``ComputeEpPairDistParams3``);
- :func:`bootstrap_distributions` — unsupervised bootstrap from raw span
  streams by the nearest-preceding-parent heuristic (reference:
  traceweaver_v3.py:108-172 ``BuildDistributions``);
- :func:`refit_from_assignments` — EM-style per-edge GMM refit with
  BIC-selected 1..5 components from a completed assignment pass
  (reference: traceweaver_v3.py:706-818 ``ComputeEpPairDistParams5``).

Distributions are represented uniformly as :class:`EdgeDist` — a Gaussian
mixture padded to ``MAX_COMPONENTS`` so every edge ships to the device as
fixed-shape (weights, means, vars) rows.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.runtime.bucketing import pow2_bucket
from traceweaver_tpu.spans import NA, SKIP, Span

MAX_COMPONENTS = 5
# Floor on std to avoid singular densities (reference traceweaver_v1.py:130-132
# substitutes 0.001 when std underflows).
MIN_STD = 1e-3

EdgeKey = Tuple[str, str]


@dataclass
class EdgeDist:
    """Gaussian-mixture delay distribution for one call-graph edge."""

    weights: np.ndarray  # [MAX_COMPONENTS]
    means: np.ndarray    # [MAX_COMPONENTS]
    stds: np.ndarray     # [MAX_COMPONENTS]

    @classmethod
    def gaussian(cls, mean: float, std: float) -> "EdgeDist":
        w = np.zeros(MAX_COMPONENTS)
        m = np.zeros(MAX_COMPONENTS)
        s = np.full(MAX_COMPONENTS, 1.0)
        w[0] = 1.0
        m[0] = mean
        s[0] = max(float(std), MIN_STD)
        return cls(w, m, s)

    @classmethod
    def from_samples_gmm(cls, samples: Sequence[float],
                         max_components: int = MAX_COMPONENTS,
                         random_state: int = 100) -> "EdgeDist":
        """BIC-selected GMM fit (reference traceweaver_v3.py:764-786)."""
        x = np.asarray(samples, dtype=np.float64).reshape(-1, 1)
        if len(x) == 0:
            return cls.gaussian(0.0, MIN_STD)
        n_unique = len(np.unique(x))
        if n_unique == 1 or len(x) < 4:
            return cls.gaussian(float(np.mean(x)), float(np.std(x)))
        from sklearn import mixture

        best, best_bic = None, np.inf
        for n in range(1, min(n_unique, max_components) + 1):
            try:
                model = mixture.GaussianMixture(
                    n_components=n, covariance_type="diag",
                    random_state=random_state).fit(x)
            except ValueError:
                continue
            bic = model.bic(x)
            if bic < best_bic:
                best, best_bic = model, bic
        if best is None:
            return cls.gaussian(float(np.mean(x)), float(np.std(x)))
        k = best.n_components
        w = np.zeros(MAX_COMPONENTS)
        m = np.zeros(MAX_COMPONENTS)
        s = np.full(MAX_COMPONENTS, 1.0)
        w[:k] = best.weights_
        m[:k] = best.means_.ravel()
        # Floor component stds at 1µs: delays are integer microseconds, and
        # a near-zero-variance component would otherwise turn into a density
        # spike that dominates every feasible candidate's score.
        s[:k] = np.maximum(np.sqrt(best.covariances_.ravel()), 1.0)
        return cls(w, m, s)

    @classmethod
    def from_samples_kde(cls, samples: Sequence[float],
                         max_components: int = MAX_COMPONENTS) -> "EdgeDist":
        """Gaussian-KDE density as a fixed-shape mixture.

        The reference's KDE score mode evaluates a ``scipy.gaussian_kde``
        over the raw per-edge delays (reference traceweaver_v1.py:117-121);
        a Gaussian KDE *is* an equal-weight mixture of n components at the
        samples with the bandwidth as std, so for n <= K this is exact.
        For n > K the samples are quantile-binned into K components with
        moment-matched stds (sqrt(h^2 + within-bin variance)) — a binned
        KDE, fixed-shape for the device. Bandwidth is Scott's rule
        (h = sigma * n^(-1/5)), scipy's default.
        """
        x = np.asarray(samples, dtype=np.float64).ravel()
        if len(x) == 0:
            return cls.gaussian(0.0, MIN_STD)
        n = len(x)
        sigma = float(np.std(x, ddof=1)) if n > 1 else 0.0
        if sigma <= 0:
            return cls.gaussian(float(x[0]), MIN_STD)
        h = sigma * n ** (-1.0 / 5.0)
        K = max_components
        w = np.zeros(MAX_COMPONENTS)
        m = np.zeros(MAX_COMPONENTS)
        s = np.full(MAX_COMPONENTS, 1.0)
        if n <= K:
            w[:n] = 1.0 / n
            m[:n] = x
            s[:n] = max(h, 1.0)
        else:
            edges = np.quantile(x, np.linspace(0, 1, K + 1))
            idx = np.clip(np.searchsorted(edges, x, side="right") - 1, 0, K - 1)
            for k in range(K):
                sel = x[idx == k]
                if len(sel) == 0:
                    continue
                w[k] = len(sel) / n
                m[k] = float(np.mean(sel))
                s[k] = max(math.sqrt(h * h + float(np.var(sel))), 1.0)
        return cls(w, m, s)

    def logpdf(self, x: np.ndarray) -> np.ndarray:
        """Mixture log-density (numpy; the device version lives in ops)."""
        x = np.asarray(x, dtype=np.float64)[..., None]
        comp = (
            -0.5 * ((x - self.means) / self.stds) ** 2
            - np.log(self.stds)
            - 0.5 * math.log(2 * math.pi)
        )
        w = np.where(self.weights > 0, self.weights, 0.0)
        logw = np.where(w > 0, np.log(np.maximum(w, 1e-300)), -np.inf)
        return np.asarray(np.logaddexp.reduce(comp + logw, axis=-1))


def fit_value_dists(values_by_edge: Dict[EdgeKey, List[float]],
                    score_mode: str = "mixture",
                    mixture_fit: str = "gaussian") -> Dict[EdgeKey, EdgeDist]:
    """Single dispatch point for turning per-edge delay samples into
    :class:`EdgeDist`s: ``score_mode == "kde"`` -> binned-KDE mixture
    (reference traceweaver_v1.py:117-121 KDE branch); otherwise
    ``mixture_fit`` picks single Gaussians or batched BIC-GMMs."""
    if score_mode == "kde":
        return {k: EdgeDist.from_samples_kde(v)
                for k, v in values_by_edge.items()}
    if mixture_fit == "gmm":
        return fit_edge_gmms(values_by_edge)
    return {
        k: EdgeDist.gaussian(float(np.mean(v)), float(np.std(v)))
        for k, v in values_by_edge.items()
    }


def batch_means_params(t1: Sequence[float], t2: Sequence[float],
                       nbatches: int = 10) -> Tuple[float, float]:
    """(mean, std) of elementwise delay between two sorted time vectors.

    The std is estimated from the spread of batch means scaled back by
    sqrt(batch_size) — robust to the unknown pairing within a batch
    (reference traceweaver_v1.py:55-76).
    """
    t1 = list(t1)
    t2 = list(t2)
    assert len(t1) == len(t2) and len(t1) > 0
    mean = (sum(t2) - sum(t1)) / len(t1)
    batch_size = math.ceil(float(len(t1)) / nbatches)
    batch_means = []
    for i in range(nbatches):
        lo, hi = i * batch_size, min(len(t1), (i + 1) * batch_size)
        if hi - lo > 0:
            batch_means.append((sum(t2[lo:hi]) - sum(t1[lo:hi])) / (hi - lo))
    if len(batch_means) >= 2:
        import scipy.stats

        std = math.sqrt(batch_size) * float(scipy.stats.tstd(batch_means))
        if math.isnan(std):
            std = MIN_STD
    else:
        std = MIN_STD
    return mean, std


def has_longer_path(dag: nx.DiGraph, src: str, dst: str) -> bool:
    """True if src reaches dst by some path of length > 1 (so the direct
    edge is a shortcut and its delay is not a primary dependency;
    reference traceweaver_v1.py:245-254 ``AlsoNonPrimaryAncestor``)."""
    for path in nx.all_simple_paths(dag, source=src, target=dst, cutoff=2):
        if len(path) - 1 > 1:
            return True
    return False


def primary_pred_edges(dag: nx.DiGraph, out_ep: str) -> List[str]:
    """Direct predecessors of ``out_ep`` whose edge is primary (not a
    shortcut past a longer path)."""
    return [
        p for p, _ in dag.in_edges(out_ep) if not has_longer_path(dag, p, out_ep)
    ]


def estimate_edge_params(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    dag: nx.DiGraph,
    lo: int,
    hi: int,
) -> Dict[EdgeKey, EdgeDist]:
    """Graph-aware batch-means estimation over span index window [lo, hi).

    Edges estimated (reference traceweaver_v3.py:619-646):
    - (in_ep, e) for every root endpoint e (no DAG predecessors): delay
      between sorted incoming starts and sorted e starts;
    - (p, e) for every primary DAG edge: sorted p ends vs sorted e starts;
    - (e, in_ep) for every endpoint: sorted e ends vs sorted incoming ends.
    """
    in_ep = next(iter(in_span_partitions))
    dists: Dict[EdgeKey, EdgeDist] = {}

    def est(ep1: str, ep2: str, t1: List[float], t2: List[float]) -> None:
        mean, std = batch_means_params(sorted(t1)[lo:hi], sorted(t2)[lo:hi])
        dists[(ep1, ep2)] = EdgeDist.gaussian(mean, std)

    in_starts = [s.start_mus for s in in_span_partitions[in_ep]]
    in_ends = [s.start_mus + s.duration_mus for s in in_span_partitions[in_ep]]

    for out_ep, out_spans in out_span_partitions.items():
        starts = [s.start_mus for s in out_spans]
        ends = [s.start_mus + s.duration_mus for s in out_spans]
        preds = primary_pred_edges(dag, out_ep)
        if len(dag.in_edges(out_ep)) == 0:
            est(in_ep, out_ep, in_starts, starts)
        for p in preds:
            if p == in_ep:
                est(p, out_ep, in_starts, starts)
            else:
                p_ends = [s.start_mus + s.duration_mus for s in out_span_partitions[p]]
                est(p, out_ep, p_ends, starts)
        est(out_ep, in_ep, ends, in_ends)
    return dists


def bootstrap_distributions(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    out_eps: List[str],
    store_processes=None,
    store_spans=None,
    score_mode: str = "mixture",
) -> Dict[EdgeKey, EdgeDist]:
    """Unsupervised bootstrap: attribute each span to its nearest plausible
    preceding parent in a merged time-sorted stream (reference
    traceweaver_v3.py:108-172). ``score_mode == "kde"`` fits each edge's
    bootstrap samples as a binned-KDE mixture instead of a single Gaussian.
    """
    in_ep = next(iter(in_span_partitions))
    tagged: List[Tuple[Span, str]] = []
    for span in in_span_partitions[in_ep]:
        tagged.append((span, in_ep))
    for out_ep in out_eps:
        for span in out_span_partitions[out_ep]:
            tagged.append((span, out_ep))
    tagged.sort(key=lambda t: t[0].start_mus)
    large_delay = max(s.duration_mus for s in in_span_partitions[in_ep])
    order = {ep: i for i, ep in enumerate(out_eps)}

    values: Dict[EdgeKey, List[float]] = {}

    for i, (span, ep) in enumerate(tagged):
        if span.span_kind == "client":
            sent = span.start_mus
            dur = span.duration_mus
            parent: Optional[Tuple[Span, str, str]] = None
            for j in range(i - 1, -1, -1):  # no slice copies: O(n^2) otherwise
                pspan, pep = tagged[j]
                if (sent + dur) - pspan.start_mus > large_delay:
                    break
                if pspan.span_kind == "server":
                    parent = (pspan, pep, "server")
                    break
                if (pspan.span_kind == "client"
                        and pspan.start_mus + pspan.duration_mus < span.start_mus
                        and order.get(pep, 1 << 30) < order.get(ep, 1 << 30)):
                    parent = (pspan, pep, "client")
                    break
            if parent is not None:
                pspan, pep, kind = parent
                delay = (sent - pspan.start_mus if kind == "server"
                         else sent - (pspan.start_mus + pspan.duration_mus))
                values.setdefault((pep, ep), []).append(delay)
        elif span.span_kind == "server":
            sent = span.start_mus
            dur = span.duration_mus
            parent = None
            for j in range(i - 1, -1, -1):
                pspan, pep = tagged[j]
                if (sent + dur) - pspan.start_mus > large_delay:
                    break
                if (pspan.span_kind == "client"
                        and pspan.start_mus + pspan.duration_mus < sent + dur):
                    parent = (pspan, pep, "client")
                    break
            if parent is not None:
                pspan, pep, _ = parent
                values.setdefault((pep, ep), []).append(
                    (sent + dur) - (pspan.start_mus + pspan.duration_mus)
                )
            values.setdefault((ep, ep), []).append(dur)

    return fit_value_dists(values, score_mode)


def refit_from_assignments(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    dag: nx.DiGraph,
    assignments: Dict[str, Dict],
    all_spans: Dict,
    score_mode: str = "mixture",
) -> Dict[EdgeKey, EdgeDist]:
    """EM refit: per-edge delay samples from a completed assignment pass,
    fit as BIC-selected GMMs (reference traceweaver_v3.py:706-818), or as
    binned-KDE mixtures when ``score_mode == "kde"`` (the reference's KDE
    score branch, traceweaver_v1.py:117-121).

    Spans are resolved from ``out_span_partitions`` (not ``all_spans``) so
    that synthetic transforms applied to the partitions — load compression,
    cache-hit shifts — stay on one consistent timeline.
    """
    if dag is None:
        # no precedence information: every endpoint hangs off the incoming span
        dag = nx.DiGraph()
        dag.add_nodes_from(out_span_partitions.keys())
    in_ep = next(iter(in_span_partitions))
    dists: Dict[EdgeKey, EdgeDist] = {}
    by_id = {
        ep: {s.GetId(): s for s in spans}
        for ep, spans in out_span_partitions.items()
    }

    def span_of(assign_map, in_span, ep):
        sid = assign_map.get(in_span.GetId())
        if sid is None or tuple(sid) in (NA, SKIP):
            return None
        sid = tuple(sid)
        return by_id[ep].get(sid) or all_spans.get(sid)

    samples_by_edge: Dict[EdgeKey, List[float]] = {}
    for out_ep in out_span_partitions:
        preds = primary_pred_edges(dag, out_ep)
        # (in_ep -> out_ep): out.start - in.start
        if len(dag.in_edges(out_ep)) == 0 or in_ep in preds:
            samples = []
            for in_span in in_span_partitions[in_ep]:
                out = span_of(assignments[out_ep], in_span, out_ep)
                if out is not None:
                    samples.append(out.start_mus - in_span.start_mus)
            samples_by_edge[(in_ep, out_ep)] = samples
        # (p -> out_ep): out.start - p_out.end
        for p in preds:
            if p == in_ep:
                continue
            samples = []
            for in_span in in_span_partitions[in_ep]:
                p_out = span_of(assignments[p], in_span, p)
                out = span_of(assignments[out_ep], in_span, out_ep)
                if p_out is not None and out is not None:
                    samples.append(
                        out.start_mus - (p_out.start_mus + p_out.duration_mus)
                    )
            samples_by_edge[(p, out_ep)] = samples
        # (out_ep -> in_ep): in.end - out.end
        samples = []
        for in_span in in_span_partitions[in_ep]:
            out = span_of(assignments[out_ep], in_span, out_ep)
            if out is not None:
                samples.append(
                    (in_span.start_mus + in_span.duration_mus)
                    - (out.start_mus + out.duration_mus)
                )
        samples_by_edge[(out_ep, in_ep)] = samples
    dists.update(fit_value_dists(samples_by_edge, score_mode,
                                 mixture_fit="gmm"))
    return dists


def fit_edge_gmms(samples_by_edge: Dict[EdgeKey, List[float]],
                  ) -> Dict[EdgeKey, EdgeDist]:
    """Fit every edge's delay GMM in one batched device dispatch
    (:func:`traceweaver_tpu.ops.gmm.fit_gmm_batched`); degenerate edges
    (constant or < 4 samples) take the closed-form host path, and
    ``TW_JAX_GMM=0`` falls back to the per-edge sklearn fit entirely."""
    use_device = _knobs.get_bool("TW_JAX_GMM")
    dists: Dict[EdgeKey, EdgeDist] = {}
    device_keys: List[EdgeKey] = []
    device_samples: List[np.ndarray] = []
    for key, v in samples_by_edge.items():
        arr = np.asarray(v, dtype=np.float64)
        if not use_device or len(arr) < 4 or len(np.unique(arr)) == 1:
            dists[key] = EdgeDist.from_samples_gmm(v)
        else:
            device_keys.append(key)
            device_samples.append(arr)
    if device_keys:
        from traceweaver_tpu.ops.gmm import fit_gmm_batched

        n = max(len(a) for a in device_samples)
        n_pad = pow2_bucket(n)
        e_pad = pow2_bucket(len(device_keys))
        # AOT lattice audit (runtime/aot.py): a plan-fit GMM block outside
        # the precompiled [e, n] lattice is a steady-state compile escape
        from traceweaver_tpu.runtime import aot as _aot

        _aot.note_gmm(e_pad, n_pad)
        # f64 all the way to fit_gmm_batched's host-side standardization —
        # packing in f32 here would forfeit the precision it preserves
        x = np.zeros((e_pad, n_pad), dtype=np.float64)
        mask = np.zeros((e_pad, n_pad), dtype=bool)
        for i, a in enumerate(device_samples):
            x[i, :len(a)] = a
            mask[i, :len(a)] = True
        w, mu, sd = (np.asarray(o) for o in
                     fit_gmm_batched(x, mask, max_k=MAX_COMPONENTS))
        for i, key in enumerate(device_keys):
            dists[key] = EdgeDist(w[i].astype(np.float64),
                                  mu[i].astype(np.float64),
                                  sd[i].astype(np.float64))
    return dists


def true_distributions(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    out_eps: List[str],
    true_assignments: Dict[str, Dict],
    score_mode: str = "mixture",
) -> Dict[EdgeKey, EdgeDist]:
    """Oracle distributions from ground truth (reference
    traceweaver_v3.py:66-106 ``BuildTrueDistributions``) — used by the
    ``WithTrueDist`` ablation."""
    in_ep = next(iter(in_span_partitions))
    by_id = {
        ep: {s.GetId(): s for s in spans}
        for ep, spans in out_span_partitions.items()
    }
    values: Dict[EdgeKey, List[float]] = {}
    for in_span in in_span_partitions[in_ep]:
        prev_span: Optional[Span] = None
        prev_ep: Optional[str] = None
        for depth, out_ep in enumerate(out_eps):
            sid = true_assignments[out_ep].get(in_span.GetId())
            if sid is None or tuple(sid) == SKIP:
                continue
            out = by_id[out_ep].get(tuple(sid))
            if out is None:
                continue
            if prev_span is None:
                values.setdefault((in_ep, out_ep), []).append(
                    out.start_mus - in_span.start_mus
                )
            else:
                values.setdefault((prev_ep, out_ep), []).append(
                    out.start_mus - (prev_span.start_mus + prev_span.duration_mus)
                )
            prev_span, prev_ep = out, out_ep
        if prev_span is not None:
            values.setdefault((prev_ep, in_ep), []).append(
                (in_span.start_mus + in_span.duration_mus)
                - (prev_span.start_mus + prev_span.duration_mus)
            )
    return fit_value_dists(values, score_mode)
