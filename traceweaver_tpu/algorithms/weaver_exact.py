"""CPU reference solver: DFS top-K enumeration + windowed exact MWIS.

A faithful-capability reimplementation of the reference's TraceWeaver
V1/V2 solvers (reference traceweaver_v1.py:363-527, traceweaver_v2.py:
32-179) with the Gurobi ILP replaced by the exact branch-and-bound MWIS in
:mod:`traceweaver_tpu.algorithms.mwis`. It exists for three reasons:

1. **Correctness oracle** — the TPU Sinkhorn solver is validated against it
   on small windows (same score model, provably optimal conflict
   resolution);
2. **Benchmark baseline** — it *is* the combinatorial CPU path whose
   spans/sec the TPU solver is measured against (BASELINE.md north star);
3. **Registry parity** — it backs predictor indices 0-2
   (``MaxScoreBatch`` / ``MaxScoreBatchParallel`` / ``MaxScore``).

Methods:
- ``MaxScore`` — per-span greedy argmax DFS, consuming spans on assignment
  (V1 semantics, traceweaver_v1.py:490-527);
- ``MaxScoreBatch`` / ``MaxScoreBatchParallel`` — top-K=5 candidate heaps
  per span; every 30 spans, a conflict graph over candidates is solved as
  exact MWIS (V2 semantics, traceweaver_v2.py:113-179; node weight
  10000+score as in traceweaver_v2.py:205).
"""

from __future__ import annotations

import heapq
import math
from typing import Dict, List, Optional, Tuple

import numpy as np
import scipy.stats

from traceweaver_tpu.algorithms.mwis import exact_mwis
from traceweaver_tpu.algorithms.timing import batch_means_params
from traceweaver_tpu.metrics.accuracy import get_out_eps_in_order
from traceweaver_tpu.spans import NA, Span

BATCH_SIZE_DIST = 100
BATCH_SIZE_MIS = 30
TOP_K = 5
MIS_WEIGHT_OFFSET = 10000.0


class WeaverExact:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes
        self.services_times: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self.parallel = False
        self.instrumented_hops: List[int] = []
        self.true_assignments = None
        self.per_span_candidates: Dict = {}

    # -- distribution estimation (traceweaver_v1.py:47-108) ---------------
    def _estimate_dists(self, in_span_partitions, out_span_partitions,
                        out_eps, lo, hi):
        in_ep = next(iter(in_span_partitions))

        def est(ep1, ep2, t1, t2):
            mean, std = batch_means_params(sorted(t1)[lo:hi], sorted(t2)[lo:hi])
            self.services_times[(ep1, ep2)] = (mean, std)

        in_starts = [s.start_mus for s in in_span_partitions[in_ep]]
        in_ends = [s.end_mus for s in in_span_partitions[in_ep]]
        if self.parallel:
            for ep in out_eps:
                est(in_ep, ep, in_starts,
                    [s.start_mus for s in out_span_partitions[ep]])
        else:
            est(in_ep, out_eps[0], in_starts,
                [s.start_mus for s in out_span_partitions[out_eps[0]]])
            for a, b in zip(out_eps, out_eps[1:]):
                est(a, b, [s.end_mus for s in out_span_partitions[a]],
                    [s.start_mus for s in out_span_partitions[b]])
            est(out_eps[-1], in_ep,
                [s.end_mus for s in out_span_partitions[out_eps[-1]]], in_ends)

    def _edge_cost(self, ep1, ep2, t1, t2) -> float:
        mean, std = self.services_times[(ep1, ep2)]
        if std < 1e-12:
            std = 0.001
        return float(scipy.stats.norm.logpdf(t2 - t1, loc=mean, scale=std))

    # -- assignment scoring (traceweaver_v1.py:196-243) --------------------
    def _score_sequential(self, in_span, in_ep, out_eps, stack) -> float:
        cost = 0.0
        prev_ep, prev_t = in_ep, in_span.start_mus
        for ep, span in zip(out_eps, stack):
            cost += self._edge_cost(prev_ep, ep, prev_t, span.start_mus)
            prev_ep, prev_t = ep, span.end_mus
        cost += self._edge_cost(prev_ep, in_ep, prev_t, in_span.end_mus)
        return cost

    def _score_parallel(self, in_span, in_ep, out_eps, stack) -> float:
        return sum(
            self._edge_cost(in_ep, ep, float(in_span.start_mus), float(span.start_mus))
            for ep, span in zip(out_eps, stack)
        )

    # -- DFS top-K enumeration (traceweaver_v2.py:32-100) ------------------
    def _topk_assignments(self, in_span, in_ep, out_eps, out_span_partitions,
                          k) -> List[Tuple[float, List[Span]]]:
        heap: List[Tuple[float, int, List[Span]]] = []
        counter = [0]

        def dfs(stack: List[Span]):
            depth = len(stack)
            if depth == len(out_eps):
                self.per_span_candidates[in_span.GetId()] = (
                    self.per_span_candidates.get(in_span.GetId(), 0) + 1
                )
                score = (self._score_parallel(in_span, in_ep, out_eps, stack)
                         if self.parallel else
                         self._score_sequential(in_span, in_ep, out_eps, stack))
                counter[0] += 1
                heapq.heappush(heap, (score, counter[0], list(stack)))
                if len(heap) > k:
                    heapq.heappop(heap)
                return
            ep = out_eps[depth]
            last_end = (in_span.start_mus if depth == 0 or self.parallel
                        else stack[-1].end_mus)
            for s in out_span_partitions[ep]:
                if s.start_mus < in_span.start_mus:
                    continue
                if s.start_mus > in_span.end_mus:
                    break  # partitions sorted by start
                if s.end_mus > in_span.end_mus:
                    continue
                if not self.parallel and s.start_mus < last_end:
                    continue
                dfs(stack + [s])

        dfs([])
        return sorted(((sc, st) for sc, _, st in heap), key=lambda x: -x[0])

    # -- windowed MWIS conflict resolution (traceweaver_v2.py:187-241) -----
    @staticmethod
    def _resolve_mis(batch: List[List[Tuple[float, List[Span]]]]):
        adj: Dict[Tuple[int, int], set] = {}
        weight: Dict[Tuple[int, int], float] = {}
        used_by: Dict[Tuple, List[Tuple[int, int]]] = {}
        for i, cands in enumerate(batch):
            for c, (score, stack) in enumerate(cands):
                node = (i, c)
                adj[node] = set()
                weight[node] = MIS_WEIGHT_OFFSET + score
                for c0 in range(c):
                    adj[node].add((i, c0))
                    adj[(i, c0)].add(node)
                for span in stack:
                    used_by.setdefault(span.GetId(), []).append(node)
        for nodes in used_by.values():
            for a in nodes:
                for b in nodes:
                    if a[0] != b[0]:
                        adj[a].add(b)
                        adj[b].add(a)
        if not weight:
            return [None] * len(batch)
        chosen, _ = exact_mwis(adj, weight)
        result: List[Optional[List[Span]]] = [None] * len(batch)
        for (i, c) in chosen:
            result[i] = batch[i][c][1]
        return result

    # -- plugin entry ------------------------------------------------------
    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments, invocation_graph=None):
        assert len(in_span_partitions) == 1
        self.parallel = bool(parallel) or method == "MaxScoreBatchParallel"
        self.instrumented_hops = instrumented_hops
        self.true_assignments = true_assignments
        self.per_span_candidates = {
            key: 0 for ep in out_span_partitions
            for key in true_assignments[ep]
        }

        in_ep, in_spans = next(iter(in_span_partitions.items()))
        out_eps = get_out_eps_in_order(out_span_partitions)
        # working copies consumed as assignments commit
        pool = {ep: list(spans) for ep, spans in out_span_partitions.items()}

        all_assignments: Dict[str, Dict] = {ep: {} for ep in out_eps}
        not_best_count = 0
        cnt_unassigned = 0

        def commit(in_span, stack: Optional[List[Span]]):
            nonlocal cnt_unassigned
            if stack is None:
                for ep in out_eps:
                    all_assignments[ep][in_span.GetId()] = NA
                cnt_unassigned += 1
                return
            for ep, span in zip(out_eps, stack):
                all_assignments[ep][in_span.GetId()] = span.GetId()
                pool[ep].remove(span)

        if method == "MaxScore":
            # V1: per-span greedy argmax, spans consumed immediately
            for cnt, in_span in enumerate(in_spans):
                if cnt % BATCH_SIZE_DIST == 0:
                    self._estimate_dists(
                        in_span_partitions, out_span_partitions, out_eps,
                        cnt, min(len(in_spans), cnt + BATCH_SIZE_DIST))
                top = self._topk_assignments(in_span, in_ep, out_eps, pool, 1)
                commit(in_span, top[0][1] if top else None)
            return all_assignments

        # V2: top-K heaps + windowed exact MWIS
        batch: List[List[Tuple[float, List[Span]]]] = []
        batch_spans: List[Span] = []
        for cnt, in_span in enumerate(in_spans):
            if cnt % BATCH_SIZE_DIST == 0:
                self._estimate_dists(
                    in_span_partitions, out_span_partitions, out_eps,
                    cnt, min(len(in_spans), cnt + BATCH_SIZE_DIST))
            top = self._topk_assignments(in_span, in_ep, out_eps, pool, TOP_K)
            batch.append(top)
            batch_spans.append(in_span)
            if len(batch) == BATCH_SIZE_MIS or cnt == len(in_spans) - 1:
                resolved = self._resolve_mis(batch)
                for in_sp, cands, stack in zip(batch_spans, batch, resolved):
                    if stack is None or not cands:
                        not_best_count += 1
                    elif [s.GetId() for s in cands[0][1]] != [s.GetId() for s in stack]:
                        not_best_count += 1
                    commit(in_sp, stack)
                batch, batch_spans = [], []

        return (all_assignments, not_best_count, len(in_spans),
                self.per_span_candidates)
