"""Thread-based baselines (vPath, SOSP'09 lineage).

- :class:`VPath` — flatten all spans to request/response events, sweep once
  in time order keeping the latest in-flight incoming span; client request
  events attach to it. Mimics inference for thread-serialized processing
  (reference: src/trace_reconstructor/ports/python/algorithms/vpath.py:36-89).
- :class:`VPathOld` — per-endpoint pointer sweep: the next outgoing span
  after each incoming span's start and before the next incoming span's start
  (reference: algorithms/vpath_old.py:1-31).
"""

from __future__ import annotations

from dataclasses import dataclass

from traceweaver_tpu.spans import NA


@dataclass
class _Event:
    trace_id: str
    sid: str
    time_mus: float
    span_kind: str
    event_kind: str  # "request" | "response"
    ep: str
    sort_key: int


class VPath:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes

    def _parent_of(self, trace_id, in_span_partitions):
        for spans in in_span_partitions.values():
            for span in spans:
                if span.trace_id == trace_id:
                    return (span.trace_id, span.sid)
        return None

    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments):
        assert len(in_span_partitions) == 1
        events = []
        for ep, spans in in_span_partitions.items():
            for s in spans:
                events.append(_Event(s.trace_id, s.sid, s.start_mus, s.span_kind, "request", ep, 1))
                events.append(_Event(s.trace_id, s.sid, s.start_mus + s.duration_mus, s.span_kind, "response", ep, 4))
        for ep, spans in out_span_partitions.items():
            for s in spans:
                events.append(_Event(s.trace_id, s.sid, s.start_mus, s.span_kind, "request", ep, 2))
                events.append(_Event(s.trace_id, s.sid, s.start_mus + s.duration_mus, s.span_kind, "response", ep, 3))
        events.sort(key=lambda e: (float(e.time_mus), e.sort_key))

        _, in_spans = next(iter(in_span_partitions.items()))
        all_assignments = {
            ep: {(s.trace_id, s.sid): NA for s in in_spans}
            for ep in out_span_partitions
        }

        latest_incoming = None
        for event in events:
            if event.span_kind == "server":
                if event.event_kind == "request":
                    latest_incoming = (event.trace_id, event.sid)
                else:
                    latest_incoming = None
            elif event.span_kind == "client":
                if event.event_kind == "request":
                    if latest_incoming is not None:
                        all_assignments[event.ep][latest_incoming] = (event.trace_id, event.sid)
                else:
                    parent = self._parent_of(event.trace_id, in_span_partitions)
                    if parent is not None:
                        latest_incoming = parent
        return all_assignments


class VPathOld:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes

    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments):
        assert len(in_span_partitions) == 1
        for part in in_span_partitions.values():
            part.sort(key=lambda s: float(s.start_mus))
        for part in out_span_partitions.values():
            part.sort(key=lambda s: float(s.start_mus))

        _, in_spans = next(iter(in_span_partitions.items()))
        all_assignments = {
            ep: {(s.trace_id, s.sid): NA for s in in_spans}
            for ep in out_span_partitions
        }

        for ep, out_spans in out_span_partitions.items():
            j = 0
            for i, in_span in enumerate(in_spans):
                while j < len(out_spans) and float(out_spans[j].start_mus) < float(in_span.start_mus):
                    j += 1
                if j >= len(out_spans):
                    break
                is_last = i == len(in_spans) - 1
                if float(out_spans[j].start_mus) >= float(in_span.start_mus) and (
                    is_last or float(out_spans[j].start_mus) < float(in_spans[i + 1].start_mus)
                ):
                    all_assignments[ep][in_span.GetId()] = out_spans[j].GetId()
                    j += 1
        return all_assignments
