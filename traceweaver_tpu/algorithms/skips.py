"""Water-filling allocation of the per-endpoint skip budget across windows.

The reference spreads each endpoint's global skip budget (|in| - |out|,
traceweaver_v3.py:972) across time windows by water-filling
(``TallySkipSpans``/``WaterFill``, traceweaver_v3.py:853-989): windows with
fewer existing outgoing spans get skip slots first, raising every window's
``existing + skips`` toward a common water level, each window capped at its
expected span count; any leftover budget is spilled into windows that still
have capacity. The DFS then draws skip spans from the window a candidate
falls in (``FetchSkipFromWindow``, :820-842).

Here the same allocation feeds the per-(window, endpoint) ``skip_cap``
column capacity of the OT solve (:func:`..weaver_tpu.solve_windows`):
windows are the solver's perfect-cut windows, "existing" is the endpoint's
candidate count in the window's time range (the same rows the packer uses),
and "expected" is the window's incoming-span count.

First deliberate deviation: the reference's per-window cap mixes sorted and
unsorted indices (``expected_spans[i] - sorted_existing_spans[i]``,
traceweaver_v3.py:900-902) — harmless there because every window's expected
count is the constant ``batch_size_mis``. Our windows have varying sizes,
so the cap is computed with consistently aligned indices (the intended
semantics). The second deviation (exact budget conservation) is documented
at the level search in :func:`water_fill`.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def water_fill(existing: np.ndarray, expected: np.ndarray,
               budget: float) -> np.ndarray:
    """Allocate ``budget`` skip slots across windows by water-filling.

    Args:
      existing: [n] count of real candidate spans per window.
      expected: [n] window's incoming-span count (allocation cap is
        ``max(expected - existing, 0)``).
      budget: global skip budget for this endpoint (``|in| - |out|``).

    Returns [n] float allocation, summing to
    ``min(budget, sum(max(expected - existing, 0)))`` when budget > 0.
    """
    n = len(existing)
    alloc = np.zeros(n)
    if budget <= 0 or n == 0:
        return alloc
    existing = np.asarray(existing, dtype=np.float64)
    expected = np.asarray(expected, dtype=np.float64)
    cap = np.maximum(expected - existing, 0.0)

    # Second deliberate deviation: the reference's level search iterates
    # windows in *descending* existing-count order and can over-allocate
    # (exceed the budget) whenever the break condition never fires while
    # some window sits above the level — harmless there because its skip
    # slots are upper bounds the DFS may ignore. We solve the intended
    # problem exactly: the unique water level L with
    # spend(L) = sum_j min(max(L - existing_j, 0), cap_j) = budget.
    def spend(level: float) -> float:
        return float(np.minimum(np.maximum(level - existing, 0.0), cap).sum())

    hi = float((existing + cap).max())
    if spend(hi) <= budget:
        return cap.copy()  # budget exceeds total capacity: fill everything
    lo = float(existing.min())
    for _ in range(64):
        mid = 0.5 * (lo + hi)
        if spend(mid) > budget:
            hi = mid
        else:
            lo = mid
    frac = np.minimum(np.maximum(lo - existing, 0.0), cap)
    alloc = np.floor(frac)

    # distribute the integer remainder one slot at a time to the windows
    # with the lowest current level that still have capacity (the
    # reference's leftover spill, traceweaver_v3.py:905-914)
    remaining = int(budget - alloc.sum())
    if remaining > 0:
        level = existing + alloc
        headroom = alloc < cap
        order = np.argsort(level, kind="stable")
        for w in order:
            if remaining <= 0:
                break
            if headroom[w]:
                alloc[w] += 1
                remaining -= 1
    return alloc


def water_fill_skip_caps(
    windows: List[Tuple[int, int]],
    ranges: np.ndarray,          # [B, E, 2] candidate index ranges
    n_in: int,
    out_counts: List[int],       # per endpoint, |out|
) -> np.ndarray:
    """Per-(window, endpoint) skip capacities from water-filled budgets.

    Returns [B, E] float32. Endpoints with no slack (budget <= 0) get zero
    rows (the solver still grants window-local slack where a window has
    fewer candidates than incoming spans — feasibility, not budget).
    """
    B = len(windows)
    E = len(out_counts)
    expected = np.array([hi - lo for lo, hi in windows], dtype=np.float64)
    caps = np.zeros((B, E), dtype=np.float32)
    for e in range(E):
        budget = n_in - out_counts[e]
        if budget <= 0:
            continue
        existing = (ranges[:, e, 1] - ranges[:, e, 0]).astype(np.float64)
        caps[:, e] = water_fill(existing, expected, float(budget))
    return caps
