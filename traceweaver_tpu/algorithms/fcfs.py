"""First-come-first-serve baseline.

The i-th incoming span maps to the i-th outgoing span at every endpoint
(reference: src/trace_reconstructor/ports/python/algorithms/fcfs.py:1-26).
"""

from __future__ import annotations

from traceweaver_tpu.spans import NA


class FCFS:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes
        self.instrumented_hops = []
        self.true_assignments = None

    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments):
        assert len(in_span_partitions) == 1
        self.instrumented_hops = instrumented_hops
        self.true_assignments = true_assignments
        _, in_spans = next(iter(in_span_partitions.items()))
        all_assignments = {ep: {} for ep in out_span_partitions}
        for ind, in_span in enumerate(in_spans):
            for j, (ep, out_spans) in enumerate(out_span_partitions.items()):
                if ind >= len(out_spans):
                    all_assignments[ep][in_span.GetId()] = NA
                elif (j + 1) in instrumented_hops:
                    all_assignments[ep][in_span.GetId()] = true_assignments[ep][in_span.GetId()]
                else:
                    all_assignments[ep][in_span.GetId()] = out_spans[ind].GetId()
        return all_assignments
