"""Reconstruction algorithms behind the reference's plugin contract.

Every algorithm is a class with ``__init__(all_spans, all_processes)`` and a
``FindAssignments(method, process, in_span_partitions, out_span_partitions,
parallel, instrumented_hops, true_assignments, ...)`` method returning
``{out_ep: {in_span_id: out_span_id}}`` (reference:
src/trace_reconstructor/ports/python/algorithms/README.md:16-53).

:func:`make_predictors` reproduces the reference executor's 11-entry,
index-selected registry (reference executor.py:888-902), with the
TPU solver registered at the TraceWeaverV3 slots (8, 9, 10).
"""

def _unavailable(module_name):
    class _Unavailable:
        def __init__(self, *args, **kwargs):
            pass

        def FindAssignments(self, *args, **kwargs):
            raise NotImplementedError(
                f"traceweaver_tpu.algorithms.{module_name} is not available "
                "in this build"
            )

    _Unavailable.__name__ = f"Unavailable[{module_name}]"
    return _Unavailable


from traceweaver_tpu.algorithms.fcfs import FCFS  # noqa: F401,E402
from traceweaver_tpu.algorithms.arrival_order import ArrivalOrder  # noqa: F401
from traceweaver_tpu.algorithms.vpath import VPath, VPathOld  # noqa: F401
from traceweaver_tpu.algorithms.wap5 import WAP5  # noqa: F401


def make_predictors(all_spans, all_processes):
    """The ordered (method_name, instance) registry, index-compatible with
    the reference (0..10). Indices:

    0 MaxScoreBatch (V2)               1 MaxScoreBatchParallel (V2)
    2 MaxScore (V1)                    3 WAP5
    4 FCFS                             5 ArrivalOrder
    6 vPathOld                         7 vPath
    8 MaxScoreBatchParallelWithoutIterations (TPU solver)
    9 MaxScoreBatchParallel (TPU solver)
    10 MaxScoreBatchSubsetWithSkips (TPU solver)
    """
    try:
        from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
    except ImportError:  # solver not built yet in this checkout
        WeaverExact = _unavailable("weaver_exact")
    try:
        from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    except ImportError:
        WeaverTPU = _unavailable("weaver_tpu")

    return [
        ("MaxScoreBatch", WeaverExact(all_spans, all_processes)),
        ("MaxScoreBatchParallel", WeaverExact(all_spans, all_processes)),
        ("MaxScore", WeaverExact(all_spans, all_processes)),
        ("WAP5", WAP5(all_spans, all_processes)),
        ("FCFS", FCFS(all_spans, all_processes)),
        ("ArrivalOrder", ArrivalOrder(all_spans, all_processes)),
        ("vPathOld", VPathOld(all_spans, all_processes)),
        ("vPath", VPath(all_spans, all_processes)),
        ("MaxScoreBatchParallelWithoutIterations", WeaverTPU(all_spans, all_processes)),
        ("MaxScoreBatchParallel", WeaverTPU(all_spans, all_processes)),
        ("MaxScoreBatchSubsetWithSkips", WeaverTPU(all_spans, all_processes)),
    ]
