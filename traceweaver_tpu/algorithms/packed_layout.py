"""Canonical channel layout of the packed solver output block.

Every fleet/packed entry point returns one int32 tensor whose LAST axis
multiplexes the per-span solver outputs (``weaver_tpu._pack_solver_outputs``).
The channel indices used to live as magic ``0``/``1``/``2``/``3`` literals
duplicated across the ``weaver_tpu`` and ``fleet`` decoders — a silent
corruption hazard the moment anyone grows the block (exactly what the
confidence channels below did). This module is now the single source of
truth; twlint rule TW008 (docs/ANALYSIS.md) flags raw channel-index
subscripts on packed blocks anywhere else.

Base layout (historical, byte-identical to the pre-confidence program)::

    [B, E, W, N_FIXED + topk]
      channel CH_ASSIGN   (0)   assign       — column index per incoming span
                                               (M = skip, -1 = unassigned)
      channel CH_NOT_BEST (1)   not_best     — OT choice differs from the row
                                               argmax (bool as int32)
      channel CH_FEAS     (2)   feas_count   — feasible candidates per row
      channels CH_TOPK..        topk columns — plan-mass-ranked alternatives
                                               (-1 below MIN_TOPK_MASS)

Confidence extension (``confidence=True`` static arg — an opt-in program
variant; the default block above is untouched)::

    [..., N_FIXED + topk + N_CONF]
      channel ch_margin(topk)   margin_q  — top1-top2 row score margin,
                                            fixed-point x CONF_SCALE
      channel ch_entropy(topk)  entropy_q — entropy (nats) of the row's
                                            entropic-OT conditional
                                            softmax(S/eps), x CONF_SCALE

The per-window sweep-convergence flag is NOT a channel: it rides its own
``[B]`` bool array so compaction can fetch O(B) bytes (PR 3).
"""

from __future__ import annotations

from typing import Dict, Optional

#: fixed (non-topk) channel indices of the packed block
CH_ASSIGN = 0
CH_NOT_BEST = 1
CH_FEAS = 2
#: first top-k column channel
CH_TOPK = 3
#: number of fixed channels before the top-k block
N_FIXED = 3
#: extra trailing channels under the confidence program variant
N_CONF = 2
#: fixed-point scale of the quantized confidence channels (int32 = value
#: x CONF_SCALE, saturating — 3 decimal digits is plenty for log-margin
#: and nat-entropy magnitudes)
CONF_SCALE = 1000.0


def n_channels(topk: int, confidence: bool = False) -> int:
    """Last-axis width of the packed block for a given ``topk``."""
    return N_FIXED + topk + (N_CONF if confidence else 0)


def ch_margin(topk: int) -> int:
    return N_FIXED + topk


def ch_entropy(topk: int) -> int:
    return N_FIXED + topk + 1


def topk_of(block_channels: int, confidence: bool = False) -> int:
    """Recover ``topk`` from a block's channel count."""
    return block_channels - N_FIXED - (N_CONF if confidence else 0)


def split_packed(block, confidence: bool = False,
                 topk: Optional[int] = None) -> Dict[str, object]:
    """Named views of a packed block's channels (no copies).

    Returns ``assign`` (int32), ``not_best`` (bool), ``feas`` (int32),
    ``topk_cols`` (int32 ``[..., topk]``), and — under the confidence
    variant — ``margin_q`` / ``entropy_q`` (int32, fixed-point
    ``x CONF_SCALE``). ``topk`` is inferred from the channel count when
    not given.
    """
    n_ch = block.shape[-1]
    if topk is None:
        topk = topk_of(n_ch, confidence)
    out = dict(
        assign=block[..., CH_ASSIGN],
        not_best=block[..., CH_NOT_BEST].astype(bool),
        feas=block[..., CH_FEAS],
        topk_cols=block[..., CH_TOPK:CH_TOPK + topk],
    )
    if confidence:
        out["margin_q"] = block[..., ch_margin(topk)]
        out["entropy_q"] = block[..., ch_entropy(topk)]
    return out
