"""Arrival-order baseline.

FCFS for the first endpoint; each subsequent endpoint's spans are matched in
the completion order of the previous endpoint's spans (reference:
src/trace_reconstructor/ports/python/algorithms/arrival_order.py:4-65).
"""

from __future__ import annotations

import numpy as np

from traceweaver_tpu.spans import NA
from traceweaver_tpu.metrics.accuracy import get_out_eps_in_order


class ArrivalOrder:
    def __init__(self, all_spans, all_processes):
        self.all_spans = all_spans
        self.all_processes = all_processes

    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments):
        assert len(in_span_partitions) == 1
        all_assignments = {ep: {} for ep in out_span_partitions}
        in_eps = list(in_span_partitions.keys())
        out_eps = get_out_eps_in_order(out_span_partitions)
        in_spans = in_span_partitions[in_eps[0]]

        out_spans = None
        for i in range(1, len(out_span_partitions) + 1):
            if i == 1:
                out_spans = out_span_partitions[out_eps[0]]
                ep_key = out_eps[0]
            else:
                prev = out_spans
                target = out_span_partitions[out_eps[i - 1]]
                order = list(np.argsort([s.start_mus + s.duration_mus for s in prev]))
                if len(prev) <= len(target):
                    order = order[: len(target)]
                    order.extend(range(len(prev), len(target)))
                else:
                    order = [x for x in order if x < len(target)]
                out_spans = [target[j] for j in order]
                ep_key = out_eps[i - 1]

            for ind, in_span in enumerate(in_spans):
                if ind >= len(out_spans):
                    all_assignments[ep_key][in_span.GetId()] = NA
                else:
                    all_assignments[ep_key][in_span.GetId()] = out_spans[ind].GetId()
        return all_assignments
