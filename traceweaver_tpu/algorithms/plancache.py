"""Amortized plan cache: fitted GMM/plan parameters carried across rounds.

Every solve round used to pay a serial host stage before the first device
dispatch: per-service distribution fitting (``timing.from_samples_gmm``
BIC sweeps inside ``estimate_edge_params`` / ``bootstrap_distributions``,
and the per-micro-batch ``refit_from_assignments`` carried-dist update in
the streaming hot path). The fitted result is a pure function of the
observed spans, which change slowly — so recomputing it every round is
the one remaining host hot path (ROADMAP item 2, PROFILE_r05 0.39% MFU).

:class:`PlanCache` makes the fitted plan a first-class artifact:

- **keyed** per service (``FleetItem.plan_key`` — the campaign runner
  uses ``"store:svc"`` because service names repeat across graphs);
- **admitted** from whatever fit ran anyway: a cold ``_prepare`` fit, a
  stream refit, an out-of-band adapt refit, or the decoded on-device
  refit tables of a two-pass EM dispatch (``dists_from_tables`` — the
  device already computed the refit; the cache just keeps it);
- **consulted** before the next fit: a hit skips the host fit entirely
  (``plan_find_assignments(skip_fit=True)``) and collapses a two-pass
  EM solve to a single warm pass, same as the existing ``warm_dists``
  contract;
- **invalidated** by the drift watcher: the adapt controller's rung
  transitions (refit scheduled / fallback / refit failed) fire
  ``invalidate_cb`` for exactly the drifting service — targeted refit,
  not cadence refit;
- **admission-gated** in the stream: only a plan fitted from a full
  window of evidence freezes (:func:`admissible`,
  ``TW_PLAN_MIN_SAMPLES``) — thin windows keep their per-window refit
  so the warm-start feedback loop and the PSI drift sensor stay
  stationary;
- **checkpointed**: ``state()``/``from_state()`` ride the service
  ``state_dict`` through the PR 1 checkpoint path, so kill/resume with
  a warm cache stays byte-identical.

``TW_PLAN_CACHE=0`` is the kill switch: ``lookup`` always misses and
``admit`` is a no-op, restoring pre-cache behavior byte-identically.

Counters are attribute increments on the instance (lint-exempt under
TW007) mirrored to ``tw_plan_cache_total{event}`` so ``/metrics`` and
the campaign ledger both see hit/miss/admit/invalidate rates.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs as _knobs

_OBS_PLAN = _get_registry().counter(
    "tw_plan_cache_total",
    "plan-cache events (hit/miss/admit/invalidate)",
    labels=("event",))


def enabled() -> bool:
    """Master switch (``TW_PLAN_CACHE``, default on). Off = every lookup
    misses and every admit drops, byte-identical to pre-cache behavior."""
    return _knobs.get_bool("TW_PLAN_CACHE")


def admissible(n_samples: int) -> bool:
    """Is a plan fitted from ``n_samples`` window spans trustworthy
    enough to FREEZE? The streaming admission bar
    (``TW_PLAN_MIN_SAMPLES``, default 64): a small-sample fit frozen in
    place starves the warm-start feedback loop (the carried statistics
    stop tracking per-window jitter) and quantizes the solver's
    confidence stream into a handful of atoms — with only a few
    confidence values per window, the PR 12 drift watcher's rolling PSI
    over those atoms is sampling noise, and the chaos-adapt leg
    reproduces the resulting false excursions walking the controller
    into fallback BEFORE the real shift. Fits from a full window of
    evidence are both accurate enough to hold and smooth enough for the
    PSI sensor to stay stationary, so only those amortize."""
    return int(n_samples) >= _knobs.get_int("TW_PLAN_MIN_SAMPLES")


class PlanCache:
    """Per-service fitted-plan store with hit/miss/invalidate telemetry.

    Values are the solver's ``dists`` dicts — ``{(parent_ep, child_ep):
    EdgeDist}`` with plain numpy arrays inside — exactly what
    ``plan_find_assignments`` fits and ``solve_fleet`` packs, and plain
    pickle material for checkpoints. The cache never mutates a stored
    dict; admission replaces the entry wholesale, so a concurrent reader
    of the old plan keeps a consistent snapshot."""

    def __init__(self):
        self._dists: Dict[str, Dict] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.invalidations = 0

    def lookup(self, key: str) -> Optional[Dict]:
        """Fitted dists for ``key``, or None (miss / disabled)."""
        if not enabled():
            return None
        with self._lock:
            dists = self._dists.get(key)
            if dists is None:
                self.misses += 1
                _OBS_PLAN.inc(1.0, event="miss")
                return None
            self.hits += 1
            _OBS_PLAN.inc(1.0, event="hit")
            return dists

    def admit(self, key: str, dists: Optional[Dict]) -> None:
        """Store a freshly fitted plan (no-op when disabled or empty)."""
        if not enabled() or not dists:
            return
        with self._lock:
            self._dists[key] = dists
            self.admissions += 1
            _OBS_PLAN.inc(1.0, event="admit")

    def invalidate(self, key: Optional[str] = None) -> None:
        """Drop one service's plan (or everything when ``key`` is None).
        Counted even when the key was absent — the drift watcher's
        intent to refit is the signal being measured."""
        with self._lock:
            if key is None:
                self._dists.clear()
            else:
                self._dists.pop(key, None)
            self.invalidations += 1
            _OBS_PLAN.inc(1.0, event="invalidate")

    def __len__(self) -> int:
        with self._lock:
            return len(self._dists)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "admissions": self.admissions,
                "invalidations": self.invalidations,
                "entries": len(self._dists),
            }

    # -- checkpoint surface (stream/checkpoint.py: plain pickle material)

    def state(self) -> Dict:
        with self._lock:
            return {
                "dists": dict(self._dists),
                "counters": {
                    "hits": self.hits,
                    "misses": self.misses,
                    "admissions": self.admissions,
                    "invalidations": self.invalidations,
                },
            }

    @classmethod
    def from_state(cls, state: Optional[Dict]) -> "PlanCache":
        cache = cls()
        if not state:
            return cache
        cache._dists = dict(state.get("dists", {}))
        c = state.get("counters", {})
        cache.hits = int(c.get("hits", 0))
        cache.misses = int(c.get("misses", 0))
        cache.admissions = int(c.get("admissions", 0))
        cache.invalidations = int(c.get("invalidations", 0))
        return cache
