"""The flagship TPU solver: windowed Sinkhorn assignment over score tensors.

This replaces the reference TraceWeaverV3 stack — per-span DFS candidate
enumeration (traceweaver_v3.py:292-351), top-K heaps, and a per-window
maximum-weight-independent-set ILP solved by Gurobi
(traceweaver_v3.py:1395-1419) — with a dense, branch-free formulation that
maps onto TPU vector units:

1. **Perfect-cut windowing** (host): incoming spans are segmented wherever
   the running max of end-times clears the next start — candidate sets of
   different segments are provably disjoint (the tensor analogue of
   traceweaver_v3.py:1020-1078 ``CreateWindows2``/``PerfectCut``) — then
   capped to a maximum window size and padded to a common width.
2. **Masked score tensors** (device): for each window and each outgoing
   endpoint in invocation-DAG topological order, a score matrix
   ``S[i, j] = log p(delay)`` under the learnt per-edge mixture, masked by
   timing containment and DAG-precedence feasibility (replacing the DFS
   pruning rules, traceweaver_v3.py:315-351).
3. **Entropic OT**: a Sinkhorn solve per (window, endpoint) with a
   budgeted *skip column* (capacity = the window's |in|-|out| slack,
   reference skip-budget semantics traceweaver_v3.py:972) and a dummy row
   absorbing unused columns. One-to-one conflicts are resolved by transport
   marginals instead of an independent-set ILP.
4. **Greedy peel rounding** to hard assignments; DAG consistency by
   sequential conditioning: each endpoint's chosen completion times feed
   the successor endpoints' score matrices inside one ``lax.scan``
   (replacing ``ScoreAssignmentAsPerInvocationGraph``,
   traceweaver_v1.py:259-361).
5. **EM iteration**: after a full pass, per-edge delay GMMs are refit from
   the assignments (BIC 1..5 components, traceweaver_v3.py:706-818) and the
   solve repeats (traceweaver_v3.py:1152-1229).

Everything between (2) and (4) is jitted and vmapped over windows; the
window axis is the sharding axis for multi-device runs
(see :mod:`traceweaver_tpu.parallel.mesh`).
"""

from __future__ import annotations

import math
import warnings
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

# the packed entry points donate their window tensors (HBM-peak buffers);
# backends without aliasing support fall back to a copy and warn per call —
# pure noise at per-chunk dispatch rates on the CPU stand-in
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

import jax
import jax.numpy as jnp
import networkx as nx

from traceweaver_tpu.algorithms import packed_layout as _layout
from traceweaver_tpu.algorithms import timing
from traceweaver_tpu.algorithms.skips import water_fill_skip_caps
from traceweaver_tpu.algorithms.timing import MAX_COMPONENTS, EdgeDist
from traceweaver_tpu.metrics.accuracy import get_out_eps_in_order
from traceweaver_tpu.obs import profile as _obs_profile
from traceweaver_tpu.obs import quality as _quality
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.ops.pallas_sinkhorn import assign_topk
from traceweaver_tpu.ops.precision import (
    precision_from_env,
    score_itemsize,
    validate_precision,
)
from traceweaver_tpu.ops.scores import mixture_logpdf, pair_scores
from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.runtime.bucketing import pow2_bucket
from traceweaver_tpu.spans import NA, SKIP, Span, SpanArray

NEG = -1.0e9
SKIP_MARGIN = 4.0    # log-space margin a real candidate must beat to avoid skip
SKIP_FLOOR = -60.0   # skip score floor so candidate-less rows still take skip
MIN_TOPK_MASS = 1e-3  # top-K fallback candidates need at least this plan mass
# Perfect-cut segments are solved whole (global one-to-one marginals) up to
# this cap; only beyond it do we fall back to capped sub-windows, which can
# double-assign an outgoing span across the artificial boundary. 1024 keeps
# the dense [W, M] score block ≤ ~8 MB — comfortably VMEM-tileable.
DEFAULT_MAX_WINDOW = 1024
DEFAULT_TOPK = 5
# Per-dispatch element budget (~f32 elements of [B, W, M] score blocks kept
# live at once). Bounds HBM while letting one dispatch cover a whole solve:
# round trips through the device tunnel cost ~100 ms each, so fewer, fatter
# dispatches win over per-size-class ones.
CHUNK_ELEMS = 1 << 26
# Merging a smaller window size class into the next larger one trades
# padding FLOPs for one fewer device round trip; merge while the extra
# padded area (elements) stays under this budget (~a round trip's worth of
# VPU work for this pipeline).
MERGE_ELEMS = 1 << 24

# obs mirror of the per-service solver ledger (docs/OBSERVABILITY.md):
# WeaverTPU.stats keeps its field names (executor merges, bench reads);
# every accumulating update below ALSO lands here so the scrape surface
# covers the per-service fallback/baseline path, not just the fleet.
_OBS_SOLVER = _get_registry().counter(
    "tw_solver_ledger_total",
    "per-service WeaverTPU solve ledger mirror (stage seconds, "
    "analytic op/byte estimates)",
    labels=("key",))


def _stat_add(stats: Dict[str, float], key: str, val: float) -> None:
    _OBS_SOLVER.inc(val, key=key)
    stats[key] = stats.get(key, 0.0) + val


# ---------------------------------------------------------------------------
# Device solve (jit + vmap over windows)
# ---------------------------------------------------------------------------

def _solve_windows_impl(
    in_start,    # [B, W] f32 (window-rebased µs)
    in_end,      # [B, W]
    in_valid,    # [B, W] bool
    out_start,   # [B, E, M]
    out_end,     # [B, E, M]
    out_valid,   # [B, E, M] bool
    skip_cap,    # [B, E] f32 — skip-column capacity per endpoint
    force_skip,  # [B, E, W] bool — true-skips ablation; normally all False
    param_idx,   # [B] int32 — row into the stacked per-problem param tables
    pred_masks,  # [P, E, E] bool — pred[e, p]: p is a primary DAG pred of e
    root_masks,  # [P, E] bool — e additionally scored from the incoming start
    is_lasts,    # [P, E] bool — add the return-edge (e -> in) term
    edge_wts, edge_mus, edge_sds,  # [P, E, E, K] mixture params for (p -> e)
    in_wts, in_mus, in_sds,        # [P, E, K] params for (in -> e)
    ret_wts, ret_mus, ret_sds,     # [P, E, K] params for (e -> in)
    epsilon: float,
    n_sinkhorn: int,
    topk: int,
    n_sweeps: int,
    sinkhorn_tol: float,
    max_preds: int = 0,
    max_succs: int = 0,
    precision: str = "f32",
    pallas: bool = True,
    confidence: bool = False,
):
    """Shared body of :func:`solve_windows` / :func:`solve_windows_fleet`.

    Every window carries ``param_idx`` — the row of the DAG-structure and
    distribution tables it scores against — so windows of *different
    services* batch into one device program (SURVEY §2.8: services become
    a batch dimension). The single-service entry points pass P=1 and a
    zero index vector.

    ``max_preds`` / ``max_succs`` (static; 0 = no bound) cap the number of
    DAG neighbours an endpoint's score matrix sums over: instead of
    evaluating a masked [W, M, K] mixture block for EVERY other endpoint
    (O(E^2) blocks per sweep — the dominant score-build cost in the r04
    profile), neighbour indices are gathered host-known-tight so only the
    real DAG edges (in-degree is ~1 in these call graphs) pay for
    evaluation. Identical sums: gathered entries are exactly the
    mask-true entries, padding contributes 0.0.

    ``precision`` (static; see :mod:`traceweaver_tpu.ops.precision`) is
    the score-BLOCK storage precision: the mixture terms are evaluated
    and summed in f32 (accumulation stays full-precision), then each
    endpoint's assembled OT block is stored at ``precision`` before the
    Sinkhorn loop streams it — under ``"bf16"`` the block the sweep
    ``while_loop`` re-reads every iteration is half the bytes. The
    Sinkhorn potentials, marginals, convergence test, transport plan,
    and rounding margins stay f32 throughout (``ops/sinkhorn.py`` /
    ``ops/pallas_sinkhorn.py``); ``"f32"`` compiles the historical
    program bit-identically (no cast is inserted at all).

    ``confidence`` (static; default False — the historical program, no
    trace change at all) additionally exports two quantized per-row
    quality channels derived from the assembled OT block the solver
    already holds in registers (:mod:`traceweaver_tpu.algorithms.packed_layout`):
    the top1-top2 row score margin and the entropy of the row's
    entropic-OT conditional ``softmax(S/epsilon)`` — the plan-derived
    confidence signals the quality telemetry path
    (:mod:`traceweaver_tpu.obs.quality`) reduces per span. A distinct
    static-arg program variant, so enabling it costs ONE compile and
    every later solve runs from cache (zero recompiles, test-pinned).
    """
    precision = validate_precision(precision)
    B, E, M = out_start.shape
    W = in_start.shape[1]
    POS = -NEG
    n_pred = max_preds if 0 < max_preds < E else E
    n_succ = max_succs if 0 < max_succs < E else E

    def solve_one(in_s, in_e, in_v, o_s, o_e, o_v, cap, fskip, pi):
        # this window's problem tables (one gather per table; P is tiny)
        pred_mask = pred_masks[pi]      # [E, E]
        root_mask = root_masks[pi]      # [E]
        is_last = is_lasts[pi]          # [E]
        edge_wt, edge_mu, edge_sd = edge_wts[pi], edge_mus[pi], edge_sds[pi]
        in_wt, in_mu, in_sd = in_wts[pi], in_mus[pi], in_sds[pi]
        ret_wt, ret_mu, ret_sd = ret_wts[pi], ret_mus[pi], ret_sds[pi]

        # neighbour index tables, mask-true entries first (stable argsort
        # keeps ascending endpoint order, matching the full-sum order)
        pred_idx = jnp.argsort(~pred_mask, axis=1)[:, :n_pred]      # [E, n_pred]
        pred_ok = jnp.take_along_axis(pred_mask, pred_idx, axis=1)
        succ_mask = pred_mask.T                                     # [E, E]
        succ_idx = jnp.argsort(~succ_mask, axis=1)[:, :n_succ]
        succ_ok = jnp.take_along_axis(succ_mask, succ_idx, axis=1)

        def ep_step(state, e):
            chosen_end, chosen_start, backward = state
            pmask = pred_mask[e]   # [E] — predecessors of e
            smask = pred_mask[:, e]  # [E] — successors of e

            pred_end = jnp.where(pmask[:, None], chosen_end, NEG)  # [E, W]
            t_pred = jnp.max(pred_end, axis=0)                     # [W]
            has_pred = jnp.any(pmask)
            t_prev = jnp.where(has_pred, t_pred, in_s)

            # successor starts (valid only when that successor picked a real
            # span; skip/none carry POS = no constraint)
            succ_start = jnp.where(smask[:, None], chosen_start, POS)  # [E, W]
            t_succ = jnp.min(succ_start, axis=0)                       # [W]

            # --- score matrix -------------------------------------------
            S = jnp.where(
                root_mask[e],
                pair_scores(in_s, o_s[e], in_wt[e], in_mu[e], in_sd[e]),
                jnp.zeros((W, M), dtype=in_s.dtype),
            )

            def pred_term(j):
                p = pred_idx[e, j]
                sc = pair_scores(chosen_end[p], o_s[e],
                                 edge_wt[e, p], edge_mu[e, p], edge_sd[e, p])
                return jnp.where(pred_ok[e, j], sc, 0.0)

            S = S + jnp.sum(jax.vmap(pred_term)(jnp.arange(n_pred)), axis=0)

            def succ_term(j):
                # edge (e -> u): delay succ_start_u - out_end_e
                u = succ_idx[e, j]
                delta = chosen_start[u][:, None] - o_e[e][None, :]
                sc = mixture_logpdf(delta, edge_wt[u, e], edge_mu[u, e],
                                    edge_sd[u, e])
                active = succ_ok[e, j] & backward
                ok = (chosen_start[u] < POS / 2)[:, None]
                return jnp.where(active & ok, sc, 0.0)

            S = S + jnp.sum(jax.vmap(succ_term)(jnp.arange(n_succ)), axis=0)

            ret_delta = in_e[:, None] - o_e[e][None, :]
            S = S + jnp.where(
                is_last[e],
                mixture_logpdf(ret_delta, ret_wt[e], ret_mu[e], ret_sd[e]),
                0.0,
            )

            # --- feasibility --------------------------------------------
            feas = (
                in_v[:, None]
                & o_v[e][None, :]
                & (in_s[:, None] <= o_s[e][None, :])
                & (o_e[e][None, :] <= in_e[:, None])
                & (t_prev[:, None] <= o_s[e][None, :])
                & ~fskip[e][:, None]
            )
            feas = feas & (
                ~backward | (o_e[e][None, :] <= t_succ[:, None])
            )
            S = jnp.where(feas, S, NEG)
            feas_count = jnp.sum(feas, axis=1).astype(jnp.int32)

            # --- skip column --------------------------------------------
            row_best = jnp.max(S, axis=1)
            skip_score = jnp.maximum(row_best - SKIP_MARGIN, SKIP_FLOOR)
            skip_score = jnp.where(fskip[e], 0.0, skip_score)
            skip_score = jnp.where(in_v, skip_score, NEG)
            Sfull = jnp.concatenate([S, skip_score[:, None]], axis=1)  # [W, M+1]
            if precision == "bf16":
                # store the assembled OT block at the score precision:
                # this is the array the Sinkhorn loop streams twice per
                # iteration, and the argmax below compares the SAME
                # values the solver actually ranked. f32 accumulation
                # already happened (the term sums above). Each row is
                # centered at its best feasible score BEFORE the
                # downcast: entropic OT plans are invariant to per-row
                # additive constants (they fold into the f potentials),
                # and DAG-conditioned rows carry common offsets of
                # hundreds of log units (e.g. the return-edge term) that
                # would otherwise eat bf16's ~8-bit mantissa — the
                # margins BETWEEN candidates, the part the solve must
                # resolve, sit near 0 after centering. Masked entries
                # stay at NEG (an all-infeasible row centers at 0).
                row_ref = jnp.where(row_best > NEG / 2, row_best, 0.0)
                Sfull = jnp.where(Sfull > NEG / 2,
                                  Sfull - row_ref[:, None], NEG)
                Sfull = Sfull.astype(jnp.bfloat16)

            # --- marginals (dummy row absorbs surplus columns) ----------
            # marginals stay f32 regardless of the score precision (S is
            # the f32 accumulated block; counts must be exact)
            n_rows = jnp.sum(in_v).astype(S.dtype)
            n_cols = jnp.sum(o_v[e]).astype(S.dtype)
            cap_e = jnp.maximum(cap[e], jnp.maximum(n_rows - n_cols, 0.0))
            row_marg = jnp.concatenate(
                [in_v.astype(S.dtype),
                 jnp.maximum(n_cols + cap_e - n_rows, 0.0)[None]]
            )
            col_marg = jnp.concatenate([o_v[e].astype(S.dtype), cap_e[None]])
            S_ot = jnp.concatenate(
                [Sfull, jnp.zeros((1, M + 1), dtype=Sfull.dtype)], axis=0
            )

            # fused persistent-sweep block: Sinkhorn + greedy rounding +
            # small-k peel in ONE Pallas kernel on TPU — the [W, M] plan
            # never leaves VMEM between the three stages (off-TPU: the
            # same composition as separate jitted stages, including the
            # topk_peel that replaced lax.top_k's lane sort — sort.47 /
            # wrapped_reduce-window in the r05 profiles). Candidate
            # columns with negligible plan mass (timing-infeasible:
            # score NEG -> plan ~ 0) come back as -1 so cross-window
            # duplicate resolution can never fall back onto an
            # infeasible out-span.
            col_valid = jnp.concatenate([o_v[e], (cap_e > 0)[None]])
            assign, tk = assign_topk(
                S_ot, row_marg, col_marg, in_v, col_valid, cap_e, W,
                epsilon=epsilon, n_iters=n_sinkhorn, tol=sinkhorn_tol,
                topk=topk, min_topk_mass=MIN_TOPK_MASS,
                allow_pallas=pallas)

            # chosen completion: skip passes the predecessor time through
            real = (assign >= 0) & (assign < M)
            safe = jnp.clip(assign, 0, M - 1)
            chosen_end = chosen_end.at[e].set(
                jnp.where(real, o_e[e][safe], t_prev)
            )
            chosen_start = chosen_start.at[e].set(
                jnp.where(real, o_s[e][safe], POS)
            )

            not_best = (assign != jnp.argmax(Sfull, axis=1)) & in_v
            outs = (assign, tk.astype(jnp.int32), not_best, feas_count)
            if confidence:
                # plan-derived quality channels, from the SAME assembled
                # block the solve ranked (f32 view; under bf16 the rows
                # are already best-centered, so margins keep mantissa).
                # Quantized fixed-point so they ride the existing int32
                # packed transfer — no extra D2H stream, no f32 output.
                Sf = Sfull.astype(jnp.float32)
                top2 = jax.lax.top_k(Sf, 2)[0]               # [W, 2]
                margin = jnp.maximum(top2[:, 0] - top2[:, 1], 0.0)
                # entropic-OT row conditional: the Sinkhorn plan row is
                # softmax((S + g)/eps) up to the column potentials; the
                # unconstrained conditional softmax(S/eps) is the
                # potential-free row entropy (0 = one-hot certainty)
                logits = jnp.where(Sf > NEG / 2, Sf / epsilon, NEG)
                p = jax.nn.softmax(logits, axis=1)
                ent = -jnp.sum(jnp.where(p > 0.0,
                                         p * jnp.log(p + 1e-30), 0.0),
                               axis=1)
                scale = _layout.CONF_SCALE
                margin_q = (jnp.minimum(margin, 2.0e6) * scale).astype(
                    jnp.int32)
                ent_q = (jnp.maximum(ent, 0.0) * scale).astype(jnp.int32)
                outs = outs + (margin_q, ent_q)
            return (chosen_end, chosen_start, backward), outs

        def sweep_body(carry):
            (chosen_end, chosen_start, _), outs, sweep, _ = carry
            prev_assign = outs[0]
            state = (chosen_end, chosen_start, sweep > 0)
            state, outs = jax.lax.scan(ep_step, state, jnp.arange(E))
            # a backward sweep (sweep >= 1) that reproduces the previous
            # sweep's assignments is a Gauss-Seidel fixed point: chosen
            # start/end times are functions of the assignments, so every
            # later sweep recomputes identical outputs — exiting early
            # changes nothing (exactness, not approximation)
            changed = jnp.any(outs[0] != prev_assign) | (sweep == 0)
            # outs ride the carry (overwritten each sweep) so only the final
            # sweep's outputs are ever materialized — stacking [n_sweeps, ...]
            # then slicing would cost n_sweeps x the output memory
            return state, outs, sweep + 1, changed

        def sweep_cond(carry):
            _, _, sweep, changed = carry
            return (sweep < n_sweeps) & changed

        init_state = (
            jnp.zeros((E, W), dtype=in_s.dtype),
            jnp.full((E, W), POS, dtype=in_s.dtype),
            jnp.asarray(False),
        )
        init_outs = (
            jnp.zeros((E, W), dtype=jnp.int32),
            jnp.zeros((E, W, topk), dtype=jnp.int32),
            jnp.zeros((E, W), dtype=bool),
            jnp.zeros((E, W), dtype=jnp.int32),
        )
        if confidence:
            init_outs = init_outs + (
                jnp.zeros((E, W), dtype=jnp.int32),   # margin_q
                jnp.zeros((E, W), dtype=jnp.int32),   # entropy_q
            )
        # one traced sweep body (compile surface independent of n_sweeps)
        _, outs, _, changed = jax.lax.while_loop(
            sweep_cond, sweep_body,
            (init_state, init_outs, jnp.asarray(0, jnp.int32),
             jnp.asarray(True)))
        # converged <=> the last executed sweep reproduced its predecessor's
        # assignments, i.e. the outputs are a Gauss-Seidel fixed point that
        # no further sweep budget could change. Exported per window so the
        # host can redispatch ONLY unconverged windows with the remaining
        # sweeps (convergence compaction, algorithms/fleet.py) — under vmap
        # this whole loop runs until the SLOWEST window converges, with
        # converged windows' updates select-masked into no-ops but still
        # burning VPU cycles.
        return outs + (~changed,)

    return jax.vmap(solve_one)(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, param_idx,
    )


@partial(jax.jit, static_argnames=("epsilon", "n_sinkhorn", "topk", "n_sweeps",
                                   "sinkhorn_tol", "max_preds", "max_succs",
                                   "precision", "pallas"))
def solve_windows(
    in_start, in_end, in_valid, out_start, out_end, out_valid,
    skip_cap, force_skip,
    pred_mask,   # [E, E] bool
    root_mask,   # [E] bool
    is_last,     # [E] bool
    edge_wt, edge_mu, edge_sd,  # [E, E, K]
    in_wt, in_mu, in_sd,        # [E, K]
    ret_wt, ret_mu, ret_sd,     # [E, K]
    epsilon: float = 1.0,
    n_sinkhorn: int = 40,
    topk: int = DEFAULT_TOPK,
    n_sweeps: int = 5,
    sinkhorn_tol: float = 0.0,
    max_preds: int = 0,
    max_succs: int = 0,
    precision: str = "f32",
    pallas: bool = True,
):
    """Solve every window by Gauss-Seidel coordinate descent over endpoints.

    Sweep 0 conditions each endpoint only on its DAG predecessors (forward
    pass in topological order). Later sweeps re-solve each endpoint with
    both directions fixed — predecessor completion times below, successor
    start times above — recovering the joint coupling the reference gets
    from enumerating whole assignments (traceweaver_v1.py:259-361) without
    combinatorial search.

    Returns:
      assign     [B, E, W] int32 — column index per incoming span
                 (M = skip, -1 = unassigned)
      topk_cols  [B, E, W, topk] int32 — per-endpoint candidate ranking
      not_best   [B, E, W] bool — OT choice differs from row argmax
      feas_count [B, E, W] int32 — feasible candidates per row
    """
    B = in_start.shape[0]
    assign, tk, not_best, feas, _ = _solve_windows_impl(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip,
        jnp.zeros((B,), dtype=jnp.int32),
        pred_mask[None], root_mask[None], is_last[None],
        edge_wt[None], edge_mu[None], edge_sd[None],
        in_wt[None], in_mu[None], in_sd[None],
        ret_wt[None], ret_mu[None], ret_sd[None],
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk,
        n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
        max_preds=max_preds, max_succs=max_succs, precision=precision,
        pallas=pallas,
    )
    return assign, tk, not_best, feas


def _pack_solver_outputs(assign, tk, not_best, feas,
                         margin_q=None, entropy_q=None):
    """The single-transfer int32 layout ``[B, E, W, 3 + topk]`` (see
    :mod:`traceweaver_tpu.algorithms.packed_layout` — the single source
    of truth for the channel indices): ``CH_ASSIGN``, ``CH_NOT_BEST``,
    ``CH_FEAS``, then the topk columns, then (confidence variant only)
    the two quantized quality channels appended at the END so every
    historical channel index is unchanged.

    The per-window sweep-convergence flag is deliberately NOT a channel
    of this block any more: the fleet entry points return it as a
    separate ``[B]`` bool array so the convergence-compaction host step
    can fetch B bytes instead of blocking on the whole packed block
    (the ``copy-start`` D2H cost the r05 profile billed at parity with
    the sweep loops themselves)."""
    chans = [assign[..., None], not_best[..., None].astype(jnp.int32),
             feas[..., None], tk]
    if margin_q is not None:
        chans += [margin_q[..., None], entropy_q[..., None]]
    return jnp.concatenate(chans, axis=-1)


@partial(jax.jit, static_argnames=("epsilon", "n_sinkhorn", "topk", "n_sweeps",
                                   "sinkhorn_tol", "max_preds", "max_succs",
                                   "precision", "pallas"),
         donate_argnums=tuple(range(8)))
def solve_windows_packed(*args, epsilon: float = 1.0, n_sinkhorn: int = 40,
                         topk: int = DEFAULT_TOPK, n_sweeps: int = 5,
                         sinkhorn_tol: float = 0.0,
                         max_preds: int = 0, max_succs: int = 0,
                         precision: str = "f32",
                         pallas: bool = True):
    """:func:`solve_windows` with the outputs packed into one int32 tensor
    ``[B, E, W, 3+topk]`` (see :func:`_pack_solver_outputs`) so a solve
    costs a single device->host transfer instead of four. The window
    tensors (args 0-7) are donated: the dense [B, E, W, M] blocks are the
    solve's HBM peak and the caller always rebuilds them per dispatch."""
    B = args[0].shape[0]
    outs = _solve_windows_impl(
        *args[:8],
        jnp.zeros((B,), dtype=jnp.int32),
        *(a[None] for a in args[8:]),
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk,
        n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
        max_preds=max_preds, max_succs=max_succs, precision=precision,
        pallas=pallas,
    )
    return _pack_solver_outputs(*outs[:4])


def em_family_samples(assign, in_start, in_end, in_valid,
                      out_start, out_end, pred_mask, root_mask):
    """Per-edge delay samples for the three production refit families,
    extracted from hard assignments — the single definition shared by the
    fused single-device EM (:func:`solve_em_packed`) and the psum'd
    multi-device EM (:func:`traceweaver_tpu.parallel.mesh.em_step_sharded`),
    mirroring the host :func:`traceweaver_tpu.algorithms.timing.refit_from_assignments`
    (reference ``ComputeEpPairDistParams5``, traceweaver_v3.py:706-818):

    - ``(in -> e)``  chosen e start − incoming start, root endpoints;
    - ``(p -> e)``   chosen e start − chosen p end, DAG-primary edges;
    - ``(e -> in)``  incoming end − chosen e end, every endpoint.

    Returns ``(samples, mask)``, both ``[E + E*E + E, B*W]`` with rows in
    that family order (edge rows ``[e, p]`` row-major).
    """
    B, E, W = assign.shape
    M = out_start.shape[2]
    safe = jnp.clip(assign, 0, M - 1)
    ch_start = jnp.take_along_axis(out_start, safe, axis=2)   # [B, E, W]
    ch_end = jnp.take_along_axis(out_end, safe, axis=2)
    real = (assign >= 0) & (assign < M) & in_valid[:, None, :]

    # structure masks may be shared ([E]/[E, E]) or per-window
    # ([B, E]/[B, E, E] — the fleet path, where windows belong to
    # different services)
    rm = (root_mask if root_mask.ndim == 2
          else jnp.broadcast_to(root_mask[None], (B, E)))
    pm = (pred_mask if pred_mask.ndim == 3
          else jnp.broadcast_to(pred_mask[None], (B, E, E)))

    d_in = ch_start - in_start[:, None, :]                    # [B, E, W]
    m_in = real & rm[:, :, None]
    d_edge = ch_start[:, :, None, :] - ch_end[:, None, :, :]  # [B, E, Ep, W]
    m_edge = (real[:, :, None, :] & real[:, None, :, :]
              & pm[:, :, :, None])
    d_ret = in_end[:, None, :] - ch_end                       # [B, E, W]
    m_ret = real

    def rows(d, m, ne):
        return (jnp.moveaxis(d, 0, -2).reshape(ne, B * W),
                jnp.moveaxis(m, 0, -2).reshape(ne, B * W))

    di, mi = rows(d_in, m_in, E)
    de, me = rows(d_edge.reshape(B, E * E, W), m_edge.reshape(B, E * E, W),
                  E * E)
    dr, mr = rows(d_ret, m_ret, E)
    return (jnp.concatenate([di, de, dr], axis=0),
            jnp.concatenate([mi, me, mr], axis=0))


@partial(jax.jit, static_argnames=("epsilon", "n_sinkhorn", "topk", "n_sweeps",
                                   "sinkhorn_tol", "max_preds", "max_succs",
                                   "precision", "pallas"),
         donate_argnums=tuple(range(8)))
def solve_em_packed(
    in_start, in_end, in_valid, out_start, out_end, out_valid,
    skip_cap, force_skip, pred_mask, root_mask, is_last,
    edge_wt, edge_mu, edge_sd, in_wt, in_mu, in_sd,
    ret_wt, ret_mu, ret_sd,
    epsilon: float = 1.0, n_sinkhorn: int = 40,
    topk: int = DEFAULT_TOPK, n_sweeps: int = 5,
    sinkhorn_tol: float = 0.0,
    max_preds: int = 0, max_succs: int = 0,
    precision: str = "f32",
    pallas: bool = True,
):
    """Both EM iterations in ONE device dispatch.

    The reference's flagship runs two passes with a host-side BIC-GMM
    refit between them (traceweaver_v3.py:1152-1229 iteration loop,
    :706-818 refit); round 2 ran the same structure with the refit as a
    separate device dispatch, leaving the refit + second solve as extra
    host round trips (~44% of the warm solve through the device tunnel).
    Here pass 0, the three-family delay extraction, the in-graph BIC-GMM
    refit (:func:`traceweaver_tpu.ops.gmm.fit_gmm_in_graph`), and pass 1
    are one XLA program: the EM loop never leaves the device.

    Refit deviations from the host path, both documented and bounded by
    the parity harness: samples come from pass-0 per-window assignments
    (before cross-window duplicate resolution — identical unless a
    perfect-cut segment was split beyond ``max_window``), and the GMM EM
    uses the deterministic quantile init / fixed iteration count of the
    device fit rather than sklearn's k-means init.

    ``precision`` covers BOTH passes' score blocks; the delay-sample
    extraction and the in-graph BIC-GMM refit between them stay f32
    (the EM statistics are the accumulator state of this pipeline).
    """
    B, E, M = out_start.shape
    W = in_start.shape[1]
    K = in_wt.shape[1]

    assign0, _, _, _ = solve_windows(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, pred_mask, root_mask, is_last,
        edge_wt, edge_mu, edge_sd, in_wt, in_mu, in_sd,
        ret_wt, ret_mu, ret_sd,
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk, n_sweeps=n_sweeps,
        sinkhorn_tol=sinkhorn_tol, max_preds=max_preds, max_succs=max_succs,
        precision=precision, pallas=pallas,
    )

    # --- M-step samples: the three production edge families --------------
    samples, smask = em_family_samples(
        assign0, in_start, in_end, in_valid, out_start, out_end,
        pred_mask, root_mask)                                 # [Ne, B*W]

    from traceweaver_tpu.ops.gmm import fit_gmm_in_graph

    prior_w = jnp.concatenate([in_wt, edge_wt.reshape(E * E, K), ret_wt])
    prior_mu = jnp.concatenate([in_mu, edge_mu.reshape(E * E, K), ret_mu])
    prior_sd = jnp.concatenate([in_sd, edge_sd.reshape(E * E, K), ret_sd])
    w, mu, sd = fit_gmm_in_graph(samples, smask, prior_w, prior_mu, prior_sd,
                                 max_k=K)

    return solve_windows_packed(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, pred_mask, root_mask, is_last,
        w[E:E + E * E].reshape(E, E, K), mu[E:E + E * E].reshape(E, E, K),
        sd[E:E + E * E].reshape(E, E, K),
        w[:E], mu[:E], sd[:E],
        w[E + E * E:], mu[E + E * E:], sd[E + E * E:],
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk, n_sweeps=n_sweeps,
        sinkhorn_tol=sinkhorn_tol, max_preds=max_preds, max_succs=max_succs,
        precision=precision, pallas=pallas,
    )


@partial(jax.jit, static_argnames=("epsilon", "n_sinkhorn", "topk", "n_sweeps",
                                   "sinkhorn_tol", "max_preds", "max_succs",
                                   "precision", "pallas", "confidence"),
         donate_argnums=tuple(range(8)))
def solve_windows_fleet(
    in_start, in_end, in_valid, out_start, out_end, out_valid,
    skip_cap, force_skip, param_idx,
    pred_masks, root_masks, is_lasts,
    edge_wts, edge_mus, edge_sds, in_wts, in_mus, in_sds,
    ret_wts, ret_mus, ret_sds,
    epsilon: float = 1.0, n_sinkhorn: int = 40,
    topk: int = DEFAULT_TOPK, n_sweeps: int = 5,
    sinkhorn_tol: float = 0.0,
    max_preds: int = 0, max_succs: int = 0,
    precision: str = "f32",
    pallas: bool = True,
    confidence: bool = False,
):
    """Multi-service :func:`solve_windows` with the packed int32 output
    (window tensors donated — see :func:`solve_windows_packed`).

    ``param_idx[b]`` selects the window's problem tables from the stacked
    ``[P, ...]`` arrays; windows of every service in a fleet ride one
    device dispatch (endpoint axes padded to the fleet max — padded
    endpoints have no valid columns, assign nothing, and pass predecessor
    times through, so they cannot disturb real endpoints).

    Returns ``(packed, converged)``: the ``[B, E, W, 3+topk]`` block
    (``confidence=True`` appends the two quantized quality channels —
    :mod:`traceweaver_tpu.algorithms.packed_layout`) plus the per-window
    sweep-fixed-point flags as a SEPARATE ``[B]`` bool array, so the
    convergence-compaction host step can fetch B bytes alone while the
    packed block streams D2H asynchronously."""
    outs = _solve_windows_impl(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, param_idx,
        pred_masks, root_masks, is_lasts,
        edge_wts, edge_mus, edge_sds, in_wts, in_mus, in_sds,
        ret_wts, ret_mus, ret_sds,
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk,
        n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
        max_preds=max_preds, max_succs=max_succs, precision=precision,
        pallas=pallas, confidence=confidence,
    )
    return _pack_solver_outputs(*outs[:-1]), outs[-1]


def _fleet_refit_tables(assign0, in_start, in_end, in_valid,
                        out_start, out_end, param_idx,
                        window_rows, window_valid, pred_masks, root_masks,
                        edge_wts, edge_mus, edge_sds,
                        in_wts, in_mus, in_sds, ret_wts, ret_mus, ret_sds):
    """Per-service three-family BIC-GMM refit from pass-0 assignments —
    the middle stage of :func:`solve_em_fleet`, shared with the
    standalone :func:`refit_fleet_params` dispatch the convergence-
    compacted flow uses (one definition, so the fused single program and
    the compacted multi-dispatch flow cannot drift). Returns the nine
    refit param tables reshaped to ``[P, ...]`` table layout."""
    B, E, W = assign0.shape
    P, _, K = in_wts.shape
    Ne = E + E * E + E
    Bmax = window_rows.shape[1]

    # family samples over the padded endpoint axis; per-window structure
    # masks so a window only feeds its own service's family rows
    samples, smask = em_family_samples(
        assign0, in_start, in_end, in_valid, out_start, out_end,
        pred_masks[param_idx], root_masks[param_idx])       # [Ne, B*W]

    fs = samples.reshape(Ne, B, W)[:, window_rows, :]       # [Ne, P, Bmax, W]
    fm = (smask.reshape(Ne, B, W)[:, window_rows, :]
          & window_valid[None, :, :, None])
    fleet_samples = jnp.moveaxis(fs, 1, 0).reshape(P * Ne, Bmax * W)
    fleet_mask = jnp.moveaxis(fm, 1, 0).reshape(P * Ne, Bmax * W)

    from traceweaver_tpu.ops.gmm import fit_gmm_in_graph

    prior_w = jnp.concatenate(
        [in_wts, edge_wts.reshape(P, E * E, K), ret_wts], axis=1
    ).reshape(P * Ne, K)
    prior_mu = jnp.concatenate(
        [in_mus, edge_mus.reshape(P, E * E, K), ret_mus], axis=1
    ).reshape(P * Ne, K)
    prior_sd = jnp.concatenate(
        [in_sds, edge_sds.reshape(P, E * E, K), ret_sds], axis=1
    ).reshape(P * Ne, K)
    w, mu, sd = fit_gmm_in_graph(fleet_samples, fleet_mask,
                                 prior_w, prior_mu, prior_sd, max_k=K)

    w, mu, sd = (a.reshape(P, Ne, K) for a in (w, mu, sd))
    return (
        w[:, E:E + E * E].reshape(P, E, E, K),
        mu[:, E:E + E * E].reshape(P, E, E, K),
        sd[:, E:E + E * E].reshape(P, E, E, K),
        w[:, :E], mu[:, :E], sd[:, :E],
        w[:, E + E * E:], mu[:, E + E * E:], sd[:, E + E * E:],
    )


@jax.jit
def refit_fleet_params(assign0, in_start, in_end, in_valid,
                       out_start, out_end, param_idx,
                       window_rows, window_valid, pred_masks, root_masks,
                       edge_wts, edge_mus, edge_sds,
                       in_wts, in_mus, in_sds, ret_wts, ret_mus, ret_sds):
    """Standalone refit dispatch for the convergence-compacted fleet flow
    (:mod:`traceweaver_tpu.algorithms.fleet`): the host merges pass-0
    assignments from the warm + compacted dispatches, then this single
    program produces the pass-1 tables — same nine-tuple, same math as
    the refit inside :func:`solve_em_fleet`."""
    return _fleet_refit_tables(
        assign0, in_start, in_end, in_valid, out_start, out_end,
        param_idx, window_rows, window_valid, pred_masks, root_masks,
        edge_wts, edge_mus, edge_sds,
        in_wts, in_mus, in_sds, ret_wts, ret_mus, ret_sds)


@partial(jax.jit, static_argnames=("epsilon", "n_sinkhorn", "topk", "n_sweeps",
                                   "sinkhorn_tol", "max_preds", "max_succs",
                                   "precision", "pallas", "confidence"),
         donate_argnums=tuple(range(8)))
def solve_em_fleet(
    in_start, in_end, in_valid, out_start, out_end, out_valid,
    skip_cap, force_skip, param_idx, window_rows, window_valid,
    pred_masks, root_masks, is_lasts,
    edge_wts, edge_mus, edge_sds, in_wts, in_mus, in_sds,
    ret_wts, ret_mus, ret_sds,
    epsilon: float = 1.0, n_sinkhorn: int = 40,
    topk: int = DEFAULT_TOPK, n_sweeps: int = 5,
    sinkhorn_tol: float = 0.0,
    max_preds: int = 0, max_succs: int = 0,
    precision: str = "f32",
    pallas: bool = True,
    confidence: bool = False,
):
    """Both EM iterations for a whole service fleet in ONE dispatch.

    The fleet analogue of :func:`solve_em_packed`: pass 0 over every
    service's windows, per-service three-family delay extraction, one
    batched BIC-GMM refit over the ``P*Ne`` family rows, then pass 1 —
    the whole bench workload's EM never leaves the device and costs a
    single round trip through the tunnel. Returns ``(packed, converged)``
    like :func:`solve_windows_fleet` (the flags are pass 1's).

    ``window_rows``/``window_valid`` ([P, Bmax] int32/bool) list each
    service's window rows in the fleet batch (the packer emits services as
    contiguous row blocks). The per-service refit matrix is built by
    GATHERING those rows — ``[P*Ne, Bmax*W]`` — rather than broadcasting
    the full sample matrix per service (``[P*Ne, B*W]``): the window axis
    a service's EM sees shrinks from the whole fleet's to its own, so the
    refit block stays ~P× smaller and scales to exp5-size fleets.
    """
    assign0, _, _, _, _ = _solve_windows_impl(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, param_idx,
        pred_masks, root_masks, is_lasts,
        edge_wts, edge_mus, edge_sds, in_wts, in_mus, in_sds,
        ret_wts, ret_mus, ret_sds,
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk,
        n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
        max_preds=max_preds, max_succs=max_succs, precision=precision,
        pallas=pallas,
    )

    tables = _fleet_refit_tables(
        assign0, in_start, in_end, in_valid, out_start, out_end,
        param_idx, window_rows, window_valid, pred_masks, root_masks,
        edge_wts, edge_mus, edge_sds,
        in_wts, in_mus, in_sds, ret_wts, ret_mus, ret_sds)
    return solve_windows_fleet(
        in_start, in_end, in_valid, out_start, out_end, out_valid,
        skip_cap, force_skip, param_idx,
        pred_masks, root_masks, is_lasts,
        *tables,
        epsilon=epsilon, n_sinkhorn=n_sinkhorn, topk=topk,
        n_sweeps=n_sweeps, sinkhorn_tol=sinkhorn_tol,
        max_preds=max_preds, max_succs=max_succs, precision=precision,
        pallas=pallas, confidence=confidence,
    )


# ---------------------------------------------------------------------------
# Host-side problem packing
# ---------------------------------------------------------------------------

def columnar_enabled() -> bool:
    """``TW_COLUMNAR=0`` kills the columnar host pack path, restoring the
    per-span object walk (the bit-identical pre-columnar flow — kept as
    the kill switch and the golden-parity reference). Read at call time,
    same discipline as every other knob."""
    return _knobs.get_bool("TW_COLUMNAR")


def in_columns(in_spans: List[Span]) -> SpanArray:
    """Columns of a sorted incoming partition (one O(n) conversion — the
    ingest → solver boundary; everything after is array work)."""
    return SpanArray.from_spans(in_spans)


def out_columns(out_span_partitions: Dict[str, List[Span]],
                out_eps: List[str]) -> Dict[str, SpanArray]:
    """Ascending-start columns per outgoing endpoint — the exact
    permutation of the object path's ``sorted(spans, key=s.start_mus)``
    (stable), so candidate slices and id-table gathers line up with the
    object path element for element."""
    return {
        ep: SpanArray.from_spans(out_span_partitions[ep]).sorted_by_start()
        for ep in out_eps
    }


def perfect_cut_windows(in_spans: List[Span], max_size: int) -> List[Tuple[int, int]]:
    """Segment sorted incoming spans at points where every earlier span has
    ended (candidate sets provably disjoint), capping segment length.

    Returns [start, end) index pairs.
    """
    n = len(in_spans)
    windows = []
    seg_start = 0
    running_max_end = -math.inf
    for i in range(n):
        s = float(in_spans[i].start_mus)
        if i > seg_start and running_max_end <= s:
            windows.append((seg_start, i))
            seg_start = i
        elif i - seg_start >= max_size:
            windows.append((seg_start, i))
            seg_start = i
        running_max_end = max(running_max_end, float(in_spans[i].start_mus)
                              + float(in_spans[i].duration_mus))
    if seg_start < n:
        windows.append((seg_start, n))
    return windows


def perfect_cut_windows_cols(cols: SpanArray,
                             max_size: int) -> List[Tuple[int, int]]:
    """Columnar :func:`perfect_cut_windows`: the running-max-of-ends cut
    condition never resets across cuts, so the perfect cut points are a
    pure function of the global end-time cummax — one vectorized pass —
    and the ``max_size`` cap then splits each perfect segment into
    fixed-stride chunks (exactly the positions the sequential loop's
    ``i - seg_start >= max_size`` check fires at). Same [start, end)
    pairs as the object version on the same sorted spans (parity-tested).
    """
    n = len(cols)
    if n == 0:
        return []
    cut = np.zeros(n, dtype=bool)
    if n > 1:
        cut[1:] = np.maximum.accumulate(cols.end)[:-1] <= cols.start[1:]
    bounds = [0, *np.flatnonzero(cut).tolist(), n]
    windows: List[Tuple[int, int]] = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        if a == b:
            continue
        for s in range(a, b, max_size):
            windows.append((s, min(s + max_size, b)))
    return windows


def scatter_window_span_stats(windows, not_best, feas,
                              span_not_best, span_cands) -> None:
    """Per-span confidence reductions over a packed window batch, written
    into the caller's ``[n_in]`` arrays in place: a span is "not best"
    when any endpoint's OT choice overrode the row argmax, and its
    candidate count is the product of per-endpoint feasible counts.

    Vectorized over the packed window index (one fancy-gather per batch
    instead of a Python loop per span) — decode sits on the dispatch
    pipeline's critical path, so per-span Python work here would gate the
    whole fleet solve.
    """
    if not windows:
        return
    w_of = np.concatenate(
        [np.full(hi - lo, b) for b, (lo, hi) in enumerate(windows)])
    i_of = np.concatenate([np.arange(hi - lo) for lo, hi in windows])
    pos = np.concatenate([np.arange(lo, hi) for lo, hi in windows])
    span_not_best[pos] = not_best[w_of, :, i_of].any(axis=1)
    # int64 accumulator matches np.prod's platform-int promotion in the
    # scalar form this replaces
    span_cands[pos] = np.maximum(
        feas[w_of, :, i_of], 1).astype(np.int64).prod(axis=1)


def _bucket(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (bounds jit recompilation variants).
    Wraps the shared :func:`traceweaver_tpu.runtime.bucketing.pow2_bucket`
    with the sublane-tile minimum the dispatch shapes want."""
    return pow2_bucket(n, minimum)


def _window_bounds(windows: List[Tuple[int, int]], start: np.ndarray,
                   end: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-window [first in start, max in end] bounds from columns: the
    end-time segment maxes ride ONE ``np.maximum.reduceat`` over the
    interleaved (lo, hi) boundary list instead of a Python max() per
    window."""
    B = len(windows)
    los = np.fromiter((lo for lo, _ in windows), np.int64, B)
    his = np.fromiter((hi for _, hi in windows), np.int64, B)
    idx = np.empty(2 * B, dtype=np.int64)
    idx[0::2] = los
    idx[1::2] = his
    n = start.shape[0]
    if idx[-1] >= n:  # reduceat indices must be < n; the last segment
        seg = np.maximum.reduceat(end, idx[:-1])  # runs to the end anyway
    else:
        seg = np.maximum.reduceat(end, idx)
    return start[los], seg[0::2]


def candidate_ranges(
    in_spans: List[Span],
    windows: List[Tuple[int, int]],
    out_eps: List[str],
    out_starts_np: Dict[str, np.ndarray],
    in_cols: Optional[SpanArray] = None,
) -> np.ndarray:
    """[B, E, 2] candidate index ranges: per window and endpoint, the slice
    of that endpoint's time-sorted out-spans starting within the window's
    [first in start, last in end] bound (the tensor analogue of the
    reference's per-endpoint binary-search cutoffs, traceweaver_v3.py:182-217).
    Single source of truth for both packing and the dispatch-size budget.

    Columnar (``TW_COLUMNAR``, default; or ``in_cols`` given): the window
    bounds come from the start/end columns and each endpoint's two
    cutoffs are ONE vectorized ``searchsorted`` over all windows — no
    per-window Python. The object loop below is the ``TW_COLUMNAR=0``
    reference; both produce identical int64 ranges (parity-tested).
    """
    if in_cols is not None or columnar_enabled():
        if in_cols is None:
            in_cols = in_columns(in_spans)
        if not windows:
            return np.zeros((0, len(out_eps), 2), dtype=np.int64)
        w_t0, w_t1 = _window_bounds(windows, in_cols.start, in_cols.end)
        ranges = np.zeros((len(windows), len(out_eps), 2), dtype=np.int64)
        for e, ep in enumerate(out_eps):
            starts = out_starts_np[ep]
            ranges[:, e, 0] = np.searchsorted(starts, w_t0, side="left")
            ranges[:, e, 1] = np.searchsorted(starts, w_t1, side="right")
        return ranges
    ranges = np.zeros((len(windows), len(out_eps), 2), dtype=np.int64)
    for b, (lo, hi) in enumerate(windows):
        w_t0 = float(in_spans[lo].start_mus)
        w_t1 = max(float(s.start_mus) + float(s.duration_mus)
                   for s in in_spans[lo:hi])
        for e, ep in enumerate(out_eps):
            starts = out_starts_np[ep]
            ranges[b, e, 0] = np.searchsorted(starts, w_t0, side="left")
            ranges[b, e, 1] = np.searchsorted(starts, w_t1, side="right")
    return ranges


class EndpointIds:
    """Decode-time id map for one endpoint of a packed batch: instead of
    materializing a ``[None] * (B * M)`` Python list at pack time (the
    object path's layout — B·M object slots, mostly None), the columnar
    path keeps the endpoint's sorted id TABLE plus each window row's
    ``(r0, count)`` candidate range and gathers ids only when the decode
    actually needs them — one fancy-index gather per endpoint per batch.
    """

    __slots__ = ("table", "r0", "count", "M")

    def __init__(self, table: np.ndarray, r0: np.ndarray, count: np.ndarray,
                 M: int) -> None:
        self.table = table      # [n_ep_spans] object — ascending-start ids
        self.r0 = r0            # [B] int64 — first candidate per window row
        self.count = count      # [B] int64 — candidates per window row
        self.M = M              # padded column count

    def rows(self, n: int) -> "EndpointIds":
        """First ``n`` window rows (the fleet packer's row truncation)."""
        return EndpointIds(self.table, self.r0[:n], self.count[:n], self.M)

    def gather(self) -> np.ndarray:
        """Materialize the object path's ``[B * M]`` id layout (None in
        empty slots) — same indexing contract (``b * M + j``), produced
        by one table gather instead of per-span list writes."""
        B, M = self.r0.shape[0], self.M
        j = np.arange(M)
        valid = j[None, :] < self.count[:, None]
        src = np.where(valid, self.r0[:, None] + j[None, :], 0)
        out = np.full((B, M), None, dtype=object)
        out[valid] = self.table[src[valid]]
        return out.reshape(B * M)


@dataclass
class PackedProblem:
    """Dense window tensors + the index maps to decode device output.

    ``out_ids`` holds, per endpoint, either the object path's flat
    ``[B * M]`` id list or the columnar path's :class:`EndpointIds`
    (id-table + ranges, gathered at decode time);
    :meth:`out_id_array` is the single accessor decode reads through.

    ``devcols`` (``TW_DEVCOLS`` fleet path only) replaces the six big
    window tensors with ring-slot INDEX arrays plus the owning
    :class:`~traceweaver_tpu.ops.devcols.ColumnRing` handles — the
    window tensors themselves are assembled on device from the resident
    columns (:func:`traceweaver_tpu.ops.devcols.assemble_windows`) and
    never exist in host memory. ``arrays`` then carries only the small
    host-shipped tensors (skip_cap/force_skip) and the problem tables.
    """

    arrays: Dict[str, np.ndarray]
    out_eps: List[str]
    windows: List[Tuple[int, int]]
    in_ids: List  # [n_in] span ids, window order == original sort order
    out_ids: List  # per ep: [B*M] id list OR EndpointIds
    n_in: int
    devcols: Optional[Dict] = None

    @property
    def M(self) -> int:
        """Padded candidate-column count (the decode stride)."""
        if self.devcols is not None:
            return int(self.devcols["out_idx"].shape[2])
        return int(self.arrays["out_start"].shape[2])

    def out_id_array(self, e: int) -> np.ndarray:
        """[B * M] object array of candidate ids for endpoint ``e``."""
        col = self.out_ids[e]
        if isinstance(col, EndpointIds):
            return col.gather()
        ids = np.empty(len(col), dtype=object)
        ids[:] = col
        return ids

    def truncate_rows(self, n_rows: int) -> None:
        """Drop the power-of-two B padding from the id maps (the fleet
        packer slices every batch tensor to its exact window count; the
        id maps must follow so decode's ``b * M + j`` indexing stays
        aligned)."""
        M = self.M
        self.out_ids = [
            col.rows(n_rows) if isinstance(col, EndpointIds)
            else col[:n_rows * M]
            for col in self.out_ids
        ]


def _problem_tables(out_eps: List[str], E_pad: int,
                    dists: Dict[Tuple[str, str], EdgeDist], in_ep: str,
                    dag: Optional[nx.DiGraph],
                    parallel: bool) -> Dict[str, np.ndarray]:
    """DAG structure masks + distribution param tables of one problem —
    identical for the columnar and object pack paths (one definition, so
    the golden parity holds by construction on everything that is not a
    window tensor)."""
    E = len(out_eps)
    pred_mask = np.zeros((E_pad, E_pad), dtype=bool)
    root_mask = np.zeros((E_pad,), dtype=bool)
    is_last = np.zeros((E_pad,), dtype=bool)
    if parallel or dag is None:
        root_mask[:E] = True
    else:
        for e, ep in enumerate(out_eps):
            preds = timing.primary_pred_edges(dag, ep)
            if len(dag.in_edges(ep)) == 0 or in_ep in preds:
                root_mask[e] = True
            for p in preds:
                if p != in_ep and p in out_eps:
                    pred_mask[e, out_eps.index(p)] = True
        is_last[E - 1] = True

    K = MAX_COMPONENTS
    wide = EdgeDist.gaussian(0.0, 1e7)  # near-flat fallback for unseen edges

    def params_of(key) -> EdgeDist:
        return dists.get(key, wide)

    edge_wt = np.zeros((E_pad, E_pad, K), dtype=np.float32)
    edge_mu = np.zeros((E_pad, E_pad, K), dtype=np.float32)
    edge_sd = np.ones((E_pad, E_pad, K), dtype=np.float32)
    in_wt = np.zeros((E_pad, K), dtype=np.float32)
    in_mu = np.zeros((E_pad, K), dtype=np.float32)
    in_sd = np.ones((E_pad, K), dtype=np.float32)
    ret_wt = np.zeros((E_pad, K), dtype=np.float32)
    ret_mu = np.zeros((E_pad, K), dtype=np.float32)
    ret_sd = np.ones((E_pad, K), dtype=np.float32)
    for e, ep in enumerate(out_eps):
        d = params_of((in_ep, ep))
        in_wt[e], in_mu[e], in_sd[e] = d.weights, d.means, d.stds
        d = params_of((ep, in_ep))
        ret_wt[e], ret_mu[e], ret_sd[e] = d.weights, d.means, d.stds
        for p, pep in enumerate(out_eps):
            d = params_of((pep, ep))
            edge_wt[e, p], edge_mu[e, p], edge_sd[e, p] = d.weights, d.means, d.stds

    return dict(
        pred_mask=pred_mask, root_mask=root_mask, is_last=is_last,
        edge_wt=edge_wt, edge_mu=edge_mu, edge_sd=edge_sd,
        in_wt=in_wt, in_mu=in_mu, in_sd=in_sd,
        ret_wt=ret_wt, ret_mu=ret_mu, ret_sd=ret_sd,
    )


def dists_from_tables(out_eps: List[str], in_ep: str,
                      edge_wt, edge_mu, edge_sd,
                      in_wt, in_mu, in_sd,
                      ret_wt, ret_mu, ret_sd
                      ) -> Dict[Tuple[str, str], EdgeDist]:
    """Inverse of the ``_problem_tables`` packing: fitted param tables
    (one service's rows, refit order — the nine-tuple
    :func:`refit_fleet_params` returns) back into the solver's
    ``{(parent_ep, child_ep): EdgeDist}`` dict.

    Decodes EVERY family row over the true edges ``e < len(out_eps)``,
    including edges the refit saw no samples for — the in-graph fit
    keeps the prior params for empty rows (ops/gmm.fit_gmm_in_graph), so
    repacking the decoded dict through ``_problem_tables`` reproduces
    the device tables bit-exactly (f32 -> f64 -> f32 round-trips
    losslessly). That exactness is what lets the plan cache admit
    on-device refit results and stay byte-identical on the next solve."""
    def mk(w, m, s) -> EdgeDist:
        return EdgeDist(np.asarray(w, dtype=np.float64),
                        np.asarray(m, dtype=np.float64),
                        np.asarray(s, dtype=np.float64))

    dists: Dict[Tuple[str, str], EdgeDist] = {}
    for e, ep in enumerate(out_eps):
        dists[(in_ep, ep)] = mk(in_wt[e], in_mu[e], in_sd[e])
        dists[(ep, in_ep)] = mk(ret_wt[e], ret_mu[e], ret_sd[e])
        for p, pep in enumerate(out_eps):
            dists[(pep, ep)] = mk(edge_wt[e, p], edge_mu[e, p],
                                  edge_sd[e, p])
    return dists


def pack_problem(
    in_spans: List[Span],
    out_span_partitions: Dict[str, List[Span]],
    out_eps: List[str],
    dists: Dict[Tuple[str, str], EdgeDist],
    in_ep: str,
    dag: Optional[nx.DiGraph],
    force_skip_ids: Optional[Dict[str, set]] = None,
    max_window: int = DEFAULT_MAX_WINDOW,
    parallel: bool = False,
    windows: Optional[List[Tuple[int, int]]] = None,
    pad_w: Optional[int] = None,
    pad_b: Optional[int] = None,
    pad_m: Optional[int] = None,
    pad_e: Optional[int] = None,
    ranges: Optional[np.ndarray] = None,
    skip_caps: Optional[np.ndarray] = None,  # [len(windows), E] water-filled
    in_cols: Optional[SpanArray] = None,
    out_cols: Optional[Dict[str, SpanArray]] = None,
) -> PackedProblem:
    """Build the dense [B, ...] window tensors for :func:`solve_windows`.

    ``windows`` (index pairs into the sorted ``in_spans``) may be supplied to
    pack a subset; when omitted, perfect cuts over the whole stream are used.
    ``pad_w``/``pad_b``/``pad_m`` force the padded window width / batch size /
    candidate-column count (all still rounded up to powers of two) so every
    chunk of a solve shares one compiled variant. ``pad_e`` pads the endpoint
    axis (fleet packing: services share one dispatch at the fleet-max E;
    padded endpoints carry no valid columns, a false root/pred/last mask and
    unit-σ zero-weight params, so the solve ignores them).

    Two implementations behind one contract (byte-identical tensors,
    identical decode — the golden parity suite pins it):

    - **columnar** (``TW_COLUMNAR=1``, the default): window rows are
      strided slices of the partition's :class:`SpanArray` columns
      (``in_cols``/``out_cols``, converted here when the caller did not
      hand them over), candidate blocks are fancy-index gathers, and the
      id maps stay :class:`EndpointIds` tables resolved at decode time —
      no per-span Python anywhere in the fill;
    - **object** (``TW_COLUMNAR=0``): the original per-window span-object
      walk, kept verbatim as the kill switch and parity reference.
    """
    if columnar_enabled():
        return _pack_problem_columnar(
            in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
            force_skip_ids=force_skip_ids, max_window=max_window,
            parallel=parallel, windows=windows, pad_w=pad_w, pad_b=pad_b,
            pad_m=pad_m, pad_e=pad_e, ranges=ranges, skip_caps=skip_caps,
            in_cols=in_cols, out_cols=out_cols)
    return _pack_problem_objects(
        in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
        force_skip_ids=force_skip_ids, max_window=max_window,
        parallel=parallel, windows=windows, pad_w=pad_w, pad_b=pad_b,
        pad_m=pad_m, pad_e=pad_e, ranges=ranges, skip_caps=skip_caps)


def _pack_problem_columnar(
    in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
    force_skip_ids=None, max_window=DEFAULT_MAX_WINDOW, parallel=False,
    windows=None, pad_w=None, pad_b=None, pad_m=None, pad_e=None,
    ranges=None, skip_caps=None, in_cols=None, out_cols=None,
) -> PackedProblem:
    """Columnar :func:`pack_problem` body: every window tensor is filled
    by array slicing/gather over the partition columns. The per-span
    Python of the object path — ``[float(s.start_mus) for s in ...]`` per
    window per endpoint, an id-list write per candidate slot — becomes
    O(1) NumPy statements per endpoint, so pack cost scales with array
    size, not span-object count (the 0.39% MFU host stall of
    PROFILE_r05, docs/PERF.md "Columnar host path")."""
    E = len(out_eps)
    E_pad = max(E, pad_e or E)
    if in_cols is None:
        in_cols = in_columns(in_spans)
    if out_cols is None:
        out_cols = out_columns(out_span_partitions, out_eps)
    if windows is None:
        windows = perfect_cut_windows_cols(in_cols, max_window)
    n_windows = len(windows)
    B = _bucket(max(n_windows, pad_b or 1), minimum=1)
    W = _bucket(max(max(hi - lo for lo, hi in windows), pad_w or 1))

    if ranges is None:  # caller may pass precomputed rows (same helper)
        out_starts_np = {ep: out_cols[ep].start for ep in out_eps}
        ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np,
                                  in_cols=in_cols)
    M = _bucket(max(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)),
                    pad_m or 1))

    in_start = np.zeros((B, W), dtype=np.float32)
    in_end = np.zeros((B, W), dtype=np.float32)
    in_valid = np.zeros((B, W), dtype=bool)
    out_start = np.zeros((B, E_pad, M), dtype=np.float32)
    out_end = np.zeros((B, E_pad, M), dtype=np.float32)
    out_valid = np.zeros((B, E_pad, M), dtype=bool)
    skip_cap = np.zeros((B, E_pad), dtype=np.float32)
    force_skip = np.zeros((B, E_pad, W), dtype=bool)

    los = np.fromiter((lo for lo, _ in windows), np.int64, n_windows)
    his = np.fromiter((hi for _, hi in windows), np.int64, n_windows)
    n_w = his - los
    origins = in_cols.start[los]                       # [Bw] f64

    # incoming rows: one strided gather for the whole batch
    jw = np.arange(W)
    w_valid = jw[None, :] < n_w[:, None]               # [Bw, W]
    w_src = np.where(w_valid, los[:, None] + jw[None, :], 0)
    in_start[:n_windows][w_valid] = (
        in_cols.start[w_src] - origins[:, None])[w_valid]
    in_end[:n_windows][w_valid] = (
        in_cols.end[w_src] - origins[:, None])[w_valid]
    in_valid[:n_windows] = w_valid

    # candidate blocks: one gather per endpoint
    jm = np.arange(M)
    r0 = ranges[:, :, 0]                               # [Bw, E]
    m_w = ranges[:, :, 1] - r0                         # [Bw, E]
    out_ids: List[EndpointIds] = []
    for e, ep in enumerate(out_eps):
        cols = out_cols[ep]
        c_valid = jm[None, :] < m_w[:, e][:, None]     # [Bw, M]
        c_src = np.where(c_valid, r0[:, e][:, None] + jm[None, :], 0)
        out_start[:n_windows, e][c_valid] = (
            cols.start[c_src] - origins[:, None])[c_valid]
        out_end[:n_windows, e][c_valid] = (
            cols.end[c_src] - origins[:, None])[c_valid]
        out_valid[:n_windows, e] = c_valid
        # id map resolved at decode time: table + per-row ranges, padded
        # to the bucketed B so gather() reproduces the [B*M] layout
        r0_pad = np.zeros(B, dtype=np.int64)
        cnt_pad = np.zeros(B, dtype=np.int64)
        r0_pad[:n_windows] = r0[:, e]
        cnt_pad[:n_windows] = m_w[:, e]
        out_ids.append(EndpointIds(cols.ids, r0_pad, cnt_pad, M))

    # skip capacity: water-filled budget when provided (reference
    # TallySkipSpans semantics); the solver still grants window-local
    # slack max(rows - cols, 0) on device for feasibility
    if skip_caps is not None:
        skip_cap[:n_windows, :E] = skip_caps
    else:
        skip_cap[:n_windows, :E] = np.maximum(n_w[:, None] - m_w, 0)

    if force_skip_ids:
        in_ids_arr = in_cols.ids
        for e, ep in enumerate(out_eps):
            fs = force_skip_ids.get(ep, set())
            if not fs:
                continue
            for b in range(n_windows):
                lo, hi = int(los[b]), int(his[b])
                mask = np.fromiter((i in fs for i in in_ids_arr[lo:hi]),
                                   bool, hi - lo)
                n_forced = int(mask.sum())
                if n_forced:
                    force_skip[b, e, :hi - lo] = mask
                # every forced row needs skip capacity even when candidate
                # ranges inflated by neighbouring windows hide the slack
                skip_cap[b, e] = max(skip_cap[b, e], n_forced)

    arrays = dict(
        in_start=in_start, in_end=in_end, in_valid=in_valid,
        out_start=out_start, out_end=out_end, out_valid=out_valid,
        skip_cap=skip_cap, force_skip=force_skip,
        **_problem_tables(out_eps, E_pad, dists, in_ep, dag, parallel),
    )
    return PackedProblem(arrays=arrays, out_eps=out_eps, windows=windows,
                         in_ids=in_cols.ids, out_ids=out_ids,
                         n_in=len(in_cols))


def _pack_problem_devcols(
    in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
    in_slots, out_slots, ring_in, ring_out,
    force_skip_ids=None, max_window=DEFAULT_MAX_WINDOW, parallel=False,
    windows=None, pad_w=None, pad_b=None, pad_m=None, pad_e=None,
    ranges=None, skip_caps=None, in_cols=None, out_cols=None,
) -> PackedProblem:
    """Device-resident :func:`pack_problem` body (``TW_DEVCOLS``, fleet
    path): the SAME windowing, candidate ranges, skip caps, id maps, and
    problem tables as :func:`_pack_problem_columnar`, but instead of
    filling the six dense window tensors in host memory it emits int32
    ring-slot INDEX arrays (``in_idx [B, W]`` / ``out_idx [B, E, M]``,
    −1 = invalid) over the resident device columns
    (:mod:`traceweaver_tpu.ops.devcols`). The tensors themselves are
    assembled by on-device gathers at dispatch time — bit-identical to
    the host fill on the integral-µs timestamps the ring admits (the
    ``TW_DEVCOLS`` parity suite pins it).

    ``in_slots`` / ``out_slots[ep]`` map each sorted partition position
    to its live ring slot (``ColumnRing.resolve``); the caller resolved
    them before packing, so ineligible partitions never reach here."""
    E = len(out_eps)
    E_pad = max(E, pad_e or E)
    if in_cols is None:
        in_cols = in_columns(in_spans)
    if out_cols is None:
        out_cols = out_columns(out_span_partitions, out_eps)
    if windows is None:
        windows = perfect_cut_windows_cols(in_cols, max_window)
    n_windows = len(windows)
    B = _bucket(max(n_windows, pad_b or 1), minimum=1)
    W = _bucket(max(max(hi - lo for lo, hi in windows), pad_w or 1))

    if ranges is None:
        out_starts_np = {ep: out_cols[ep].start for ep in out_eps}
        ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np,
                                  in_cols=in_cols)
    M = _bucket(max(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)),
                    pad_m or 1))

    skip_cap = np.zeros((B, E_pad), dtype=np.float32)
    force_skip = np.zeros((B, E_pad, W), dtype=bool)
    in_idx = np.full((B, W), -1, dtype=np.int32)
    out_idx = np.full((B, E_pad, M), -1, dtype=np.int32)
    origin_in = np.zeros(B, dtype=np.int32)
    origin_out = np.zeros(B, dtype=np.int32)

    los = np.fromiter((lo for lo, _ in windows), np.int64, n_windows)
    his = np.fromiter((hi for _, hi in windows), np.int64, n_windows)
    n_w = his - los
    origins = in_cols.start[los]                       # [Bw] f64 absolute
    origin_in[:n_windows] = ring_in.rel32(origins)
    origin_out[:n_windows] = ring_out.rel32(origins)

    jw = np.arange(W)
    w_valid = jw[None, :] < n_w[:, None]               # [Bw, W]
    w_src = np.where(w_valid, los[:, None] + jw[None, :], 0)
    in_idx[:n_windows][w_valid] = in_slots[w_src][w_valid]

    jm = np.arange(M)
    r0 = ranges[:, :, 0]
    m_w = ranges[:, :, 1] - r0
    out_ids: List[EndpointIds] = []
    for e, ep in enumerate(out_eps):
        cols = out_cols[ep]
        c_valid = jm[None, :] < m_w[:, e][:, None]     # [Bw, M]
        c_src = np.where(c_valid, r0[:, e][:, None] + jm[None, :], 0)
        out_idx[:n_windows, e][c_valid] = out_slots[ep][c_src][c_valid]
        r0_pad = np.zeros(B, dtype=np.int64)
        cnt_pad = np.zeros(B, dtype=np.int64)
        r0_pad[:n_windows] = r0[:, e]
        cnt_pad[:n_windows] = m_w[:, e]
        out_ids.append(EndpointIds(cols.ids, r0_pad, cnt_pad, M))

    if skip_caps is not None:
        skip_cap[:n_windows, :E] = skip_caps
    else:
        skip_cap[:n_windows, :E] = np.maximum(n_w[:, None] - m_w, 0)

    if force_skip_ids:
        in_ids_arr = in_cols.ids
        for e, ep in enumerate(out_eps):
            fs = force_skip_ids.get(ep, set())
            if not fs:
                continue
            for b in range(n_windows):
                lo, hi = int(los[b]), int(his[b])
                mask = np.fromiter((i in fs for i in in_ids_arr[lo:hi]),
                                   bool, hi - lo)
                n_forced = int(mask.sum())
                if n_forced:
                    force_skip[b, e, :hi - lo] = mask
                skip_cap[b, e] = max(skip_cap[b, e], n_forced)

    arrays = dict(
        skip_cap=skip_cap, force_skip=force_skip,
        **_problem_tables(out_eps, E_pad, dists, in_ep, dag, parallel),
    )
    return PackedProblem(
        arrays=arrays, out_eps=out_eps, windows=windows,
        in_ids=in_cols.ids, out_ids=out_ids, n_in=len(in_cols),
        devcols=dict(in_idx=in_idx, out_idx=out_idx,
                     origin_in=origin_in, origin_out=origin_out,
                     ring_in=ring_in, ring_out=ring_out))


def _pack_problem_objects(
    in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
    force_skip_ids=None, max_window=DEFAULT_MAX_WINDOW, parallel=False,
    windows=None, pad_w=None, pad_b=None, pad_m=None, pad_e=None,
    ranges=None, skip_caps=None,
) -> PackedProblem:
    """Object-walk :func:`pack_problem` body (``TW_COLUMNAR=0``): the
    pre-columnar per-window span loops, kept verbatim as the kill switch
    and the golden-parity reference."""
    E = len(out_eps)
    E_pad = max(E, pad_e or E)
    if windows is None:
        windows = perfect_cut_windows(in_spans, max_window)
    n_windows = len(windows)
    B = _bucket(max(n_windows, pad_b or 1), minimum=1)
    W = _bucket(max(max(hi - lo for lo, hi in windows), pad_w or 1))

    out_sorted = {
        ep: sorted(out_span_partitions[ep], key=lambda s: s.start_mus)
        for ep in out_eps
    }
    out_starts_np = {
        ep: np.array([float(s.start_mus) for s in out_sorted[ep]]) for ep in out_eps
    }

    if ranges is None:  # caller may pass precomputed rows (same helper)
        ranges = candidate_ranges(in_spans, windows, out_eps, out_starts_np)
    M = _bucket(max(int((ranges[:, :, 1] - ranges[:, :, 0]).max(initial=1)),
                    pad_m or 1))

    in_start = np.zeros((B, W), dtype=np.float32)
    in_end = np.zeros((B, W), dtype=np.float32)
    in_valid = np.zeros((B, W), dtype=bool)
    out_start = np.zeros((B, E_pad, M), dtype=np.float32)
    out_end = np.zeros((B, E_pad, M), dtype=np.float32)
    out_valid = np.zeros((B, E_pad, M), dtype=bool)
    skip_cap = np.zeros((B, E_pad), dtype=np.float32)
    force_skip = np.zeros((B, E_pad, W), dtype=bool)

    out_ids: List[List] = [[None] * (B * M) for _ in range(E)]
    in_ids = [s.GetId() for s in in_spans]

    for b, (lo, hi) in enumerate(windows):
        origin = float(in_spans[lo].start_mus)
        n_w = hi - lo
        in_start[b, :n_w] = [float(s.start_mus) - origin for s in in_spans[lo:hi]]
        in_end[b, :n_w] = [
            float(s.start_mus) + float(s.duration_mus) - origin
            for s in in_spans[lo:hi]
        ]
        in_valid[b, :n_w] = True
        for e, ep in enumerate(out_eps):
            r0, r1 = int(ranges[b, e, 0]), int(ranges[b, e, 1])
            m_w = r1 - r0
            cands = out_sorted[ep][r0:r1]
            out_start[b, e, :m_w] = [float(s.start_mus) - origin for s in cands]
            out_end[b, e, :m_w] = [
                float(s.start_mus) + float(s.duration_mus) - origin for s in cands
            ]
            out_valid[b, e, :m_w] = True
            for j, s in enumerate(cands):
                out_ids[e][b * M + j] = s.GetId()
            # water-filled budget when provided (reference TallySkipSpans
            # semantics); the solver still grants window-local slack
            # max(rows - cols, 0) on device for feasibility
            skip_cap[b, e] = (float(skip_caps[b, e]) if skip_caps is not None
                              else max(0, n_w - m_w))
            if force_skip_ids:
                fs = force_skip_ids.get(ep, set())
                n_forced = 0
                for i, s in enumerate(in_spans[lo:hi]):
                    if s.GetId() in fs:
                        force_skip[b, e, i] = True
                        n_forced += 1
                # every forced row needs skip capacity even when candidate
                # ranges inflated by neighbouring windows hide the slack
                skip_cap[b, e] = max(skip_cap[b, e], n_forced)

    arrays = dict(
        in_start=in_start, in_end=in_end, in_valid=in_valid,
        out_start=out_start, out_end=out_end, out_valid=out_valid,
        skip_cap=skip_cap, force_skip=force_skip,
        **_problem_tables(out_eps, E_pad, dists, in_ep, dag, parallel),
    )
    return PackedProblem(arrays=arrays, out_eps=out_eps, windows=windows,
                         in_ids=in_ids, out_ids=out_ids, n_in=len(in_spans))


def plan_find_assignments(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    out_eps: List[str],
    dag,
    true_assignments,
    score_mode: str = "mixture",
    true_skips: bool = False,
    true_dist: bool = False,
    parallel_mode: bool = False,
    skip_fit: bool = False,
) -> Dict:
    """The solve plan shared by the per-service entry point
    (:meth:`WeaverTPU.FindAssignments`) and the fleet packer
    (:func:`traceweaver_tpu.algorithms.fleet._prepare`): per-endpoint
    skip budgets (reference traceweaver_v3.py:972), the dynamism flag,
    forced-skip rows for the true-skips oracle, initial distributions
    (bootstrap under dynamism / missing DAG, graph-aware batch means
    otherwise, oracle truth under true_dist) and the iteration count.
    ONE definition so the two production paths cannot drift.

    ``skip_fit=True`` skips ONLY the distribution fit (``dists`` comes
    back empty) — for callers that will override dists anyway (a warm
    carried state or a plan-cache hit), where the host BIC sweeps are
    the round's dominant serial stage and pure dead computation.
    Budgets, dynamism, forced skips and the iteration count are computed
    identically, so the plan is otherwise byte-for-byte the same.
    """
    in_ep = next(iter(in_span_partitions))
    n_in = len(in_span_partitions[in_ep])
    skip_budget = {
        ep: n_in - len(out_span_partitions[ep]) for ep in out_eps
    }
    dynamism = any(b > 0 for b in skip_budget.values())

    force_skip_ids = None
    if true_skips:
        force_skip_ids = {
            ep: {
                in_id for in_id, out_id in true_assignments[ep].items()
                if tuple(out_id) == SKIP
            }
            for ep in out_eps
        }

    if skip_fit:
        dists = {}
    elif true_dist:
        dists = timing.true_distributions(
            in_span_partitions, out_span_partitions, out_eps,
            true_assignments, score_mode=score_mode,
        )
    elif dynamism or dag is None:
        dists = timing.bootstrap_distributions(
            in_span_partitions, out_span_partitions, out_eps,
            score_mode=score_mode,
        )
    else:
        dists = timing.estimate_edge_params(
            in_span_partitions, out_span_partitions, dag, 0, n_in,
        )

    iterations = 1 if (parallel_mode or dynamism or true_dist) else 2
    return dict(skip_budget=skip_budget, dynamism=dynamism,
                force_skip_ids=force_skip_ids, dists=dists,
                iterations=iterations, n_in=n_in, in_ep=in_ep)


# ---------------------------------------------------------------------------
# The plugin-facing solver class
# ---------------------------------------------------------------------------

class WeaverTPU:
    """TraceWeaverV3-capability solver behind the plugin contract.

    Registered at predictor indices 8/9/10
    (``MaxScoreBatchParallelWithoutIterations`` / ``MaxScoreBatchParallel``
    / ``MaxScoreBatchSubsetWithSkips``); also accepts the oracle ablation
    methods ``MaxScoreBatchSubsetWithTrueSkips`` / ``WithTrueDist``
    (reference executor.py:976-987).
    """

    def __init__(self, all_spans, all_processes, max_window: int = DEFAULT_MAX_WINDOW,
                 epsilon: float = 1.0, n_sinkhorn: int = 40, n_sweeps: int = 5,
                 mesh=None, score_mode: str = "mixture",
                 sinkhorn_tol: float = 1e-3, precision: Optional[str] = None):
        self.all_spans = all_spans
        self.all_processes = all_processes
        self.max_window = max_window
        self.epsilon = epsilon
        self.n_sinkhorn = n_sinkhorn
        self.n_sweeps = n_sweeps
        # score-block storage precision ("f32" default — bit-identical
        # historical program — or "bf16"; see ops/precision.py). None
        # reads TW_PRECISION at construction time.
        self.precision = validate_precision(
            precision if precision is not None else precision_from_env())
        # early-exit tolerance for the Sinkhorn potentials (n_sinkhorn stays
        # the hard cap); the Gauss-Seidel sweep loop exits exactly on
        # assignment stability regardless of this value
        self.sinkhorn_tol = sinkhorn_tol
        # optional jax.sharding.Mesh: window batches shard over its first
        # axis (XLA SPMD over ICI); None = single device
        self.mesh = mesh
        # "mixture" (default: Gaussian / BIC-GMM, reference norm+GMM score
        # branches) or "kde" (binned-KDE mixtures, reference
        # traceweaver_v1.py:117-121 KDE branch)
        self.score_mode = score_mode
        # per-solve stage accounting (seconds / analytic op counts),
        # populated by FindAssignments; read by the benchmark
        self.stats: Dict[str, float] = {}
        # per-span reconstruction-quality records of the LAST solve
        # ({in span id: {conf, not_best, cands, support}} —
        # obs/quality.py); the fleet's per-service fallback path copies
        # this into the caller's confidences slot, so fallback windows
        # carry tw.confidence exactly like fused ones
        self.per_span_confidence: Dict = {}

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _topo_out_eps(out_span_partitions, invocation_graph) -> List[str]:
        if invocation_graph is not None and len(invocation_graph) > 0:
            first_start = {
                ep: spans[0].start_mus if spans else 0
                for ep, spans in out_span_partitions.items()
            }
            return list(
                nx.lexicographical_topological_sort(
                    invocation_graph, key=lambda ep: first_start.get(ep, 0)
                )
            )
        return get_out_eps_in_order(out_span_partitions)

    def _solve_once(self, in_spans, out_span_partitions, out_eps, dists,
                    in_ep, dag, force_skip_ids, parallel, fused=False):
        """Solve all perfect-cut windows in as few device dispatches as
        possible: size classes are merged upward while the padding cost
        stays under MERGE_ELEMS, batches are chunked only to bound live HBM
        (budgeted on the true [B, W, M] block), outputs are packed into a
        single int32 tensor and fetched asynchronously — each device round
        trip through the tunnel costs ~100 ms, so dispatch count dominates.

        Returns a list of ``(packed, (assign, topk, not_best, feas))``.
        """
        E = max(1, len(out_eps))
        n_sweeps = 1 if E == 1 else self.n_sweeps

        # columnar host path (TW_COLUMNAR, default): ONE object -> column
        # conversion per partition here; windowing, candidate ranges, and
        # every pack below are array work over these columns
        in_cols = out_cols = None
        if columnar_enabled():
            in_cols = in_columns(in_spans)
            out_cols = out_columns(out_span_partitions, out_eps)
            all_windows = perfect_cut_windows_cols(in_cols, self.max_window)
            out_starts_np = {ep: out_cols[ep].start for ep in out_eps}
        else:
            all_windows = perfect_cut_windows(in_spans, self.max_window)
            out_starts_np = {
                ep: np.array(sorted(float(s.start_mus)
                                    for s in out_span_partitions[ep]))
                for ep in out_eps
            }
        # candidate ranges computed ONCE for all windows (the same rows the
        # packer consumes), so padding costs and the chunk budget reflect
        # the true [B, W, M] block without re-running searchsorted per class
        ranges_all = candidate_ranges(
            in_spans, all_windows, out_eps, out_starts_np, in_cols=in_cols)
        # per-endpoint global skip budget spread across windows by
        # water-filling (reference TallySkipSpans, traceweaver_v3.py:853-989)
        skip_caps_all = water_fill_skip_caps(
            all_windows, ranges_all, len(in_spans),
            [len(out_span_partitions[ep]) for ep in out_eps],
        )
        width_of = {
            w: int((ranges_all[i, :, 1] - ranges_all[i, :, 0]).max(initial=1))
            for i, w in enumerate(all_windows)
        }
        row_of = {w: i for i, w in enumerate(all_windows)}

        def est_m(wins: List[Tuple[int, int]]) -> int:
            return _bucket(max(width_of[w] for w in wins))

        # size classes (power-of-two widths), with smaller classes greedily
        # merged upward while the extra padded area stays under MERGE_ELEMS —
        # one dispatch for typical skews, separate classes when padding a
        # swarm of small windows up to a burst's width would cost more
        # compute than the saved round trip
        groups: Dict[int, List[Tuple[int, int]]] = {}
        for w in all_windows:
            groups.setdefault(_bucket(w[1] - w[0]), []).append(w)
        classes = sorted(groups)
        batches_spec: List[Tuple[int, List[Tuple[int, int]]]] = []
        carry: List[Tuple[int, int]] = []
        for idx, c in enumerate(classes):
            wins = carry + groups[c]
            if idx + 1 < len(classes):
                nxt = classes[idx + 1]
                # charge each window from its ORIGINAL class — a window
                # carried across several merges compounds padding that a
                # per-step (nxt - c) charge would undercount
                extra = sum(nxt - _bucket(hi - lo) for lo, hi in wins)
                if extra * est_m(wins) * E <= MERGE_ELEMS:
                    carry = wins
                    continue
            batches_spec.append((c, wins))
            carry = []

        import time as _time

        # multi-device: window batches shard over the mesh's first axis
        # (XLA SPMD over ICI; see traceweaver_tpu.parallel.mesh) — each
        # device then owns a contiguous slice of windows, so the chunk
        # element budget (per-device HBM) scales by the mesh size
        mesh = self.mesh
        n_dev = 1
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            assert n_dev & (n_dev - 1) == 0, (
                "mesh size must be a power of two so padded window batches "
                "divide evenly across devices")

        stats = self.stats
        # per-dispatch budget in BYTES (CHUNK_ELEMS is denominated in f32
        # elements for knob back-compat): a bf16 score block charges half,
        # so the same HBM bound admits ~2x the windows per dispatch
        itemsize = score_itemsize(self.precision)
        chunk_bytes = CHUNK_ELEMS * 4
        plan = []
        for wclass, wins in batches_spec:
            m_est = est_m(wins)
            per_chunk = max(
                1, chunk_bytes // (wclass * m_est * E * itemsize)) * n_dev
            chunks = [wins[i:i + per_chunk]
                      for i in range(0, len(wins), per_chunk)]
            for chunk in chunks:
                plan.append((wclass, m_est, per_chunk, len(chunks), chunk))
        # the fused two-pass EM dispatch refits from its own windows'
        # samples, so it is only equivalent to the global host refit when
        # one dispatch covers the whole solve (the common case — the
        # dispatch planner merges aggressively for exactly this reason)
        use_fused = fused and len(plan) == 1
        if use_fused:
            stats["fused_em_applied"] = 1.0

        pending = []
        for wclass, m_est, per_chunk, n_chunks, chunk in plan:
            t0 = _time.perf_counter()
            packed = pack_problem(
                in_spans, out_span_partitions, out_eps, dists, in_ep, dag,
                force_skip_ids=force_skip_ids, parallel=parallel,
                windows=chunk, pad_w=wclass,
                pad_b=(per_chunk if n_chunks > 1 else n_dev
                       if n_dev > 1 else None),
                pad_m=m_est if n_chunks > 1 else None,
                ranges=ranges_all[[row_of[w] for w in chunk]],
                skip_caps=skip_caps_all[[row_of[w] for w in chunk]],
                in_cols=in_cols, out_cols=out_cols,
            )
            _stat_add(stats, "pack_s", _time.perf_counter() - t0)
            a = packed.arrays
            if mesh is not None:
                from traceweaver_tpu.parallel.mesh import put_sharded

                a = put_sharded(a, mesh)
            B_c, W_c = a["in_start"].shape
            M_c = a["out_start"].shape[2]
            K_c = a["in_wt"].shape[1]
            # static neighbour bounds: tightest power-of-two cover of the
            # DAG's max in/out degree, so the score build only evaluates
            # real DAG edges (in-degree ~1 here) instead of all E
            pm_np = packed.arrays["pred_mask"]
            mp = _bucket(max(1, int(pm_np.sum(axis=1).max(initial=0))),
                         minimum=1)
            ms = _bucket(max(1, int(pm_np.sum(axis=0).max(initial=0))),
                         minimum=1)
            n_pred, n_succ = min(mp, E), min(ms, E)
            # analytic op accounting for utilization estimates:
            # score build ~ (n_pred+n_succ+2) mixture evals of K comps
            # (~8 flops each) per cell; Sinkhorn 2 LSE passes/iter
            # (~6 flops/cell); rounding ~log2(W) rounds (~8 flops/cell).
            # NOTE: an UPPER BOUND since the sweep loop and the Sinkhorn
            # iteration both exit early on convergence — derived MFU/HBM
            # figures are therefore upper bounds too
            n_passes = 2 if use_fused else 1
            cells = B_c * E * W_c * M_c * n_sweeps * n_passes
            _stat_add(stats, "flops_est", cells * (
                8.0 * K_c * (n_pred + n_succ + 2)
                + 6.0 * 2 * self.n_sinkhorn
                + 8.0 * max(1, W_c.bit_length())
            ))
            # XLA-path HBM traffic bound: the [W, M] score block streams
            # twice per Sinkhorn iteration (row+col LSE) at the SCORE
            # itemsize (bf16 halves this — the whole point of
            # TW_PRECISION); the Pallas kernel keeps it VMEM-resident and
            # only pays one score read plus the f32 plan/result write
            _stat_add(stats, "bytes_est_xla",
                      cells * float(itemsize) * 2 * self.n_sinkhorn)
            _stat_add(stats, "bytes_est_pallas",
                      cells * (float(itemsize) + 2 * 4.0))
            t0 = _time.perf_counter()
            solve_fn = solve_em_packed if use_fused else solve_windows_packed
            if mesh is None:
                # AOT-escape accounting for the per-service path (tier
                # "full" of the lattice, runtime/aot.py); numeric here —
                # the ordered shape ledger rides the fleet stats dict
                from traceweaver_tpu.runtime import aot as _aot

                if _aot.note_packed(
                        solve_fn.__name__, B_c, E, W_c, M_c, mp, ms,
                        n_sweeps, self.epsilon, self.n_sinkhorn,
                        self.sinkhorn_tol, self.precision):
                    _stat_add(stats, "aot_packed_misses", 1.0)
            with _obs_profile.annotate("tw:solve:dispatch"):
                out = solve_fn(
                    a["in_start"], a["in_end"], a["in_valid"],
                    a["out_start"], a["out_end"], a["out_valid"],
                    a["skip_cap"], a["force_skip"],
                    a["pred_mask"], a["root_mask"], a["is_last"],
                    a["edge_wt"], a["edge_mu"], a["edge_sd"],
                    a["in_wt"], a["in_mu"], a["in_sd"],
                    a["ret_wt"], a["ret_mu"], a["ret_sd"],
                    epsilon=self.epsilon, n_sinkhorn=self.n_sinkhorn,
                    n_sweeps=n_sweeps, sinkhorn_tol=self.sinkhorn_tol,
                    max_preds=mp, max_succs=ms, precision=self.precision,
                )
            _stat_add(stats, "dispatch_s", _time.perf_counter() - t0)
            pending.append((packed, out))

        for _, out in pending:
            try:
                out.copy_to_host_async()
            except AttributeError:  # plain np.ndarray under some backends
                pass

        results = []
        t0 = _time.perf_counter()
        for packed, out in pending:
            # twlint: disable=TW003 — ledgered fetch site: the whole
            # loop is billed to wait_s below (the copy_to_host_async
            # pass above started every transfer; fleet-path fetches go
            # through fleet._fetch instead)
            o = np.asarray(out)
            ch = _layout.split_packed(o)
            results.append((packed, (ch["assign"], ch["topk_cols"],
                                     ch["not_best"], ch["feas"])))
        _stat_add(stats, "wait_s", _time.perf_counter() - t0)
        return results

    @staticmethod
    def _decode(packed: PackedProblem, assign: np.ndarray,
                topk_cols: np.ndarray, all_assignments, all_topk):
        """Device indices -> wire-format assignment dicts (merged in place).

        Vectorized: column indices for a whole packed batch are translated
        to span ids by one object-array gather per endpoint (the id tables
        are [B*M] object arrays), so per-span Python work is only the final
        dict insertion — not index arithmetic (at exp5 scale the decode is
        otherwise host-bound).
        """
        B, E, W = assign.shape
        M = packed.M
        K = topk_cols.shape[3]
        # 0-d object holders let tuple sentinels assign under boolean masks
        skip_v = np.empty((), dtype=object)
        skip_v[()] = SKIP
        na_v = np.empty((), dtype=object)
        na_v[()] = NA

        w_of = np.concatenate(
            [np.full(hi - lo, b) for b, (lo, hi) in enumerate(packed.windows)]
        )
        i_of = np.concatenate(
            [np.arange(hi - lo) for lo, hi in packed.windows]
        )
        pos = np.concatenate([np.arange(lo, hi) for lo, hi in packed.windows])
        if isinstance(packed.in_ids, np.ndarray):
            # columnar: the id column gathers by position in one step
            span_ids = packed.in_ids[pos].tolist()
        else:
            span_ids = [packed.in_ids[p] for p in pos]

        for e, ep in enumerate(packed.out_eps):
            # id maps resolve HERE (EndpointIds.gather on the columnar
            # path): pack never materializes B*M Python id slots
            ids = packed.out_id_array(e)

            cols = assign[w_of, e, i_of]                       # [n]
            chosen = ids[w_of * M + np.clip(cols, 0, M - 1)]
            chosen[chosen == None] = na_v  # noqa: E711 — elementwise None test
            chosen[cols < 0] = na_v
            chosen[cols == M] = skip_v

            tk = topk_cols[w_of, e, i_of, :]                   # [n, K]
            tk_ids = ids[w_of[:, None] * M + np.clip(tk, 0, M - 1)]
            tk_ids[tk_ids == None] = na_v  # noqa: E711
            tk_ids[(tk < 0) | (tk > M)] = na_v
            tk_ids[tk == M] = skip_v

            amap = all_assignments[ep]
            tmap = all_topk[ep]
            chosen_l = chosen.tolist()
            tk_l = tk_ids.tolist()
            for j, in_id in enumerate(span_ids):
                out_id = chosen_l[j]
                tks = tk_l[j]
                if out_id in tks:
                    tks.remove(out_id)
                amap[in_id] = out_id
                tmap[in_id] = [out_id] + tks[: K - 1]

    @staticmethod
    def _resolve_cross_window_duplicates(all_assignments, all_topk, in_ids,
                                         skip_budget):
        """Restore global one-to-one-ness across capped sub-windows.

        Perfect-cut segments are solved whole, so duplicates can only arise
        when a segment longer than ``max_window`` was split and two
        sub-windows both claimed an outgoing span from their (overlapping)
        candidate ranges. Per contested out-span, the earliest incoming
        span in time order (``in_ids`` order — the serial-peel convention)
        keeps it; only the losers are reassigned, to their best-ranked
        top-K alternative that no row (winner or not) holds, taking SKIP
        only while the endpoint's global ``|in| - |out|`` budget
        (traceweaver_v3.py:972) has room, else NA.
        """
        for ep, assign_map in all_assignments.items():
            claims: Dict = {}
            skips_used = 0
            for in_id in in_ids:
                out_id = assign_map.get(in_id)
                if out_id == SKIP:
                    skips_used += 1
                elif out_id is not None and out_id != NA:
                    claims.setdefault(out_id, []).append(in_id)
            used = set(claims)
            for out_id, claimants in claims.items():
                for in_id in claimants[1:]:  # earliest claimant keeps it
                    replacement = NA
                    for cand in all_topk.get(ep, {}).get(in_id, []):
                        if cand == SKIP:
                            if skips_used < skip_budget.get(ep, 0):
                                replacement = SKIP
                                skips_used += 1
                                break
                            continue
                        if cand != NA and cand not in used:
                            replacement = cand
                            break
                    assign_map[in_id] = replacement
                    if replacement not in (NA, SKIP):
                        used.add(replacement)
                    tk = all_topk.get(ep, {}).get(in_id)
                    if tk and replacement in tk:
                        tk.remove(replacement)
                        tk.insert(0, replacement)

    # -- plugin entry point ------------------------------------------------
    def FindAssignments(self, method, process, in_span_partitions,
                        out_span_partitions, parallel, instrumented_hops,
                        true_assignments, invocation_graph=None,
                        true_skips: bool = False, true_dist: bool = False):
        assert len(in_span_partitions) == 1
        in_ep, in_spans = next(iter(in_span_partitions.items()))
        in_spans = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
        out_eps = self._topo_out_eps(out_span_partitions, invocation_graph)
        parallel_mode = parallel or method == "MaxScoreBatchParallelWithoutIterations"

        plan = plan_find_assignments(
            in_span_partitions, out_span_partitions, out_eps,
            invocation_graph, true_assignments,
            score_mode=self.score_mode, true_skips=true_skips,
            true_dist=true_dist, parallel_mode=parallel_mode,
        )
        n_in = plan["n_in"]
        skip_budget = plan["skip_budget"]
        force_skip_ids = plan["force_skip_ids"]
        dists = plan["dists"]
        iterations = plan["iterations"]

        import time as _time

        self.stats = {}
        all_assignments = all_topk = None
        not_best_count = 0
        per_span_candidates: Dict = {}
        in_ids = [s.GetId() for s in in_spans]
        it = 0
        while it < iterations:
            batches = self._solve_once(
                in_spans, out_span_partitions, out_eps, dists, in_ep,
                invocation_graph, force_skip_ids, parallel_mode,
                # fused on-device refit fits GMMs; the KDE score mode's
                # binned-KDE refit stays on the host two-pass path
                fused=(iterations == 2 and it == 0
                       and self.score_mode == "mixture"),
            )
            if self.stats.get("fused_em_applied"):
                # the single fused dispatch already ran refit + pass 2
                iterations = 1
            t0 = _time.perf_counter()
            all_assignments = {ep: {} for ep in out_eps}
            all_topk = {ep: {} for ep in out_eps}
            # confidence: a span is "not best" if OT overrode the row argmax
            span_not_best = np.zeros(n_in, dtype=bool)
            span_cands = np.ones(n_in, dtype=np.int64)
            conf_on = _quality.conf_enabled()
            conf_arrs = _quality.new_span_arrays(n_in) if conf_on else None
            for packed, (assign, topk_cols, not_best, feas) in batches:
                self._decode(packed, assign, topk_cols,
                             all_assignments, all_topk)
                scatter_window_span_stats(packed.windows, not_best, feas,
                                          span_not_best, span_cands)
                if conf_on:
                    _quality.scatter_confidence(packed.windows, not_best,
                                                feas, topk_cols, conf_arrs)
            not_best_count = int(span_not_best.sum())
            per_span_candidates = {
                in_ids[i]: int(span_cands[i]) for i in range(n_in)
            }
            self.per_span_confidence = (_quality.confidence_records(
                in_ids, _quality.finish_confidence(conf_arrs))
                if conf_on else {})
            self._resolve_cross_window_duplicates(
                all_assignments, all_topk, in_ids, skip_budget)
            _stat_add(self.stats, "decode_s", _time.perf_counter() - t0)
            if it + 1 < iterations:
                t0 = _time.perf_counter()
                dists = timing.refit_from_assignments(
                    in_span_partitions, out_span_partitions,
                    invocation_graph, all_assignments, self.all_spans,
                    score_mode=self.score_mode,
                )
                _stat_add(self.stats, "refit_s",
                          _time.perf_counter() - t0)
            it += 1

        cnt_unassigned = sum(
            1
            for in_id in in_ids
            if any(all_assignments[ep][in_id] == NA for ep in out_eps)
        )

        return (all_assignments, all_topk, not_best_count, n_in,
                per_span_candidates, cnt_unassigned)
