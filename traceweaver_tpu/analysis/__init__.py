"""twlint — repo-native static analysis for the traceweaver contracts.

The codebase runs on invariants that grep can't hold: every ``TW_*``
knob goes through the typed registry (PR 5), bf16 is storage-only with
f32 accumulation (PR 4), dispatch shapes stay pow2-bucketed so the
second solve costs zero compiles (PR 2/3), and shared pipeline state is
mutated under locks (PR 3/6). This package mechanizes them as an
import-light, stdlib-``ast`` rule engine with per-line suppression and
a checked-in baseline, run as a tier-1 gate (tests/test_analysis.py)
and on demand::

    python -m traceweaver_tpu.analysis            # whole repo
    python -m traceweaver_tpu.analysis ops/       # one subtree
    python -m traceweaver_tpu.runtime.cli lint    # CLI spelling

Rule catalog, suppression grammar, and how to add a rule:
docs/ANALYSIS.md.
"""

from traceweaver_tpu.analysis.engine import (  # noqa: F401
    DEFAULT_BASELINE,
    BaselineError,
    Finding,
    Module,
    Report,
    analyze_sources,
    format_baseline,
    iter_python_files,
    load_baseline,
    run,
)
from traceweaver_tpu.analysis.rules import RULE_CLASSES  # noqa: F401
