"""twlint rule engine: findings, suppressions, baseline, repo walking.

Import-light by design — stdlib ``ast``/``tokenize`` only, no jax, no
numpy — so the lint gate costs milliseconds and can run before any
backend exists (CI, pre-commit, the ``lint`` CLI subcommand, and the
tier-1 test in tests/test_analysis.py all call :func:`run`).

The moving parts:

- :class:`Finding` — one violation, with a content-addressed
  :meth:`~Finding.fingerprint` (rule | path | stripped source line) so
  baseline entries survive unrelated line drift;
- suppressions — ``# twlint: disable=TW003`` on the offending line (or
  on a comment-only line immediately above it) waives named rules;
  ``# twlint: disable-file=TW004`` anywhere waives a rule for the whole
  file. A typo'd rule id in a suppression is itself reported (TW000) so
  a misspelled waiver can never silently not work;
- baseline — a checked-in file of grandfathered findings
  (:data:`DEFAULT_BASELINE`); every entry MUST carry a ``#`` justification
  or loading fails. Stale entries (matching nothing) are reported as
  TW000 so the baseline can only shrink honestly;
- rules — objects with ``check_module(mod)`` (per-file) and optionally
  ``check_repo(modules)`` (cross-file, e.g. the knob-registry
  reconciliation in TW001), instantiated fresh per :func:`run`.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: the meta rule id: engine-level problems (bad suppression ids, stale
#: baseline entries). Not suppressible and never baselined.
META_RULE = "TW000"

_SUPPRESS_RE = re.compile(
    r"#\s*twlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+?)"
    r"(?:\s*(?:—|--|:).*)?$")


@dataclass(frozen=True)
class Finding:
    rule: str          # "TW001"
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    col: int           # 0-based
    message: str
    line_text: str = ""  # stripped source line, for the fingerprint

    def fingerprint(self) -> str:
        """Content-addressed id for baseline matching: stable across
        line-number drift, invalidated when the flagged line changes."""
        key = "|".join((self.rule, self.path, self.line_text.strip()))
        return hashlib.sha1(key.encode()).hexdigest()[:12]

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule} {self.message}")


class Module:
    """One parsed source file handed to rules."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       line_text=self.line_text(getattr(node, "lineno", 1)))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class Suppressions:
    """Per-file suppression table parsed from comments."""

    def __init__(self, by_line: Dict[int, Set[str]],
                 file_wide: Set[str], bad_ids: List[Tuple[int, str]]) -> None:
        self.by_line = by_line
        self.file_wide = file_wide
        self.bad_ids = bad_ids  # (line, bogus id) — surfaced as TW000

    def waives(self, finding: Finding) -> bool:
        if finding.rule == META_RULE:
            return False
        if finding.rule in self.file_wide:
            return True
        return finding.rule in self.by_line.get(finding.line, set())


def parse_suppressions(text: str, known_rules: Set[str]) -> Suppressions:
    by_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    bad: List[Tuple[int, str]] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError):
        return Suppressions({}, set(), [])
    # comment-only lines: a suppression there covers the NEXT source line
    # (long statements can't always fit a trailing comment)
    code_lines = {t.start[0] for t in tokens
                  if t.type not in (tokenize.COMMENT, tokenize.NL,
                                    tokenize.NEWLINE, tokenize.INDENT,
                                    tokenize.DEDENT, tokenize.ENCODING,
                                    tokenize.ENDMARKER)}
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind, raw_ids = m.groups()
        ids = {s.strip() for s in raw_ids.split(",") if s.strip()}
        line = tok.start[0]
        for rid in ids:
            if rid not in known_rules:
                bad.append((line, rid))
        ids &= known_rules
        if kind == "disable-file":
            file_wide |= ids
        elif line in code_lines:
            by_line.setdefault(line, set()).update(ids)
        else:
            # standalone comment line → applies to the next code line
            nxt = min((ln for ln in code_lines if ln > line), default=None)
            if nxt is not None:
                by_line.setdefault(nxt, set()).update(ids)
    return Suppressions(by_line, file_wide, bad)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class BaselineError(ValueError):
    """A malformed baseline file (missing justification, bad shape)."""


def load_baseline(path: str) -> Dict[Tuple[str, str, str], str]:
    """Parse a baseline file into ``{(rule, path, fingerprint): line}``.

    Grammar (one grandfathered finding per line)::

        TW001 traceweaver_tpu/foo.py 1a2b3c4d5e6f  # why this is still here

    The trailing ``#`` justification is MANDATORY — an unexplained
    baseline entry is exactly the silent rot this tool exists to stop.
    Blank lines and full-line comments are ignored.
    """
    entries: Dict[Tuple[str, str, str], str] = {}
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for n, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, sep, reason = line.partition("#")
            if not sep or not reason.strip():
                raise BaselineError(
                    f"{path}:{n}: baseline entry lacks a '# justification' "
                    f"comment: {line!r}")
            parts = body.split()
            if len(parts) != 3:
                raise BaselineError(
                    f"{path}:{n}: expected 'RULE path fingerprint  "
                    f"# reason', got: {line!r}")
            rule, rel, fp = parts
            if rule == META_RULE:
                raise BaselineError(
                    f"{path}:{n}: {META_RULE} (engine) findings cannot be "
                    "baselined")
            entries[(rule, rel, fp)] = line
    return entries


def format_baseline(findings: Sequence[Finding]) -> str:
    """Render findings as baseline lines (justifications left as TODO —
    the author must fill them in, or loading will fail)."""
    out = ["# twlint baseline — one grandfathered finding per line.",
           "# Every entry needs a real '# justification'; see "
           "docs/ANALYSIS.md.",
           ""]
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        out.append(f"{f.rule} {f.path} {f.fingerprint()}  "
                   f"# TODO justify: {f.message[:60]}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# repo walking + run
# ---------------------------------------------------------------------------

#: directories never scanned (vcs/caches/build junk)
EXCLUDE_DIRS = {".git", "__pycache__", ".jax_cache", ".claude",
                ".pytest_cache", ".ruff_cache", "build", "node_modules"}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.txt")


def iter_python_files(root: str,
                      paths: Optional[Sequence[str]] = None) -> List[str]:
    """Repo-relative paths of every ``.py`` file under ``root`` (or under
    the given sub-``paths``), sorted, caches excluded."""
    rels: List[str] = []
    targets = [os.path.join(root, p) for p in paths] if paths else [root]
    for target in targets:
        if os.path.isfile(target):
            rels.append(os.path.relpath(target, root))
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(set(r.replace(os.sep, "/") for r in rels))


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # live, ranked
    suppressed: int = 0
    baselined: int = 0
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"twlint: {len(self.findings)} finding(s) across {self.files} "
            f"file(s) ({self.baselined} baselined, "
            f"{self.suppressed} suppressed)")
        return "\n".join(lines)


def _default_rules():
    from traceweaver_tpu.analysis import rules as _rules

    return [cls() for cls in _rules.RULE_CLASSES]


def analyze_sources(sources: Sequence[Tuple[str, str]],
                    rules=None) -> Tuple[List[Finding], int]:
    """Run rules over in-memory ``(rel_path, text)`` pairs (the fixture
    path — tests feed snippets without touching disk). Applies
    suppressions but no baseline. Returns (findings, suppressed_count)."""
    rules = _default_rules() if rules is None else rules
    known = {r.id for r in rules} | {META_RULE}
    modules: List[Module] = []
    raw: List[Finding] = []
    sups: Dict[str, Suppressions] = {}
    for rel, text in sources:
        try:
            mod = Module(rel, text)
        except SyntaxError as e:
            raw.append(Finding(META_RULE, rel.replace(os.sep, "/"),
                               e.lineno or 1, (e.offset or 1) - 1,
                               f"syntax error: {e.msg}"))
            continue
        modules.append(mod)
        sup = parse_suppressions(text, known)
        sups[mod.path] = sup
        for line, rid in sup.bad_ids:
            raw.append(Finding(META_RULE, mod.path, line, 0,
                               f"suppression names unknown rule {rid!r} "
                               f"(known: {', '.join(sorted(known))})"))
        for rule in rules:
            raw.extend(rule.check_module(mod))
    for rule in rules:
        check_repo = getattr(rule, "check_repo", None)
        if check_repo is not None:
            raw.extend(check_repo(modules))
    live: List[Finding] = []
    suppressed = 0
    for f in raw:
        sup = sups.get(f.path)
        if sup is not None and sup.waives(f):
            suppressed += 1
        else:
            live.append(f)
    live.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return live, suppressed


def run(root: str = REPO_ROOT,
        paths: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = DEFAULT_BASELINE,
        rules=None) -> Report:
    """The repo-wide pass: walk, parse, rule, suppress, baseline."""
    rels = iter_python_files(root, paths)
    sources: List[Tuple[str, str]] = []
    for rel in rels:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            sources.append((rel, f.read()))
    findings, suppressed = analyze_sources(sources, rules=rules)
    report = Report(suppressed=suppressed, files=len(sources))
    baseline = (load_baseline(baseline_path) if baseline_path else {})
    matched: Set[Tuple[str, str, str]] = set()
    for f in findings:
        key = (f.rule, f.path, f.fingerprint())
        if key in baseline:
            matched.add(key)
            report.baselined += 1
        else:
            report.findings.append(f)
    for key in sorted(set(baseline) - matched):
        # only meaningful when the full repo (or the entry's file) was
        # scanned; a partial run must not call untouched entries stale
        if paths and key[1] not in {s[0] for s in sources}:
            continue
        report.findings.append(Finding(
            META_RULE, os.path.relpath(
                baseline_path, root).replace(os.sep, "/"), 1, 0,
            f"stale baseline entry (nothing matches): {baseline[key]!r} — "
            "delete it"))
    return report
