"""twlint rules: the repo's cross-cutting contracts, mechanized.

Each rule encodes an invariant the codebase already relies on but until
now enforced only by convention (and violated silently — see the PR-8
issue). Rules are deliberately narrow: they pattern-match the concrete
hazard that has actually bitten, not a style preference, so a finding is
actionable and a clean run means the contract holds. docs/ANALYSIS.md
is the operator-facing catalog (rationale, examples, suppression
guidance); this module is the source of truth for what each rule flags.

Rule ids are stable (baseline entries and suppressions reference them):

- TW001 knob discipline      — every TW_* env access goes through
  runtime/knobs.py; registry and readers reconciled both ways
- TW002 import-time freeze   — no module-scope TW_* reads in the library
- TW003 host-sync hazard     — device→host conversions in hot-path
  modules only at ledgered fetch sites
- TW004 recompile discipline — precision/pallas-style jit args declared
  static; pow2 bucketing never re-implemented inline
- TW005 lock discipline      — attributes guarded by a class's lock are
  guarded everywhere
- TW006 precision discipline — no accumulation over bf16 storage blocks
  without an explicit f32 accumulator
- TW007 metric discipline    — counters in fleet/stream/serve grow only
  through the obs-mirrored accumulators
- TW008 channel layout       — packed-block channel indices come from
  packed_layout.py only
- TW009 devcols residency    — ring-resident columns materialize on host
  only through the ledgered fetch
- TW010 adapt ledger         — adaptation actuations route through the
  controller's evented ledger; no silent rung transitions
- TW012 ticket discipline    — per-tenant ``in_flight`` windows mutate
  only inside the ticket lifecycle (submit extends, retire removes)
- TW013 ack discipline       — a 2xx ack on the serve ingest paths is
  ledgered (``wal_ingest*``) or explicitly ``TW_WAL``-guarded
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from traceweaver_tpu.analysis.engine import Finding, Module

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``os.environ.get`` for
    the matching Attribute chain, ``""`` when not a plain name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _tw_name(node: ast.AST) -> Optional[str]:
    s = const_str(node)
    return s if s is not None and s.startswith("TW_") else None


def outer_functions(tree: ast.AST) -> List[ast.FunctionDef]:
    """FunctionDefs not nested inside another function (methods count;
    their nested helpers are visited via ``ast.walk`` on the outer def)."""
    out: List[ast.FunctionDef] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            else:
                visit(child)

    visit(tree)
    return out


_GETTERS = {"get", "get_int", "get_float", "get_bool"}


def registry_read(node: ast.Call) -> Optional[str]:
    """The TW_* name read through the knob registry by this call, if any
    (``knobs.get_int("TW_X")``, ``_knobs.get("TW_X")``, bare
    from-imported ``get_bool("TW_X")``)."""
    name = dotted(node.func)
    if not name:
        return None
    parts = name.split(".")
    if parts[-1] not in _GETTERS:
        return None
    if len(parts) > 1 and parts[-2] not in ("knobs", "_knobs"):
        return None
    if not node.args:
        return None
    return _tw_name(node.args[0])


def raw_env_read(node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """(TW_* name, site) for a raw environment READ: ``os.environ.get``,
    ``os.getenv``, or a Load-context ``os.environ[...]`` subscript.
    Writes (``os.environ[k] = v``, ``setdefault``, ``pop``) are how
    launchers configure children and are not reads."""
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
            if node.args:
                tw = _tw_name(node.args[0])
                if tw:
                    return tw, node
    elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted(node.value) in ("os.environ", "environ"):
            tw = _tw_name(node.slice)
            if tw:
                return tw, node
    return None


def _env_touch(node: ast.AST) -> Optional[str]:
    """Any TW_* name this node reads OR writes through the environment —
    usage evidence for the registered-but-never-read reconciliation."""
    got = raw_env_read(node)
    if got:
        return got[0]
    if isinstance(node, ast.Subscript) and dotted(node.value) in (
            "os.environ", "environ"):
        return _tw_name(node.slice)
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name in ("os.environ.setdefault", "environ.setdefault",
                    "os.environ.pop", "environ.pop") and node.args:
            return _tw_name(node.args[0])
    return None


def _path_in(mod: Module, suffixes: Sequence[str]) -> bool:
    return any(mod.path.endswith(s) for s in suffixes)


# ---------------------------------------------------------------------------
# TW001 — knob discipline
# ---------------------------------------------------------------------------

class KnobDiscipline:
    """Every ``TW_*`` environment knob goes through the typed registry.

    The registry (``runtime/knobs.py``, PR 5) is the single
    parse/validate/default path: a typo'd value raises instead of
    silently running the default, and ``warn_unknown`` can only see
    knobs the registry knows. A raw ``os.environ`` read anywhere else
    re-opens both holes. ``runtime/faults.py`` is the one other allowed
    reader: it owns the TW_FAULTS spec grammar (site:p[:max=N]), which
    is richer than the registry's scalar types.

    Cross-module, the rule reconciles registry and readers both ways:
    a knob read through the registry but never declared raises KeyError
    at runtime — flag it at the read site; a knob declared but read
    nowhere is dead configuration surface — flag it at the declaration.
    """

    id = "TW001"
    title = "TW_* knob access outside the typed registry"

    #: modules allowed to touch os.environ for TW_* names directly
    ALLOWED_RAW = ("runtime/knobs.py", "runtime/faults.py")
    #: declaration helpers inside knobs.py whose first arg names a knob
    _DECLS = ("_k", "Knob")

    def __init__(self) -> None:
        self._registry_reads: List[Tuple[str, Module, ast.AST]] = []
        self._touched: Set[str] = set()

    def check_module(self, mod: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        allowed = _path_in(mod, self.ALLOWED_RAW)
        for node in ast.walk(mod.tree):
            touched = _env_touch(node)
            if touched:
                self._touched.add(touched)
            got = raw_env_read(node)
            if got and not allowed:
                tw, site = got
                findings.append(mod.finding(
                    self.id, site,
                    f"raw environment read of {tw!r} — route it through "
                    "the typed registry (traceweaver_tpu.runtime.knobs."
                    "get_*), which parses, validates, and defaults in one "
                    "place"))
            if isinstance(node, ast.Call):
                tw = registry_read(node)
                if tw:
                    self._touched.add(tw)
                    self._registry_reads.append((tw, mod, node))
        return findings

    def _parse_registry(self, knobs_mod: Module) -> Dict[str, ast.AST]:
        decls: Dict[str, ast.AST] = {}
        for node in ast.walk(knobs_mod.tree):
            if (isinstance(node, ast.Call)
                    and dotted(node.func).split(".")[-1] in self._DECLS
                    and node.args):
                tw = _tw_name(node.args[0])
                if tw:
                    decls[tw] = node
        return decls

    def check_repo(self, modules: Sequence[Module]) -> Iterable[Finding]:
        knobs_mod = next((m for m in modules
                          if m.path.endswith("runtime/knobs.py")), None)
        if knobs_mod is None:
            return []  # partial scan without the registry: nothing to say
        decls = self._parse_registry(knobs_mod)
        findings: List[Finding] = []
        for tw, mod, node in self._registry_reads:
            if tw not in decls:
                findings.append(mod.finding(
                    self.id, node,
                    f"knob {tw!r} is read through the registry but never "
                    "declared in runtime/knobs.py — the read raises "
                    "KeyError at runtime; declare it typed + ranged"))
        for tw in sorted(set(decls) - self._touched):
            findings.append(knobs_mod.finding(
                self.id, decls[tw],
                f"knob {tw!r} is declared in the registry but read "
                "nowhere — dead configuration surface; delete the "
                "declaration or wire up the reader"))
        return findings


# ---------------------------------------------------------------------------
# TW002 — import-time knob freeze
# ---------------------------------------------------------------------------

class ImportTimeFreeze:
    """No module-scope ``TW_*`` reads inside the library.

    A knob read at import time is frozen before test fixtures or a
    launcher can export it (``monkeypatch.setenv`` after import is a
    no-op), which is exactly how ``ops/scores.py`` ``_USE_GEMM`` and
    ``algorithms/fleet.py`` ``FLEET_BUDGET_ELEMS`` went untestable.
    Library modules (``traceweaver_tpu/``) must read knobs at call time;
    one-shot scripts (bench.py, exps/) may keep module constants since
    their env is fixed at launch.
    """

    id = "TW002"
    title = "import-time TW_* read freezes the knob"

    ROOT = "traceweaver_tpu/"

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if self.ROOT not in mod.path:
            return []
        findings: List[Finding] = []

        def visit(node: ast.AST, in_func: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    visit(child, True)
                    continue
                if not in_func:
                    tw = None
                    got = raw_env_read(child)
                    if got:
                        tw = got[0]
                    elif isinstance(child, ast.Call):
                        tw = registry_read(child)
                    if tw:
                        findings.append(mod.finding(
                            self.id, child,
                            f"module-scope read of {tw!r} freezes the knob "
                            "at import time (env changes and test "
                            "fixtures can never reach it) — read it at "
                            "call time, keeping a plain module attribute "
                            "only as an explicit test-override hook"))
                visit(child, in_func)

        visit(mod.tree, False)
        return findings


# ---------------------------------------------------------------------------
# TW003 — host-sync hazard
# ---------------------------------------------------------------------------

class HostSyncHazard:
    """Device→host conversions in hot-path modules only at ledgered
    fetch sites.

    The PR-3 pipeline exists because an unledgered blocking fetch stalls
    the dispatch flow invisibly: ``np.asarray(device_handle)`` blocks on
    device execution and D2H without billing ``wait_s`` or the
    ``d2h_bytes_*`` ledger, so the stall never shows up in stats and the
    overlap math silently lies. In the hot modules every conversion of a
    value produced by a device call must go through the ledgered helper
    (``fleet._fetch``) or carry a per-line justification.

    Mechanics: name-level taint, per function. Names bound (directly,
    via tuple unpack, loop/comprehension targets, or container append)
    from calls matching the device-producer patterns (``solve_*``,
    ``refit_*``, ``fused_*``, ``device_put``) are device handles;
    ``np.asarray`` / ``np.array`` / ``float()`` / ``.item()`` over a
    tainted value is a finding. ``_fetch`` launders taint — its result
    is host memory, already billed.
    """

    id = "TW003"
    title = "unledgered device sync in a hot-path module"

    HOT = ("algorithms/fleet.py", "algorithms/weaver_tpu.py",
           "stream/service.py")
    #: functions allowed to convert device handles: the ledgered
    #: helpers (_fetch_flags wraps _fetch with the mesh shard fan-in)
    ALLOWED_FUNCS = ("_fetch", "_fetch_flags")
    #: the helper named in finding messages (subclasses re-point it)
    LEDGER_HINT = "fleet._fetch"
    _DEVICE_RE = re.compile(r"^(solve_|refit_|fused_)")
    _DEVICE_EXACT = {"jax.device_put", "device_put"}
    _CONVERSIONS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "float"}
    _LAUNDER = {"_fetch", "_fetch_flags", "np.asarray", "np.array",
                "numpy.asarray", "numpy.array", "float"}

    def _is_device_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted(node.func)
        last = name.split(".")[-1]
        return bool(self._DEVICE_RE.match(last)) or name in self._DEVICE_EXACT

    def _value_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Does evaluating this expression yield (or contain) a device
        handle? Laundering calls (``_fetch``, the conversions themselves)
        yield host arrays, so the walk does not descend into them."""
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name.split(".")[-1] in self._LAUNDER or name in self._LAUNDER:
                return False
        if self._is_device_call(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in tainted
        for child in ast.iter_child_nodes(node):
            if self._value_tainted(child, tainted):
                return True
        return False

    @staticmethod
    def _target_names(target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in target.elts:
                out.extend(HostSyncHazard._target_names(elt))
            return out
        return []

    def _collect_taints(self, fn: ast.AST) -> Set[str]:
        tainted: Set[str] = set()
        for _ in range(4):  # small fixpoint: taint chains are short
            before = len(tainted)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self._value_tainted(node.value, tainted):
                        for t in node.targets:
                            tainted.update(self._target_names(t))
                elif isinstance(node, ast.AnnAssign) and node.value:
                    if self._value_tainted(node.value, tainted):
                        tainted.update(self._target_names(node.target))
                elif isinstance(node, ast.For):
                    if self._value_tainted(node.iter, tainted):
                        tainted.update(self._target_names(node.target))
                elif isinstance(node, ast.comprehension):
                    if self._value_tainted(node.iter, tainted):
                        tainted.update(self._target_names(node.target))
                elif isinstance(node, ast.Call):
                    # pending.append((packed, out)) taints `pending`
                    name = dotted(node.func)
                    if name.split(".")[-1] in ("append", "extend", "insert"):
                        base = node.func.value if isinstance(
                            node.func, ast.Attribute) else None
                        if isinstance(base, ast.Name) and any(
                                self._value_tainted(a, tainted)
                                for a in node.args):
                            tainted.add(base.id)
            if len(tainted) == before:
                break
        return tainted

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not _path_in(mod, self.HOT):
            return []
        findings: List[Finding] = []
        for fn in outer_functions(mod.tree):
            if fn.name in self.ALLOWED_FUNCS:
                continue
            tainted = self._collect_taints(fn)
            if not tainted:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name in self._CONVERSIONS and node.args and \
                        self._value_tainted(node.args[0], tainted):
                    findings.append(mod.finding(
                        self.id, node,
                        f"{name}() over a device handle blocks on device "
                        "execution + D2H without billing wait_s / "
                        "d2h_bytes_* — fetch through the ledgered helper "
                        f"({self.LEDGER_HINT}) or justify with a "
                        "suppression"))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args
                        and self._value_tainted(node.func.value, tainted)):
                    findings.append(mod.finding(
                        self.id, node,
                        ".item() over a device handle is an unledgered "
                        f"blocking sync — fetch through {self.LEDGER_HINT}"))
        return findings


# ---------------------------------------------------------------------------
# TW004 — jit / recompile discipline
# ---------------------------------------------------------------------------

class RecompileDiscipline:
    """Precision/pallas-style arguments are static jit args; pow2
    bucketing is never re-implemented inline.

    (a) ``precision`` and ``pallas``/``allow_pallas`` select different
    device programs (PR 4 made precision a static arg precisely so f32
    compiles the historical program bit-identically; the supervisor's
    Pallas-free rung needs its own cache entry). A jit call site that
    takes such a parameter without declaring it static either fails at
    trace time (string arg) or, worse, bakes one variant's program into
    the other's cache key.

    (b) Dispatch shapes must come from the shared pow2 bucketing helpers
    (``runtime/bucketing.pow2_bucket`` and its wrappers
    ``weaver_tpu._bucket`` / ``mesh.bucket_rows_per_shard``) so the
    zero-recompile smoke keeps meaning something; an inline
    ``1 << (n - 1).bit_length()`` is a second implementation of the
    contract that can drift (and did — ``algorithms/timing.py``).
    """

    id = "TW004"
    title = "jit static-arg / pow2-bucketing discipline"

    SENSITIVE = {"precision", "pallas", "allow_pallas", "interpret",
                 "method"}
    BUCKET_MODULES = ("runtime/bucketing.py",)

    # -- (a) static args ----------------------------------------------------

    @staticmethod
    def _is_jax_jit(node: ast.AST) -> bool:
        return dotted(node) in ("jax.jit", "jit")

    @staticmethod
    def _static_names(call: ast.Call, params: List[str]) -> Set[str]:
        static: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    s = const_str(v)
                    if s:
                        static.add(s)
            elif kw.arg == "static_argnums":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, int) and 0 <= v.value < len(params):
                        static.add(params[v.value])
        return static

    @staticmethod
    def _params(args: ast.arguments) -> List[str]:
        return [a.arg for a in args.posonlyargs + args.args
                + args.kwonlyargs]

    def _check_site(self, mod: Module, site: ast.AST, jit_call,
                    fn_args: ast.arguments) -> Iterable[Finding]:
        params = self._params(fn_args)
        static = (self._static_names(jit_call, params)
                  if isinstance(jit_call, ast.Call) else set())
        for p in params:
            if p in self.SENSITIVE and p not in static:
                yield mod.finding(
                    self.id, site,
                    f"jit call site takes {p!r} without declaring it in "
                    "static_argnames/static_argnums — precision/pallas-"
                    "class arguments select distinct device programs and "
                    "must be static (PR 4 contract, docs/PERF.md)")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        fn_defs: Dict[str, ast.arguments] = {
            f.name: f.args
            for f in ast.walk(mod.tree)
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jax_jit(dec):
                        findings.extend(self._check_site(
                            mod, dec, None, node.args))
                    elif isinstance(dec, ast.Call):
                        if self._is_jax_jit(dec.func):
                            findings.extend(self._check_site(
                                mod, dec, dec, node.args))
                        elif (dotted(dec.func).split(".")[-1] == "partial"
                              and dec.args
                              and self._is_jax_jit(dec.args[0])):
                            findings.extend(self._check_site(
                                mod, dec, dec, node.args))
            elif (isinstance(node, ast.Call) and self._is_jax_jit(node.func)
                    and node.args and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in fn_defs):
                findings.extend(self._check_site(
                    mod, node, node, fn_defs[node.args[0].id]))
            # -- (b) inline pow2 bucketing --------------------------------
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.LShift)
                    and isinstance(node.right, ast.Call)
                    and isinstance(node.right.func, ast.Attribute)
                    and node.right.func.attr == "bit_length"
                    and not _path_in(mod, self.BUCKET_MODULES)):
                findings.append(mod.finding(
                    self.id, node,
                    "inline power-of-two bucketing (`1 << "
                    "(...).bit_length()`) bypasses the shared helpers — "
                    "use traceweaver_tpu.runtime.bucketing.pow2_bucket "
                    "(or weaver_tpu._bucket / mesh.bucket_rows_per_shard) "
                    "so dispatch shapes share ONE bucketing contract"))
        return findings


# ---------------------------------------------------------------------------
# TW005 — lock discipline
# ---------------------------------------------------------------------------

class LockDiscipline:
    """Attributes guarded by a class's lock are guarded everywhere.

    ``fleet._Stats`` exists because pack threads, decode workers, and
    the serve pump all mutate shared state (PR 3/6); a single bare
    ``self.d[k] = ...`` outside the lock re-introduces the silent
    dropped-count race the accumulator was built to kill. For every
    class that owns a ``threading.Lock``/``RLock``/``Condition``
    attribute, any attribute that is ever written under ``with
    self.<lock>`` must be written under it in every method
    (``__init__`` excepted — construction happens-before publication).
    Nested functions count as unlocked even when lexically inside a
    ``with`` block: closures outlive the critical section (the pipeline
    submits them to worker pools).
    """

    id = "TW005"
    title = "lock-guarded attribute written without the lock"

    _LOCK_CTORS = {"threading.Lock", "threading.RLock",
                   "threading.Condition", "Lock", "RLock", "Condition"}
    _MUTATORS = {"append", "extend", "add", "update", "setdefault", "pop",
                 "popleft", "clear", "remove", "discard", "insert",
                 "appendleft"}

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and dotted(node.value.func) in self._LOCK_CTORS):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        locks.add(t.attr)
        return locks

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        """`self.X` → X; `self.X[...]` → X; else None."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _writes(self, method: ast.FunctionDef, locks: Set[str]
                ) -> List[Tuple[str, bool, ast.AST]]:
        """(attr, under_lock, site) for every self-attribute write."""
        out: List[Tuple[str, bool, ast.AST]] = []

        def visit(node: ast.AST, locked: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_locked = locked
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    # a closure's body runs whenever it is CALLED — the
                    # enclosing with-block guards nothing about that
                    visit(child, False)
                    continue
                if isinstance(child, ast.With):
                    holds = any(
                        self._self_attr(item.context_expr) in locks
                        for item in child.items)
                    child_locked = locked or holds
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                                else [t])
                        for e in elts:
                            attr = self._self_attr(e)
                            if attr:
                                out.append((attr, child_locked, child))
                elif (isinstance(child, ast.Call)
                        and isinstance(child.func, ast.Attribute)
                        and child.func.attr in self._MUTATORS):
                    attr = self._self_attr(child.func.value)
                    if attr:
                        out.append((attr, child_locked, child))
                visit(child, child_locked)

        visit(method, False)
        return out

    def check_module(self, mod: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(mod.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks = self._lock_attrs(cls)
            if not locks:
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            writes = {m.name: self._writes(m, locks) for m in methods}
            guarded: Set[str] = {
                attr
                for name, ws in writes.items() if name != "__init__"
                for attr, locked, _ in ws if locked}
            guarded -= locks
            for name, ws in writes.items():
                if name == "__init__":
                    continue
                for attr, locked, site in ws:
                    if attr in guarded and not locked:
                        findings.append(mod.finding(
                            self.id, site,
                            f"self.{attr} is written under `with "
                            f"self.{'/'.join(sorted(locks))}` elsewhere in "
                            f"class {cls.name} but not here — an unlocked "
                            "read-modify-write silently drops updates "
                            "under the pipelined dispatcher (PR 3 "
                            "contract; fleet._Stats is the pattern)"))
        return findings


# ---------------------------------------------------------------------------
# TW006 — precision discipline
# ---------------------------------------------------------------------------

class PrecisionDiscipline:
    """bf16 is storage-only: accumulation happens in f32.

    The PR-4 contract: score blocks may be STORED bfloat16, but every
    accumulating op (sum/cumsum/dot/logsumexp/...) runs f32 — bf16's
    8-bit mantissa loses whole spans' worth of log-density mass when
    hundreds of window cells reduce into one scalar. In ``ops/``,
    feeding a value cast to bf16 into an accumulating op without an f32
    upcast (or a ``preferred_element_type`` f32 accumulator on the
    matmul forms) is a finding.
    """

    id = "TW006"
    title = "accumulating op over a bf16 block without f32 accumulation"

    OPS_DIR = "ops/"
    ACCUM = {"sum", "cumsum", "dot", "tensordot", "matmul", "einsum",
             "logsumexp", "mean", "prod", "cumprod", "dot_general"}

    @staticmethod
    def _is_bf16_cast(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and len(node.args) == 1
                and dotted(node.args[0]).split(".")[-1] in ("bfloat16",)
                )

    @staticmethod
    def _is_f32_cast(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and len(node.args) == 1
                and dotted(node.args[0]).split(".")[-1] in (
                    "float32", "float64"))

    def _value_bf16(self, node: ast.AST, tainted: Set[str]) -> bool:
        if self._is_f32_cast(node):
            return False  # explicit upcast launders
        if isinstance(node, ast.Call) and self._has_f32_accumulator(node):
            return False  # f32-accumulated matmul yields f32
        if self._is_bf16_cast(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in tainted
        for child in ast.iter_child_nodes(node):
            if self._value_bf16(child, tainted):
                return True
        return False

    def _collect(self, fn: ast.AST) -> Set[str]:
        tainted: Set[str] = set()
        for _ in range(4):
            before = len(tainted)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and self._value_bf16(
                        node.value, tainted):
                    for t in node.targets:
                        tainted.update(HostSyncHazard._target_names(t))
            if len(tainted) == before:
                break
        return tainted

    @staticmethod
    def _has_f32_accumulator(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "preferred_element_type":
                return dotted(kw.value).split(".")[-1] not in ("bfloat16",)
        return False

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if self.OPS_DIR not in mod.path:
            return []
        findings: List[Finding] = []
        for fn in outer_functions(mod.tree):
            tainted = self._collect(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name:
                    last = name.split(".")[-1]
                elif isinstance(node.func, ast.Attribute):
                    # method form on a non-Name root: expr.sum()
                    last = node.func.attr
                else:
                    continue
                if last not in self.ACCUM:
                    continue
                if self._has_f32_accumulator(node):
                    continue
                hot = any(self._value_bf16(a, tainted) for a in node.args)
                if not hot and isinstance(node.func, ast.Attribute):
                    # method form: x_bf16.sum()
                    hot = self._value_bf16(node.func.value, tainted)
                if hot:
                    findings.append(mod.finding(
                        self.id, node,
                        f"{last}() accumulates a bfloat16 block — bf16 is "
                        "storage-only (PR 4 contract, docs/PERF.md): "
                        "upcast with .astype(jnp.float32) first, or pass "
                        "preferred_element_type=jnp.float32 on the matmul "
                        "forms"))
        return findings


# ---------------------------------------------------------------------------
# TW007 — metric discipline
# ---------------------------------------------------------------------------

class MetricDiscipline:
    """Counters in the telemetry-bearing modules grow only through the
    obs-mirrored accumulators.

    The obs registry (``traceweaver_tpu/obs``, PR 9) exists because the
    ledgers lived in ad-hoc dicts nothing could scrape; every sanctioned
    accumulator (``fleet._Stats.add/merge/note/bucket/record_max``, the
    stream/serve ``_bump`` helpers) now mirrors into the registry, so a
    NEW bare ``stats[k] += 1`` or ``d[k] = d.get(k, 0) + v`` in
    ``algorithms/fleet.py`` / ``stream/`` / ``serve/`` is a counter the
    scrape surface silently never sees — exactly the blind spot this PR
    closed. Module-level counter-table dicts (``_COUNTERS = {"x": 0}``)
    in those modules are the same hazard at module scope.

    Narrow by design: attribute counters (``self.shed_spilled += 1``)
    are typed object state with explicit mirror sites and are not
    flagged; dict read-modify-writes outside a sanctioned accumulator
    method are.
    """

    id = "TW007"
    title = "ad-hoc counter growth outside the obs-mirrored accumulators"

    #: telemetry-bearing modules the registry must see completely
    WATCH_FILES = ("algorithms/fleet.py",)
    WATCH_DIRS = ("traceweaver_tpu/stream/", "traceweaver_tpu/serve/")
    #: accumulator methods whose body IS the sanctioned write path
    SANCTIONED = {"add", "merge", "note", "bucket", "record_max",
                  "_bump", "bump", "inc", "observe", "set", "set_max"}

    def _watched(self, mod: Module) -> bool:
        return (_path_in(mod, self.WATCH_FILES)
                or any(d in mod.path for d in self.WATCH_DIRS))

    @staticmethod
    def _numeric_const(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, (int, float))
                and not isinstance(node.value, bool))

    def _is_counter_table(self, node: ast.AST) -> bool:
        """``{"x": 0, "y": 0}`` — a dict literal whose values are all
        numeric constants (at least one entry)."""
        return (isinstance(node, ast.Dict) and node.values
                and all(self._numeric_const(v) for v in node.values))

    @staticmethod
    def _get_rmw(node: ast.Assign) -> bool:
        """``d[k] = d.get(k, 0) + v`` (either operand order): the target
        is a subscript and the value contains a ``.get`` call on the
        same receiver expression."""
        if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Subscript):
            return False
        base_dump = ast.dump(node.targets[0].value)
        if not isinstance(node.value, ast.BinOp) or not isinstance(
                node.value.op, ast.Add):
            return False
        for side in (node.value.left, node.value.right):
            if (isinstance(side, ast.Call)
                    and isinstance(side.func, ast.Attribute)
                    and side.func.attr == "get"
                    and ast.dump(side.func.value) == base_dump):
                return True
        return False

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not self._watched(mod):
            return []
        findings: List[Finding] = []

        # (a) module-scope counter tables
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and self._is_counter_table(
                    stmt.value):
                findings.append(mod.finding(
                    self.id, stmt,
                    "module-level counter dict — a private ledger the "
                    "metrics registry never sees; declare a counter on "
                    "traceweaver_tpu.obs (or mirror through the "
                    "sanctioned accumulators) instead"))

        # (b)/(c) counter read-modify-writes outside sanctioned methods
        def visit(node: ast.AST, sanctioned: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_ok = sanctioned
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_ok = child.name in self.SANCTIONED
                if (not sanctioned
                        and isinstance(child, ast.AugAssign)
                        and isinstance(child.op, ast.Add)
                        and isinstance(child.target, ast.Subscript)):
                    findings.append(mod.finding(
                        self.id, child,
                        "`...[key] += n` outside the obs-mirrored "
                        "accumulators — this count never reaches the "
                        "metrics registry (/metrics blind spot); route "
                        "it through _Stats/_bump or an obs counter"))
                elif (not sanctioned and isinstance(child, ast.Assign)
                        and self._get_rmw(child)):
                    findings.append(mod.finding(
                        self.id, child,
                        "`d[k] = d.get(k, ...) + v` outside the "
                        "obs-mirrored accumulators — this count never "
                        "reaches the metrics registry (/metrics blind "
                        "spot); route it through _Stats/_bump or an obs "
                        "counter"))
                visit(child, child_ok)

        visit(mod.tree, False)
        return findings


# ---------------------------------------------------------------------------
# TW008 — packed-block channel layout discipline
# ---------------------------------------------------------------------------

class ChannelLayoutDiscipline:
    """Packed-block channel indices come from ``algorithms/packed_layout.py``.

    The packed solver output multiplexes per-span channels on its last
    axis (``[B, E, W, N_FIXED + topk (+ conf)]``); the indices used to
    live as magic ``0``/``1``/``2``/``3:`` literals duplicated across the
    ``weaver_tpu`` and ``fleet`` decoders. That duplication is a silent
    data-corruption hazard: growing the block (the confidence channels
    did) shifts the top-k base, and a stale literal decodes margins as
    top-k candidate columns without any error. ``packed_layout.py`` is
    now the single source of truth (named constants +
    ``split_packed``); this rule flags raw trailing-axis integer
    subscripts — ``x[..., 2]``, ``x[..., 3:]`` — in the modules that
    touch packed blocks.

    Narrow by design: only Ellipsis-leading subscripts with an integer
    constant (or an integer-bounded slice) are channel accesses;
    ``x[..., None]`` (axis insertion) and explicit-dim indexing like
    ``arr[:, :, 0]`` on non-packed tensors are untouched, and only the
    packed-block-bearing modules are watched.
    """

    id = "TW008"
    title = "raw packed-block channel index outside packed_layout.py"

    #: modules that decode/construct packed solver blocks
    WATCH_FILES = ("algorithms/weaver_tpu.py", "algorithms/fleet.py",
                   "obs/quality.py")
    #: the layout module itself is the one legitimate home of the indices
    ALLOWED = ("algorithms/packed_layout.py",)

    @staticmethod
    def _int_const(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and not isinstance(node.value, bool))

    def _channel_elt(self, node: ast.AST) -> bool:
        if self._int_const(node):
            return True
        if isinstance(node, ast.Slice):
            return any(part is not None and self._int_const(part)
                       for part in (node.lower, node.upper))
        return False

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not _path_in(mod, self.WATCH_FILES) or _path_in(mod, self.ALLOWED):
            return []
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Subscript):
                continue
            sl = node.slice
            if not (isinstance(sl, ast.Tuple) and sl.elts
                    and isinstance(sl.elts[0], ast.Constant)
                    and sl.elts[0].value is Ellipsis):
                continue
            if any(self._channel_elt(e) for e in sl.elts[1:]):
                findings.append(mod.finding(
                    self.id, node,
                    "raw channel index on a packed-block trailing axis — "
                    "use the named constants / split_packed from "
                    "traceweaver_tpu.algorithms.packed_layout (the single "
                    "source of truth for the [*, 3+topk(+conf)] layout)"))
        return findings


# ---------------------------------------------------------------------------
# TW009 — device-resident column discipline
# ---------------------------------------------------------------------------

class DevcolsResidency(HostSyncHazard):
    """Ring-resident columns materialize on host only through the
    ledgered fetch.

    The device-resident span-column path (``TW_DEVCOLS``,
    :mod:`traceweaver_tpu.ops.devcols`) exists so the window tensors
    never cross the host↔device tunnel: the ring buffers live in HBM
    and :func:`~traceweaver_tpu.ops.devcols.assemble_windows` gathers
    from them on device. A bare ``np.asarray`` over a ring buffer or an
    assembled window tensor silently re-ships the very data the path
    keeps resident — and, worse, bills nothing, so the ``h2d``/``d2h``
    byte ledger (the resident path's honesty contract) lies. Host
    copies of resident values go through
    ``ops/devcols.fetch_resident`` (``d2h_bytes_resident``) or the
    fleet's ``_fetch``.

    Same name-taint mechanics as TW003; the taint SOURCES here are the
    devcols programs (``assemble_windows``/``ring_append``) and ``.buf``
    ring-buffer attribute reads.
    """

    id = "TW009"
    title = "unledgered host copy of ring-resident device columns"

    HOT = ("algorithms/fleet.py", "algorithms/weaver_tpu.py",
           "stream/service.py", "ops/devcols.py")
    ALLOWED_FUNCS = ("_fetch", "fetch_resident")
    LEDGER_HINT = "ops/devcols.fetch_resident"
    _DEVICE_RE = re.compile(r"^(assemble_|ring_append$)")
    _DEVICE_EXACT: set = set()
    _LAUNDER = {"_fetch", "fetch_resident", "np.asarray", "np.array",
                "numpy.asarray", "numpy.array", "float"}

    def _is_device_call(self, node: ast.AST) -> bool:
        # a ring buffer read (`ring.buf`) is resident data, call or not
        if isinstance(node, ast.Attribute) and node.attr == "buf":
            return True
        return super()._is_device_call(node)


# ---------------------------------------------------------------------------
# TW010 — adaptation actuation discipline
# ---------------------------------------------------------------------------

class AdaptLedgerDiscipline:
    """Adaptation actuations route through the evented ledger.

    The drift→adapt controller (``traceweaver_tpu/adapt``, PR 12)
    closes a CONTROL loop over production traffic: a refit replaces a
    service's carried score statistics, a fallback swaps its score
    model for wide priors. An unledgered actuation is a silent state
    transition — the operator sees reconstruction quality change with
    no ``tw_adapt_actions_total`` increment and no ``TW_EVENTS`` record
    explaining why, which is exactly the debugging hole the PR 10
    sensors were built to close. Two checks:

    - inside ``traceweaver_tpu/adapt/``: a function that calls an
      actuation primitive (``solve_fleet`` — the out-of-band refit
      dispatch — or ``refit_from_assignments`` — the statistics
      install) must also call the evented ledger (``_act`` directly or
      ``refit_done``, whose body is ledgered) in the same function; a
      bare refit path cannot land unannounced;
    - everywhere else: underscore-private controller internals must not
      be called through an ``.adapt`` receiver — consumers (stream
      pump, serve dispatcher) drive the controller only through its
      public, evented API (``observe``/``pending_refits``/
      ``begin_refit``/``refit_done``/``warm_dists``), so no consumer
      can flip a rung without the ledger seeing it.

    Narrow by design: ``stream/service.py``'s per-window
    ``refit_from_assignments`` (the ordinary warm-state refresh) is not
    an adaptation actuation and is untouched — the primitive check
    applies only inside ``adapt/``.
    """

    id = "TW010"
    title = "adaptation actuation outside the evented ledger"

    ADAPT_DIR = "traceweaver_tpu/adapt/"
    #: the actuation primitives (refit dispatch + statistics install)
    ACTUATIONS = {"solve_fleet", "refit_from_assignments"}
    #: the evented ledger entry points (refit_done's body calls _act)
    LEDGER = {"_act", "refit_done"}

    @staticmethod
    def _call_name(node: ast.Call) -> str:
        if isinstance(node.func, ast.Attribute):
            return node.func.attr
        if isinstance(node.func, ast.Name):
            return node.func.id
        return ""

    def _check_adapt(self, mod: Module) -> Iterable[Finding]:
        findings: List[Finding] = []

        def top_functions(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    yield child
                elif isinstance(child, ast.ClassDef):
                    yield from top_functions(child)

        for fn in top_functions(mod.tree):
            actuations = []
            ledgered = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = self._call_name(node)
                if name in self.ACTUATIONS:
                    actuations.append(node)
                elif name in self.LEDGER:
                    ledgered = True
            if actuations and not ledgered:
                for node in actuations:
                    findings.append(mod.finding(
                        self.id, node,
                        "adaptation actuation primitive outside a "
                        "ledgered function — every refit/fallback path "
                        "in adapt/ must land in the evented ledger "
                        "(_act / refit_done): no silent state "
                        "transitions (docs/ROBUSTNESS.md)"))
        return findings

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if self.ADAPT_DIR in mod.path:
            return self._check_adapt(mod)
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr.startswith("_")
                    and isinstance(node.func.value, ast.Attribute)
                    and node.func.value.attr == "adapt"):
                continue
            findings.append(mod.finding(
                self.id, node,
                "private adaptation-controller internal called outside "
                "adapt/ — consumers drive the controller through its "
                "public evented API only (observe/pending_refits/"
                "begin_refit/refit_done/warm_dists), so every rung "
                "transition reaches the ledger"))
        return findings


# ---------------------------------------------------------------------------
# TW011 — AOT compile discipline
# ---------------------------------------------------------------------------

class AotCompileDiscipline:
    """``.lower().compile()`` / compile-cache config writes live only in
    ``runtime/aot.py`` + ``runtime/jax_cache.py``.

    The AOT shape lattice (ISSUE 14) is the single source of
    precompiled variants: every ahead-of-time compile goes through the
    lattice enumerator so the miss ledger, the ``/readyz`` gate, and
    the ``tw_aot_*`` telemetry see the complete precompile surface. A
    stray ``entry.lower(...).compile()`` elsewhere is an unledgered
    program the readiness gate doesn't know it is waiting for (or
    worse, not waiting for); a stray
    ``jax.config.update("jax_compilation_cache_dir", ...)`` forks the
    persistent-cache location away from the host-keyed directory that
    ``jax_cache.py`` namespaces (the round-3 poisoned-cache lesson).

    Mechanics: flags (a) a ``.compile()`` call whose receiver is a
    ``.lower(...)`` call (the chained idiom), (b) a ``.compile()`` call
    on a name bound from a ``.lower(...)`` call in the same function
    (the two-statement form), and (c) ``jax.config.update`` with a
    first-argument string starting ``jax_compilation_cache`` /
    ``jax_persistent_cache``. String ``.lower()`` is untouched — only a
    ``.compile`` on the lowered VALUE matches, and strings have none.
    """

    id = "TW011"
    title = "AOT lower/compile or compile-cache write outside the lattice"

    ALLOWED = ("runtime/aot.py", "runtime/jax_cache.py")
    _CACHE_PREFIXES = ("jax_compilation_cache", "jax_persistent_cache")

    @staticmethod
    def _is_lower_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "lower")

    def _flag(self, mod: Module, node: ast.AST) -> Finding:
        return mod.finding(
            self.id, node,
            "ahead-of-time .lower().compile() outside runtime/aot.py — "
            "the shape lattice is the single source of precompiled "
            "variants (miss ledger + /readyz gate); add the variant to "
            "the lattice enumerator instead of compiling it privately")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if _path_in(mod, self.ALLOWED):
            return []
        findings: List[Finding] = []
        # the whole module (module scope included): the chained form and
        # cache-config writes; then the two-statement form per function
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compile"
                    and self._is_lower_call(node.func.value)):
                findings.append(self._flag(mod, node))
            elif (dotted(node.func) in ("jax.config.update",
                                        "config.update")
                    and node.args):
                key = const_str(node.args[0])
                if key and key.startswith(self._CACHE_PREFIXES):
                    findings.append(mod.finding(
                        self.id, node,
                        f"compile-cache config write ({key!r}) outside "
                        "runtime/jax_cache.py — the cache directory is "
                        "namespaced per backend+host there (a foreign "
                        "location risks the round-3 poisoned-cache "
                        "failure); route it through "
                        "enable_persistent_compilation_cache"))
        for fn in outer_functions(mod.tree):
            lowered: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and self._is_lower_call(
                        node.value):
                    for t in node.targets:
                        lowered.update(HostSyncHazard._target_names(t))
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "compile"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in lowered):
                    findings.append(self._flag(mod, node))
        return findings


# ---------------------------------------------------------------------------
# TW012 — serve ticket discipline
# ---------------------------------------------------------------------------

class TicketDiscipline:
    """Per-tenant ``in_flight`` windows mutate only inside the ticket
    lifecycle.

    The overlapped serve drain (ISSUE 19) splits admit→solve→consume
    into tickets: ``submit_admitted`` takes windows off the tenant
    queues and records them in ``Tenant.in_flight`` (under the service
    lock), and ``_ring_retire_locked`` identity-removes exactly that
    ticket's windows when it retires (complete or abort, again under
    the lock). Everything between — retention pruning, checkpoint
    skip/barrier decisions, ``migrate_out``'s wait-for-retire, the
    flush barrier — only READS the list. A mutation anywhere else
    breaks the accounting both directions: windows vanish from
    ``in_flight`` while a worker still holds them (retention prunes a
    buffer mid-solve, a checkpoint captures a state the replay will
    double-count), or linger after retirement (drain and migration
    barriers deadlock waiting for a ticket that already completed).
    TW005 cannot see this — ``in_flight`` lives on ``Tenant``, not on
    the lock-owning service — so the lifecycle contract gets its own
    rule.

    Mechanics: flags any mutator-method call on ``<x>.in_flight``
    (the TW005 mutator set: append/extend/clear/remove/...) and any
    assignment or augmented assignment whose target is
    ``<x>.in_flight`` or ``<x>.in_flight[...]`` (the slice-assign
    retire idiom counts), unless the enclosing outer function is one
    of the lifecycle sites: ``__init__`` (construction), the submit
    half, or the retire helper.
    """

    id = "TW012"
    title = "in_flight mutated outside the ticket lifecycle"

    #: the only functions allowed to mutate in_flight — construction,
    #: the submit half (extend under the service lock), and the single
    #: retire helper both complete and abort funnel through
    LIFECYCLE = frozenset({"__init__", "submit_admitted",
                           "_ring_retire_locked"})
    ATTR = "in_flight"

    @classmethod
    def _inflight_attr(cls, node: ast.AST) -> bool:
        """``<x>.in_flight`` or ``<x>.in_flight[...]`` (any receiver —
        ``self``, a tenant local, a dict lookup)."""
        if isinstance(node, ast.Subscript):
            node = node.value
        return isinstance(node, ast.Attribute) and node.attr == cls.ATTR

    def check_module(self, mod: Module) -> Iterable[Finding]:
        exempt: Set[int] = set()
        for fn in outer_functions(mod.tree):
            if fn.name in self.LIFECYCLE:
                exempt.update(id(n) for n in ast.walk(fn))
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            hit = False
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
                    hit = hit or any(self._inflight_attr(e) for e in elts)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in LockDiscipline._MUTATORS):
                hit = self._inflight_attr(node.func.value)
            if hit and id(node) not in exempt:
                findings.append(mod.finding(
                    self.id, node,
                    "in_flight mutated outside the ticket lifecycle — "
                    "only submit_admitted (extend) and "
                    "_ring_retire_locked (identity removal) may touch "
                    "per-tenant in_flight, under the service lock; "
                    "anything else desyncs retention pruning, "
                    "checkpoint barriers, and migrate_out's "
                    "wait-for-retire (docs/SERVING.md, ticket "
                    "lifecycle)"))
        return findings


# ---------------------------------------------------------------------------
# TW013 — serve ack discipline
# ---------------------------------------------------------------------------

class AckDiscipline:
    """A 2xx ack on the serve ingest paths implies durability.

    The ingest WAL (ISSUE 20, ``stream/wal.py``) moves the front door's
    contract from "accepted into memory" to "accepted into the ledger":
    a client that got a 200 for a ``/spans`` or ``/capture`` POST may
    retire its send buffer, so the bytes behind that 200 must survive
    ``kill -9`` — which means the handler must have routed them through
    the WAL-appending service entry points (``wal_ingest`` /
    ``wal_ingest_capture``, which append + fsync-per-policy BEFORE
    applying) rather than the bare in-memory forms. The one legitimate
    bare-ingest ack is the explicit opt-out: a reply dominated by a
    ``TW_WAL`` guard (the knob's off-branch), where the operator chose
    no-durability on purpose and the byte-identity contract
    (``TW_WAL=0`` == pre-WAL wire behavior) requires the un-ledgered
    path to stay reachable.

    Mechanics: inside the serve HTTP front door, flags any
    ``self._reply(2xx, <payload>)`` whose payload expression contains a
    call to a bare ingest entry point (attribute name ``ingest`` /
    ``ingest_capture``), unless the reply sits under an ``if`` whose
    test mentions the ``TW_WAL`` constant (either branch — the guard IS
    the documentation) — the ledgered ``wal_ingest*`` forms are always
    clean. Narrow by design: only the ingest attribute names are
    acked-durability surfaces; stats/flush/tenant-admin replies return
    derived state a retry can rebuild and are untouched.
    """

    id = "TW013"
    title = "unledgered 2xx ack on a serve ingest path"

    WATCH_FILES = ("serve/http.py",)
    #: bare in-memory ingest entry points — acking these without a
    #: TW_WAL guard promises durability the process cannot deliver
    INGEST = {"ingest", "ingest_capture"}
    #: the ledgered forms (append + policy fsync before apply)
    LEDGERED = {"wal_ingest", "wal_ingest_capture"}

    @staticmethod
    def _mentions_wal(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Constant) and n.value == "TW_WAL"
                   for n in ast.walk(node))

    @staticmethod
    def _ack_payload(node: ast.AST) -> Optional[ast.AST]:
        """The payload expression of a ``*._reply(2xx, payload)`` call,
        else None."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_reply"
                and len(node.args) >= 2):
            return None
        code = node.args[0]
        if not (isinstance(code, ast.Constant)
                and isinstance(code.value, int)
                and 200 <= code.value < 300):
            return None
        return node.args[1]

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not _path_in(mod, self.WATCH_FILES):
            return []
        findings: List[Finding] = []

        def visit(node: ast.AST, guarded: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_guarded = guarded
                if isinstance(child, ast.If) and self._mentions_wal(
                        child.test):
                    child_guarded = True
                payload = self._ack_payload(child)
                if payload is not None and not child_guarded:
                    for n in ast.walk(payload):
                        if (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Attribute)
                                and n.func.attr in self.INGEST):
                            findings.append(mod.finding(
                                self.id, child,
                                f"2xx ack over bare {n.func.attr}() — a "
                                "200 on an ingest path promises the "
                                "client its bytes survive kill -9; route "
                                "through the ledgered wal_ingest* entry "
                                "points, or put the reply under an "
                                "explicit TW_WAL guard (the no-"
                                "durability opt-out must be a visible "
                                "operator choice, docs/ROBUSTNESS.md "
                                "Durability)"))
                            break
                visit(child, child_guarded)

        visit(mod.tree, False)
        return findings


#: registration order == reporting order for same-line findings
RULE_CLASSES = [KnobDiscipline, ImportTimeFreeze, HostSyncHazard,
                RecompileDiscipline, LockDiscipline, PrecisionDiscipline,
                MetricDiscipline, ChannelLayoutDiscipline,
                DevcolsResidency, AdaptLedgerDiscipline,
                AotCompileDiscipline, TicketDiscipline, AckDiscipline]
