"""``python -m traceweaver_tpu.analysis`` — the twlint CLI.

Exit status: 0 = clean (suppressed/baselined findings don't count),
1 = live findings, 2 = bad invocation or malformed baseline.
"""

from __future__ import annotations

import argparse
import sys

from traceweaver_tpu.analysis import engine
from traceweaver_tpu.analysis.rules import RULE_CLASSES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m traceweaver_tpu.analysis",
        description="twlint: static analysis of the repo's knob, "
                    "precision, recompile, host-sync, and lock contracts "
                    "(docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan, relative to the repo "
                        "root (default: the whole repo)")
    p.add_argument("--root", default=engine.REPO_ROOT,
                   help="repo root (default: autodetected from the "
                        "installed package)")
    p.add_argument("--baseline", default=engine.DEFAULT_BASELINE,
                   help="baseline file of grandfathered findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--write-baseline", action="store_true",
                   help="print a baseline covering the current findings "
                        "to stdout (justifications left TODO) and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.id}  {cls.title}")
        return 0
    baseline = None if args.no_baseline or args.write_baseline \
        else args.baseline
    try:
        report = engine.run(root=args.root, paths=args.paths or None,
                            baseline_path=baseline)
    except engine.BaselineError as e:
        print(f"twlint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        sys.stdout.write(engine.format_baseline(report.findings))
        return 0
    print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
