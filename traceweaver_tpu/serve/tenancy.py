"""Shared-fleet tenancy: many tenants' windows, one dispatch stream.

The stream subsystem is a single-app pipeline; this module multiplexes N
of them. Each tenant owns a full per-tenant reconstruction pipeline —
watermark, windowing engine, live span store, carried warm-start
statistics, sink/dead-letter files, bounded emitted-trace ring — wrapped
around an externally-pumped
:class:`~traceweaver_tpu.stream.service.StreamingReconstructor`. What
tenants SHARE is the device: the :class:`TenantService` pump collects
every healthy tenant's sealed-window batches, builds their
:class:`~traceweaver_tpu.algorithms.fleet.FleetItem` lists (tagged with
the tenant id — the id column fleet's pack/compaction/decode carries —
and carrying each window's pre-built :class:`SpanArray` column slices,
so a pump's pack path is pure array work: the shared micro-batch
builder hands windows over columnar, ``TW_COLUMNAR``, docs/PERF.md),
and rides them all through ONE :func:`solve_fleet` call, so tenants with
similar window geometry land in the same padded shape class and the
dispatch count stays O(shape classes), not O(tenants) — the whole point
of serving from a fleet (the ``fleet_dispatches`` ledger proves it:
fewer dispatch groups than a tenant-serial loop, tests/test_serve.py).

Isolation is explicit, per tenant:

- **backpressure**: each tenant has its own pending bound -> spill queue
  -> counted shed (``TW_SERVE_PENDING`` / ``TW_SERVE_SPILL``); one
  tenant's ingest burst fills one tenant's queues;
- **fault storms**: a tenant with a ``fault_spec`` (or one the
  supervisor quarantines repeatedly) solves in its OWN dispatches under
  :func:`faults.override`, so its retries/bisections/quarantines never
  occupy the shared dispatch stream — neighbors keep their throughput
  (the bench ``--serve-tenants`` isolation leg measures exactly this);
- **quarantine/dead-letter accounting**: a quarantined window
  dead-letters into its OWN tenant's sidecar and counters, preserving
  per-tenant conservation (emitted + dead-lettered == sealed windows);
- **checkpoints**: per-tenant files under ``state_dir/<tenant>/``;
  graceful drain checkpoints every tenant (time-boxed by
  ``TW_SERVE_DRAIN_S``) and a restarted service resumes all of them with
  zero lost windows — still-open window buffers ride the checkpoint, so
  nothing depends on a replayable source (HTTP ingest has none).

See docs/SERVING.md for the operator view and the HTTP surface
(:mod:`traceweaver_tpu.serve.http`).
"""

from __future__ import annotations

import base64
import json
import os
import queue
import re
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.ingest.jaeger import (
    FIX_ROOT_OPS,
    MalformedSpan,
    parse_trace_payload,
)
from traceweaver_tpu.ingest import wire as _wire
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs import quality as _quality
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.ops.precision import precision_from_env
from traceweaver_tpu.query.delay_culprit import live_delay_culprit
from traceweaver_tpu.runtime import knobs
from traceweaver_tpu.serve.ring import TraceRing, build_trace_records
from traceweaver_tpu.stream import wal as _walmod
from traceweaver_tpu.stream.checkpoint import (
    load_checkpoint,
    read_checkpoint_bytes,
    save_checkpoint,
    write_checkpoint_bytes,
)
from traceweaver_tpu.stream.service import (
    StreamConfig,
    StreamingReconstructor,
    TraceSink,
)
from traceweaver_tpu.stream.sources import SpanEvent

_TENANT_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}")

#: durable migration tombstone, one per moved-out tenant dir: survives a
#: restart so ``TenantService.resume`` re-tombstones instead of minting
#: a forked twin from whatever files the tenant left behind
MIGRATED_MARKER = "migrated_out.json"

#: client-seq dedup window depth per tenant: how many recently applied
#: client seqs a retried POST can be answered from without re-ingesting
#: (past it the oldest entries roll off — a client that retries an ack
#: lost 4096 accepted POSTs ago is outside any real retry policy)
WAL_DEDUP_WINDOW = 4096

# obs registry mirrors (docs/OBSERVABILITY.md): per-tenant counters and
# the service-wide pump ledger. /metrics does NOT scrape these mirrors
# for the per-tenant surface — it scrapes TenantService.metrics_families
# (derived from the same stats() dicts at request time) so the exposed
# values equal /api/v1/stats by construction.
_OBS_TENANT_LEDGER = _get_registry().counter(
    "tw_serve_tenant_ledger_total",
    "per-tenant serve counters mirror (posts/ingest/quarantine/...)",
    labels=("tenant", "key"))
_OBS_PUMP = _get_registry().counter(
    "tw_serve_pump_total",
    "tenancy pump ledger mirror (shared/isolated solves, windows, ...)",
    labels=("key",))
_OBS_DISPATCHER_DEGRADED = _get_registry().gauge(
    "tw_serve_dispatcher_degraded",
    "1 while the continuous dispatcher thread has crashed and serve is "
    "degraded to the fixed inline pump (0 = dispatcher healthy / pump "
    "mode by configuration)")
_OBS_WIRE_INGEST = _get_registry().counter(
    "tw_wire_ingest_total",
    "span POSTs by parse path: columnar (the TW_WIRE_COLUMNAR wire "
    "parse, ingest/wire.py) vs object (parse_trace_payload — knob off, "
    "strict mode, repair-shim fixes, or converter payloads)",
    labels=("path",))
_OBS_INFLIGHT = _get_registry().gauge(
    "tw_serve_inflight",
    "dispatch-ring tickets currently outstanding (admitted + launched, "
    "consume not yet retired; 0 in pump mode / idle)")
_OBS_OVERLAP = _get_registry().gauge(
    "tw_serve_overlap_pct",
    "percent of ring device-dispatch wall that ran concurrently with "
    "another ticket (100*(1 - union/busy); 0 under the serial "
    "dispatcher / TW_SERVE_INFLIGHT=1)")
_OBS_RETRY_AFTER = _get_registry().histogram(
    "tw_serve_retry_after_seconds",
    "Retry-After seconds advertised on 429 backpressure responses "
    "(drain-rate derived since the in-flight ring; sub-second values "
    "are the point — the old 1s floor quantized closed-loop "
    "generators into lockstep waves)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))


def _merge_stats(dst: Dict, src: Dict) -> None:
    """Fold a local fleet-ledger dict into the shared one (the
    lock-free dispatch phase accumulates locally; this runs under the
    service lock so a concurrent ``stats()`` scrape never iterates a
    dict the solver is mutating). Counter keys add, ``record_max``
    gauges take the max, ordered event lists extend, per-tenant buckets
    merge. The obs-registry mirror already saw every update live
    (``_Stats`` mirrors at write time), so this only moves the legacy
    dict view."""
    _GAUGES = ("pipeline_depth", "fleet_group_cost_max")
    for k, v in src.items():
        if isinstance(v, list):
            dst.setdefault(k, []).extend(v)
        elif isinstance(v, dict):
            d = dst.setdefault(k, {})
            for kk, vv in v.items():
                # twlint: disable=TW007 — ledger MERGE of already-
                # mirrored _Stats buckets, not a fresh counter
                d[kk] = d.get(kk, 0.0) + vv
        elif k in _GAUGES:
            dst[k] = max(dst.get(k, 0.0), v)
        else:
            # twlint: disable=TW007 — ledger MERGE of already-mirrored
            # _Stats counters, not a fresh counter
            dst[k] = dst.get(k, 0.0) + v


class TenancyError(ValueError):
    """A tenancy-layer refusal (bad tenant id, tenant cap reached) — the
    HTTP layer maps these to 4xx responses instead of 500s."""


@dataclass
class ServeConfig:
    """Multi-tenant service knobs. ``None`` fields resolve from the
    ``TW_SERVE_*`` registry (:mod:`traceweaver_tpu.runtime.knobs`) at
    construction, so a typo'd env value raises at startup, not
    mid-serve."""

    # per-tenant stream geometry (event-time microseconds)
    window_us: float = 60e6
    overlap_us: float = 5e6
    ooo_bound_us: float = 2e6
    grace_us: float = 0.0
    fix: int = 5                   # ingest FIX mode for posted payloads
    strict: bool = False           # malformed span records raise (HTTP 400)
    warm_start: bool = True
    verbose: bool = False
    state_dir: Optional[str] = None  # per-tenant sinks + checkpoints
    checkpoint_every: int = 8
    # tenancy bounds; None -> TW_SERVE_* knob defaults
    max_tenants: Optional[int] = None
    max_pending: Optional[int] = None
    spill_max: Optional[int] = None
    ring_size: Optional[int] = None
    drain_timeout_s: Optional[float] = None
    pump_windows: Optional[int] = None
    # continuous batching (serve/continuous.py): event-driven admission
    # on a dispatcher thread instead of the ingest-inline threshold
    # pump. False here (library default — direct constructors keep the
    # pinned pump semantics); the serve CLI defaults it ON via
    # TW_SERVE_CONTINUOUS. slo_p99_ms None -> TW_SERVE_SLO_P99_MS.
    continuous: bool = False
    slo_p99_ms: Optional[float] = None
    # dispatch-ring depth: tickets (admitted batches) allowed in flight
    # at once under the continuous dispatcher. 1 restores the serial
    # admit→solve→consume loop byte-exactly (the kill switch, test-
    # pinned); None -> TW_SERVE_INFLIGHT (default 2).
    inflight: Optional[int] = None

    def __post_init__(self) -> None:
        if self.max_tenants is None:
            self.max_tenants = knobs.get_int("TW_SERVE_MAX_TENANTS")
        if self.max_pending is None:
            self.max_pending = knobs.get_int("TW_SERVE_PENDING")
        if self.spill_max is None:
            self.spill_max = knobs.get_int("TW_SERVE_SPILL")
        if self.ring_size is None:
            self.ring_size = knobs.get_int("TW_SERVE_RING")
        if self.drain_timeout_s is None:
            self.drain_timeout_s = knobs.get_float("TW_SERVE_DRAIN_S")
        if self.pump_windows is None:
            self.pump_windows = knobs.get_int("TW_SERVE_PUMP_WINDOWS")
        if self.slo_p99_ms is None:
            self.slo_p99_ms = knobs.get_float("TW_SERVE_SLO_P99_MS")
        if self.inflight is None:
            self.inflight = knobs.get_int("TW_SERVE_INFLIGHT")


class Tenant:
    """One tenant's full reconstruction pipeline (never shared)."""

    def __init__(self, tenant_id: str, cfg: ServeConfig) -> None:
        if not _TENANT_ID_RE.fullmatch(tenant_id):
            raise TenancyError(
                f"invalid tenant id {tenant_id!r}: expected "
                "[A-Za-z0-9][A-Za-z0-9._-]{0,63}")
        self.id = tenant_id
        self.cfg = cfg
        self.dir = (os.path.join(cfg.state_dir, tenant_id)
                    if cfg.state_dir else None)
        self.ckpt_path = (os.path.join(self.dir, "ckpt.pkl")
                          if self.dir else None)
        # durable ingest WAL (stream/wal.py, docs/ROBUSTNESS.md
        # "Durability"): opened lazily on the first ledgered append or
        # resume replay, so TW_WAL=0 never creates wal/
        self.wal_dir = (os.path.join(self.dir, "wal") if self.dir else None)
        self.wal: Optional[_walmod.WriteAheadLog] = None
        # client-seq dedup window: client seq -> traces/spans the
        # original application ingested (echoed verbatim on a dedup hit
        # so a retried POST's accounting matches the lost ack's)
        self._wal_seen: "OrderedDict[int, int]" = OrderedDict()
        sink = (TraceSink(os.path.join(self.dir, "traces.jsonl"))
                if self.dir else None)
        stream_cfg = StreamConfig(
            window_us=cfg.window_us, overlap_us=cfg.overlap_us,
            ooo_bound_us=cfg.ooo_bound_us, grace_us=cfg.grace_us,
            max_pending=cfg.max_pending, spill_max=cfg.spill_max,
            solve_min_batch=1, warm_start=cfg.warm_start,
            grade=False, prune=True,
            # the serve SLO rides the tenant's stream config so the
            # per-tenant seal→emit p99 carries breach telemetry
            # (tw_slo_breach_total{tenant} + one event per excursion);
            # tenants are externally pumped, so this never changes the
            # solve cadence — telemetry only
            slo_p99_ms=cfg.slo_p99_ms,
            # the TENANT owns checkpointing (its checkpoint wraps the
            # service state with ring/counter bookkeeping), so the inner
            # service's own cadence is disabled
            checkpoint_path=None,
            verbose=cfg.verbose,
        )
        self.svc = StreamingReconstructor(None, stream_cfg, sink=sink)
        # self-trace identity: this tenant's window journeys key as
        # "<tenant>:<window k>" on the shared tracer (obs/selftrace.py)
        self.svc.trace_prefix = tenant_id + ":"
        self.ring = TraceRing(cfg.ring_size)
        # Alibaba self-loop remap state must be stable across payloads
        # (and across a resume) exactly like the batch loader's
        # per-corpus map — it rides the tenant checkpoint
        self._self_loop_map: Dict[str, List[str]] = {}
        self.ingest_counters: Dict[str, int] = {}
        self.counters: Dict[str, float] = {}
        # per-tenant fault spec: a tenant under a configured fault storm
        # (or operator quarantine) solves in ISOLATED dispatches so its
        # ladder walks cannot slow the shared stream. The parsed plan is
        # cached so draw position/injection counters persist across
        # pumps (a fresh seeded plan per pump would replay the same
        # first draw forever).
        self.fault_spec: Optional[str] = None
        self._fault_plan = None
        self._fault_plan_spec: Optional[str] = None
        # per-tenant fleet ledger for isolated solves (the shared solve
        # ledgers on the manager, attributed via the tenant id column)
        self.fleet_stats: Dict[str, float] = {}
        # windows taken off the queues by the continuous dispatcher and
        # currently solving OUTSIDE the service lock: retention pruning
        # must not advance past them (their spans are still being
        # decoded/stitched)
        self.in_flight: List = []
        # capture ingestion (docs/COLLECTOR.md): lazy (CaptureCounters,
        # SkewEstimator) pair shared across every capture POST this
        # tenant receives, so loss rates/skew accumulate per tenant; the
        # stream service's confidence discount reads it through
        # capture_quality_ext once armed
        self._capture = None

    # -- ingestion --------------------------------------------------------
    def ingest_payload(self, payload) -> Dict[str, int]:
        """Fold one posted Jaeger-JSON payload (raw POST ``bytes`` on
        the default columnar wire path, or a decoded dict) into the
        tenant's stream.

        ``TW_WIRE_COLUMNAR`` (default on) parses eligible payloads
        through the columnar wire path (:mod:`traceweaver_tpu.ingest.
        wire`): native byte-level field extraction, Span objects
        materialized only for traces that pass the root-op filter.
        Ineligible payloads (and the knob-off path) reuse the batch
        loader's object pipeline (:func:`parse_trace_payload`) — both
        share the malformed-span dead-letter counters. Either way the
        FIX mode's root-operation filter applies (rejected-and-counted,
        same rule as ``ingest_trace``) and every accepted span feeds as
        an arrival-ordered event through watermark -> windowing ->
        scheduler, exactly the stream service's loop body; the host
        parse cost lands in the ``parse_s`` stage ledger.
        """
        self._bump("posts")
        root_op = FIX_ROOT_OPS[self.cfg.fix]
        n_traces = n_spans = rejected = 0
        accepted = []
        t0 = time.perf_counter()
        entries = None
        if knobs.get_bool("TW_WIRE_COLUMNAR"):
            entries = _wire.parse_payload_wire(
                payload, self.cfg.fix, self._self_loop_map,
                strict=self.cfg.strict, counters=self.ingest_counters)
        if entries is not None:
            parse_s = time.perf_counter() - t0
            for wt in entries:
                if wt is None:
                    continue
                if root_op is not None and wt.root_op != root_op:
                    rejected += 1
                    continue
                t1 = time.perf_counter()
                accepted.append(wt.materialize())
                parse_s += time.perf_counter() - t1
            self.svc._bump("parse_s", parse_s)
            _OBS_WIRE_INGEST.inc(1.0, path="columnar")
            self._bump("wire_columnar_posts")
        else:
            if isinstance(payload, (bytes, bytearray)):
                try:
                    payload = json.loads(payload)
                except json.JSONDecodeError as e:
                    raise MalformedSpan(f"invalid JSON: {e}") from None
            parsed = parse_trace_payload(
                payload, self.cfg.fix, self._self_loop_map,
                self.svc.live.service_loop_map, strict=self.cfg.strict,
                counters=self.ingest_counters)
            self.svc._bump("parse_s", time.perf_counter() - t0)
            _OBS_WIRE_INGEST.inc(1.0, path="object")
            self._bump("wire_object_posts")
            for entry in parsed:
                if entry is None:
                    continue
                trace_id, spans, processes = entry
                root = next((s for s in spans.values() if s.IsRoot()),
                            None)
                if root is None or (root_op is not None
                                    and root.op_name != root_op):
                    rejected += 1
                    continue
                accepted.append(entry)
        for trace_id, spans, processes in accepted:
            n_traces += 1
            ordered = sorted(spans.values(),
                             key=lambda s: (float(s.start_mus), s.sid))
            for span in ordered:
                self._ingest_event(SpanEvent(
                    span=span, event_us=float(span.start_mus),
                    arrival_us=float(span.start_mus), trace_id=trace_id,
                    processes=processes))
                n_spans += 1
        self._bump("ingested_traces", n_traces)
        self._bump("ingested_spans", n_spans)
        self._bump("rejected_traces", rejected)
        return dict(
            ingested_traces=n_traces,
            ingested_spans=n_spans,
            rejected_traces=rejected,
            malformed_spans=self.ingest_counters.get("malformed_spans", 0),
            backlog=self.backlog,
        )

    def ingest_capture(self, captures,
                       source: Optional[str] = None) -> Dict[str, int]:
        """Fold one posted ``strace -f [-ttt]`` capture into the
        tenant's stream (``POST /api/v1/tenants/<id>/capture`` — the
        serve half of the capture-to-trace loop, docs/COLLECTOR.md).

        ``captures`` is either one log's text (single capture host,
        named by ``source``; uncaptured callees synthesize as stubs) or
        a ``{source name: log text}`` bundle — one post carrying every
        host's capture of the same time window, so cross-source
        exchanges join, the skew fit sees its pairs, and callee spans
        attach to their callers instead of minting duplicate roots.

        Every log runs through the collector ingress — HTTP/2 replay,
        skew correction, partial-capture/churn hardening — and every
        recovered span feeds the same watermark → windowing → scheduler
        loop a Jaeger POST does. Loss/skew/churn ledgers accumulate
        across posts (per tenant), and once a tenant has posted a
        capture its emitted-trace confidence is discounted by the
        observed loss rate."""
        from traceweaver_tpu.collector.source import (
            CaptureCounters,
            CollectorSource,
        )
        from traceweaver_tpu.collector.skew import SkewEstimator

        self._bump("capture_posts")
        if self._capture is None:
            counters, estimator = CaptureCounters(), SkewEstimator()
            self._capture = (counters, estimator)
            self.svc.capture_quality_ext = (
                lambda: counters.snapshot(skew=estimator))
        counters, estimator = self._capture
        if isinstance(captures, str):
            captures = {(source or "capture"): captures}
        src = CollectorSource(captures,
                              counters=counters, estimator=estimator)
        n_spans = 0
        for ev in src.events():
            self._ingest_event(ev)
            n_spans += 1
        self._bump("capture_spans", n_spans)
        quality = src.capture_quality()
        return dict(
            ingested_spans=n_spans,
            capture_loss=quality["loss"],
            capture_loss_rate=quality["loss_rate"],
            rekeyed_streams=quality["rekeyed_streams"],
            skew_us=quality.get("skew_us", {}),
            backlog=self.backlog,
        )

    def _ingest_event(self, ev: SpanEvent) -> None:
        svc = self.svc
        svc.consumed += 1
        svc.watermark.observe(ev.event_us)
        span = svc.live.add(ev)
        svc.windower.add(span, ev.event_us)
        svc._trace_touch()
        sealed = svc.windower.poll(svc.watermark.value)
        svc._trace_seal(sealed)
        for buf in sealed:
            svc.scheduler.offer(buf)
        if sealed and svc.cfg.prune:
            self._prune()

    def _prune(self) -> None:
        # same retention rule as the stream run loop: two windows behind
        # the watermark, never past the oldest backlog window — nor past
        # a window the continuous dispatcher is solving right now
        svc = self.svc
        backlog = (list(svc.scheduler.pending) + list(svc.scheduler.spill)
                   + list(self.in_flight))
        oldest = min((b.start_us for b in backlog),
                     default=svc.watermark.value)
        horizon = min(svc.watermark.value - 2 * svc.cfg.window_us,
                      oldest - svc.cfg.window_us) - svc.cfg.grace_us
        svc.live.prune(horizon)

    def flush(self) -> int:
        """Seal every still-open window (without poisoning future event
        times: the sealing frontier advances only to the last open
        window's end, not to infinity) and queue them for the next pump.
        Returns how many windows were sealed."""
        svc = self.svc
        if not svc.windower.open:
            return 0
        frontier = max(b.end_us for b in svc.windower.open.values()) \
            + svc.windower.grace_us
        sealed = svc.windower.poll(frontier)
        svc._trace_seal(sealed)
        for buf in sealed:
            svc.scheduler.offer(buf)
        return len(sealed)

    # -- durable ingest WAL (stream/wal.py, TW_WAL) -----------------------
    def _wal(self) -> Optional[_walmod.WriteAheadLog]:
        """The tenant's write-ahead log, opened lazily (``None`` for
        state-dir-less tenants — nothing to be durable on)."""
        if self.wal is None and self.wal_dir:
            self.wal = _walmod.WriteAheadLog(
                self.wal_dir,
                segment_bytes=knobs.get_int("TW_WAL_SEGMENT_MB") << 20,
                sync=knobs.get("TW_WAL_SYNC"))
        return self.wal

    def wal_seen(self, client_seq: Optional[int]) -> Optional[int]:
        """Dedup-window lookup: the original application's ingested
        count when this client seq was already applied, else None."""
        if client_seq is None:
            return None
        return self._wal_seen.get(int(client_seq))

    def wal_note(self, client_seq: Optional[int], n: int) -> None:
        """Record an applied client seq (bounded window — the retry-of-
        a-lost-ack dedup horizon)."""
        if client_seq is None:
            return
        self._wal_seen[int(client_seq)] = int(n)
        while len(self._wal_seen) > WAL_DEDUP_WINDOW:
            self._wal_seen.popitem(last=False)

    def wal_append(self, kind: str, body: bytes,
                   client_seq: Optional[int] = None,
                   meta: Optional[Dict] = None) -> Optional[int]:
        """Ledgered append of one accepted wire payload — the ack-
        discipline point (twlint TW013): the raw POST bytes hit the log
        (durability per ``TW_WAL_SYNC``) before the caller can write a
        2xx. The envelope is a tiny JSON head (kind, client seq, capture
        source/ctype) + NUL + the raw body, so replay re-drives the
        normal ingest path — columnar wire parse included — with no
        re-serialization."""
        w = self._wal()
        if w is None:
            return None
        head = dict(k=kind)
        if client_seq is not None:
            head["seq"] = int(client_seq)
        if meta:
            head.update({k: v for k, v in meta.items() if v is not None})
        rec = (json.dumps(head, separators=(",", ":")).encode("utf-8")
               + b"\0" + body)
        seq = w.append(rec)
        self._bump("wal_appends")
        return seq

    def wal_sync(self) -> None:
        """Group commit (the ``batch`` policy's durability point): fsync
        pending WAL appends on the pump cadence. Failure is counted, not
        raised — the appends are already OS-flushed (process-death
        safe); only the power-loss window widens."""
        if self.wal is None:
            return
        try:
            self.wal.sync()
        except (OSError, RuntimeError) as e:
            from traceweaver_tpu.runtime import faults

            if not (isinstance(e, (OSError, faults.FaultError))
                    or faults.is_transient_fault(e)):
                raise
            self._bump("wal_sync_failures")

    def wal_replay(self, low_water: int) -> int:
        """Resume half: re-apply every WAL record past the checkpoint's
        low-water mark through the normal ingest path, in append order —
        the acked-but-uncheckpointed tail a hard death would otherwise
        lose. Torn tails were truncated (counted + evented) at open;
        per-record decode/apply errors are counted and skipped, never
        raised (a poison payload must not wedge recovery — its client
        was answered 4xx/5xx in the original run too)."""
        w = self._wal()
        if w is None:
            return 0
        if w.torn_tails:
            self._bump("wal_torn_tail", w.torn_tails)
        n = 0
        for _seq, rec in w.replay(int(low_water)):
            head_b, _, body = rec.partition(b"\0")
            try:
                head = json.loads(head_b)
            except ValueError:
                self._bump("wal_replay_errors")
                continue
            cseq = head.get("seq")
            try:
                if head.get("k") == "capture":
                    captures = body.decode("utf-8", "replace")
                    if head.get("ctype") == "json":
                        captures = json.loads(captures)
                    summary = self.ingest_capture(
                        captures, source=head.get("source"))
                    self.wal_note(cseq, summary.get("ingested_spans", 0))
                else:
                    summary = self.ingest_payload(body)
                    self.wal_note(cseq, summary.get("ingested_traces", 0))
            except (MalformedSpan, ValueError):
                self._bump("wal_replay_errors")
                continue
            n += 1
        if n:
            self._bump("wal_replayed", n)
            _events.emit("serve", "wal_replayed", tenant=self.id,
                         records=n, low_water=int(low_water))
        return n

    # -- solve plumbing (driven by the TenantService pump) ----------------
    @property
    def backlog(self) -> int:
        return self.svc.scheduler.backlog

    def pop_batch(self) -> List:
        """Take the next micro-batch off the tenant's queues (the
        scheduler's own refill-from-spill pump rule)."""
        return self.svc.scheduler.pop_batch()

    def emit_results(self, results) -> None:
        """Emit one batch's solved windows: sink/dead-letter via the
        stream service's own emission path, plus ring insertion for the
        live query surface and per-tenant quarantine accounting. Ring
        records carry each trace's ``tw.confidence`` so the live query
        surface can rank/exclude by reconstruction trust. Sink writes
        go through the stream service's batched emitter (one buffered
        write per solved batch, ``emit_s`` ledger) — the emitted bytes
        are identical to the per-window writes, just coalesced."""
        self.svc.emit_batch(results)
        for res in results:
            if res.poisoned:
                self._bump("quarantined_windows")
                self._bump("quarantined_services",
                           max(1, len(res.quarantined_services)))
                continue
            conf_by_span: Dict = {}
            for recs in (res.confidence or {}).values():
                conf_by_span.update(recs)
            for rec in build_trace_records(res.traces, self.svc.live,
                                           res.buf.k,
                                           confidence=conf_by_span):
                self.ring.add(rec)
        self.svc.scheduler.solved_windows += len(results)

    # -- checkpoint / resume ----------------------------------------------
    def checkpoint(self) -> bool:
        """Write this tenant's checkpoint (service state + ring +
        tenancy counters). Same failure tolerance as the stream service:
        a failed write is counted and the last good generation stays."""
        if not self.ckpt_path:
            return False
        state = self.svc.state_dict()
        state["serve"] = dict(
            tenant=self.id,
            ring=self.ring.records(),
            ring_evicted=self.ring.evicted,
            counters=dict(self.counters),
            ingest_counters=dict(self.ingest_counters),
            self_loop_map={k: list(v)
                           for k, v in self._self_loop_map.items()},
            fault_spec=self.fault_spec,
            fleet_stats=dict(self.fleet_stats),
            # WAL low-water mark: appends are applied to the service
            # state synchronously under the lock, so everything up to
            # last_seq is inside THIS checkpoint — segments at or below
            # it truncate once the write lands, and resume replays only
            # the seqs past it
            wal=dict(
                low_water=(self.wal.last_seq
                           if self.wal is not None else 0),
                seen=[(int(k), int(v))
                      for k, v in self._wal_seen.items()],
            ),
        )
        try:
            if self.wal is not None:
                # the log must be at least as durable as the checkpoint
                # that supersedes it ('off' policy flushes here)
                self.wal.sync()
            save_checkpoint(self.ckpt_path, state)
        except (OSError, RuntimeError) as e:
            from traceweaver_tpu.runtime import faults

            if not (isinstance(e, (OSError, faults.FaultError))
                    or faults.is_transient_fault(e)):
                raise
            self._bump("checkpoint_failures")
            return False
        self.svc._since_checkpoint = 0
        if self.wal is not None:
            self.wal.truncate_below(
                int(state["serve"]["wal"]["low_water"]))
        return True

    @classmethod
    def resume(cls, tenant_id: str, cfg: ServeConfig) -> "Tenant":
        tenant = cls(tenant_id, cfg)
        state = load_checkpoint(tenant.ckpt_path)
        if state.pop("_recovered_from_prev", False):
            tenant._bump("checkpoint_recovered")
        tenant.svc.apply_state(state)
        serve = state.get("serve", {})
        tenant.ring.load(serve.get("ring", []))
        tenant.ring.evicted = serve.get("ring_evicted", 0)
        tenant.counters.update(serve.get("counters", {}))
        tenant.ingest_counters.update(serve.get("ingest_counters", {}))
        tenant._self_loop_map.update(serve.get("self_loop_map", {}))
        tenant.fault_spec = serve.get("fault_spec")
        tenant.fleet_stats.update(serve.get("fleet_stats", {}))
        wal_state = serve.get("wal") or {}
        for k, v in wal_state.get("seen", []):
            tenant._wal_seen[int(k)] = int(v)
        if knobs.get_bool("TW_WAL"):
            tenant.wal_replay(int(wal_state.get("low_water", 0)))
        return tenant

    @classmethod
    def recover(cls, tenant_id: str, cfg: ServeConfig) -> "Tenant":
        """Crash-recovery resume: like :meth:`resume`, but tolerates a
        missing checkpoint — a tenant that died hard before its first
        checkpoint recovers purely from its WAL tail (the checkpoint
        low-water mark is implicitly 0)."""
        probe = cls(tenant_id, cfg)
        if probe.ckpt_path and os.path.isfile(probe.ckpt_path):
            return cls.resume(tenant_id, cfg)
        if knobs.get_bool("TW_WAL"):
            probe.wal_replay(0)
        return probe

    def fault_plan(self):
        """The tenant's persistent parsed fault plan (None when no storm
        is configured); rebuilt only when ``fault_spec`` changes."""
        from traceweaver_tpu.runtime import faults

        if self._fault_plan_spec != self.fault_spec:
            self._fault_plan = (
                faults.parse_faults(self.fault_spec,
                                    seed=knobs.get_int("TW_FAULTS_SEED"))
                if self.fault_spec else None)
            self._fault_plan_spec = self.fault_spec
        return self._fault_plan

    def close(self) -> None:
        if self.svc.sink is not None:
            self.svc.sink.close()
        if self.svc.deadletter is not None:
            self.svc.deadletter.close()
        if self.wal is not None:
            self.wal.close()

    # -- accounting -------------------------------------------------------
    def _bump(self, key: str, n: float = 1) -> None:
        _OBS_TENANT_LEDGER.inc(n, tenant=self.id, key=key)
        self.counters[key] = self.counters.get(key, 0) + n

    def stats(self) -> Dict:
        svc = self.svc
        sched = svc.scheduler
        return dict(
            tenant=self.id,
            consumed=svc.consumed,
            emitted_windows=svc.emitted_windows,
            spans_emitted=int(svc.stats.get("spans_emitted", 0)),
            traces_emitted=int(svc.stats.get("traces_emitted", 0)),
            backlog=sched.backlog,
            solved_windows=sched.solved_windows,
            shed_spilled=sched.shed_spilled,
            shed_dropped_windows=sched.shed_dropped_windows,
            shed_dropped_spans=sched.shed_dropped_spans,
            late_rerouted=svc.windower.late_rerouted,
            late_dropped=svc.windower.late_dropped,
            deadletter_windows=int(svc.stats.get("deadletter_windows", 0)),
            deadletter_spans=int(svc.stats.get("deadletter_spans", 0)),
            low_confidence_traces=int(
                svc.stats.get("low_confidence_traces", 0)),
            seal_emit_p99_ms=round(svc.seal_emit_p99_ms() or 0.0, 2),
            parse_s=round(float(svc.stats.get("parse_s", 0.0)), 6),
            stitch_s=round(float(svc.stats.get("stitch_s", 0.0)), 6),
            emit_s=round(float(svc.stats.get("emit_s", 0.0)), 6),
            consume_s=round(float(svc.stats.get("consume_s", 0.0)), 6),
            slo_breaches=int(svc.stats.get("slo_breaches", 0)),
            adapt_refits=int(svc.stats.get("adapt_refits", 0)),
            adapt=(svc.adapt.summary() if svc.adapt is not None else None),
            quarantined_windows=int(
                self.counters.get("quarantined_windows", 0)),
            ring_traces=len(self.ring),
            ring_evicted=self.ring.evicted,
            fault_spec=self.fault_spec,
            counters=dict(self.counters),
            ingest=dict(self.ingest_counters),
            wal=(self.wal.stats() if self.wal is not None else None),
            faults=dict(
                retries=int(self.fleet_stats.get("fault_retries", 0)),
                bisections=int(self.fleet_stats.get("fault_bisections", 0)),
                xla_fallbacks=int(
                    self.fleet_stats.get("fault_xla_fallbacks", 0)),
                host_fallbacks=int(
                    self.fleet_stats.get("fault_host_fallbacks", 0)),
                quarantined=int(
                    self.fleet_stats.get("fault_quarantined", 0)),
                injected=int(self.fleet_stats.get("faults_injected", 0)),
            ),
        )


class _Ticket:
    """One outstanding dispatch-ring entry: an admitted batch taken off
    its tenants' queues (``submit_admitted``), through the lock-free
    device phase (``_ring_dispatch``), to the FIFO locked consume
    (``complete_ticket``). The ticket carries everything the three
    phases hand each other, so per-tenant ``in_flight`` accounting can
    retire EXACTLY this ticket's windows (identity removal, never a
    wholesale clear — another ticket's windows may be in flight too)."""

    __slots__ = ("seq", "taken", "shared", "isolated", "prepared",
                 "items", "quarantined", "confidences", "outs",
                 "local_stats", "solve_s", "via_ring")

    def __init__(self, seq: int) -> None:
        self.seq = seq
        #: every (tenant, bufs) taken — shared AND isolated — for
        #: in_flight retire/requeue
        self.taken: List[Tuple["Tenant", List]] = []
        self.shared: List[Tuple["Tenant", List]] = []
        self.isolated: List[Tuple["Tenant", List]] = []
        self.prepared: List = []
        self.items: List = []
        self.quarantined: List[int] = []
        self.confidences: Optional[List] = None
        self.outs: List = []
        self.local_stats: Dict[str, float] = {}
        self.solve_s = 0.0
        #: launched onto the worker pool (completion feeds the
        #: dispatcher's EWMA through note_solve); False for the serial
        #: solve_admitted composition
        self.via_ring = False


class TenantService:
    """The multi-tenant reconstruction service (the HTTP layer's model).

    All public methods are thread-safe (ThreadingHTTPServer handlers call
    in concurrently); one re-entrant lock serializes tenancy state and
    solves — the device is a serially-dispatched resource anyway, and the
    fleet call itself pipelines internally.

    **The in-flight dispatch ring** (``TW_SERVE_INFLIGHT``, default 2):
    under the continuous dispatcher, :meth:`solve_admitted`'s three
    phases are split into :meth:`submit_admitted` (locked take +
    prepare, returns a ticket), the lock-free device dispatch on a
    small worker pool, and :meth:`complete_ticket` (locked FIFO consume
    + emit) — so the dispatcher admits and packs batch N+1 while batch
    N executes on the device. Consumes retire strictly in ticket-seq
    order, which keeps per-tenant emission order identical to the
    serial loop; ``TW_SERVE_INFLIGHT=1`` bypasses the ring entirely and
    runs the serial composition byte-exactly (test-pinned kill switch).
    """

    def __init__(self, cfg: Optional[ServeConfig] = None) -> None:
        self.cfg = cfg or ServeConfig()
        if self.cfg.state_dir:
            os.makedirs(self.cfg.state_dir, exist_ok=True)
        self.tenants: Dict[str, Tenant] = {}
        self._lock = threading.RLock()
        self.precision = precision_from_env()
        # drain-aware readiness (the rolling-restart contract): flipped
        # by begin_drain() the instant a SIGTERM drain starts, so
        # /readyz stops advertising a dying replica BEFORE the listener
        # closes — the fleet router routes around it with zero failed
        # POSTs instead of racing the socket teardown
        self.draining = False
        # live-migration tombstones (fleet_serve): a tenant moved off
        # this replica must not silently resurrect here on a late POST —
        # that would fork its stream state across replicas. Requests for
        # a tombstoned tenant get a TenancyError the HTTP layer maps to
        # 410 so the router re-resolves the tenant's pin.
        self.migrated_out: Dict[str, float] = {}
        # shared-dispatch ledger: every healthy tenant's windows ride the
        # solve_fleet calls accounted here; the tenant id column breaks
        # the totals down per tenant (tenant_windows_* buckets)
        self.fleet_stats: Dict[str, float] = {}
        self.stats_counters: Dict[str, float] = dict(
            shared_solves=0, tenant_batches=0, isolated_solves=0,
            pumped_windows=0, drain_timeouts=0)
        # continuous batching (serve/continuous.py): a dispatcher thread
        # owns the solve loop; ingest only seals + kicks. The fixed
        # threshold pump stays the library default (and the drained
        # fallback): cfg.continuous opts in.
        self.dispatcher = None
        # crash containment (docs/ROBUSTNESS.md): an uncaught exception
        # on the dispatcher thread degrades serve to the fixed pump
        # instead of silently wedging every tenant's seal→emit path
        self.dispatcher_degraded = False
        # -- the in-flight dispatch ring (TW_SERVE_INFLIGHT) --------------
        # outstanding tickets by seq; _ring_done counts retired tickets
        # (consume runs when a ticket's seq == _ring_done: FIFO order).
        # The condition shares the service lock so "outstanding changed"
        # waits compose with the ordinary locked sections.
        self._ring_limit = max(1, int(self.cfg.inflight or 1))
        self._ring_cond = threading.Condition(self._lock)
        self._ring_seq = 0
        self._ring_done = 0
        self._ring_outstanding: Dict[int, _Ticket] = {}
        self._ring_exc: Optional[BaseException] = None
        self._ring_queue: Optional[queue.Queue] = None
        self._ring_workers: List[threading.Thread] = []
        # overlap accounting (its own tiny mutex — updated inside the
        # LOCK-FREE device phase, where taking the service lock would
        # serialize the very overlap being measured): busy = Σ per-ticket
        # device walls, union = wall time with ≥1 ticket dispatching;
        # overlap_pct = 100*(1 - union/busy)
        self._ring_mutex = threading.Lock()
        self._ring_active = 0
        self._ring_active_since = 0.0
        self._ring_busy_s = 0.0
        self._ring_union_s = 0.0
        # recent ticket retirements (monotonic time, windows) — the live
        # drain rate Retry-After derives from
        self._ring_completions: deque = deque(maxlen=32)
        if self.cfg.continuous:
            from traceweaver_tpu.serve.continuous import ContinuousDispatcher

            if self._ring_limit > 1:
                self._ring_queue = queue.Queue()
                for i in range(self._ring_limit):
                    w = threading.Thread(
                        target=self._ring_worker,
                        name=f"tw-serve-ring-{i}", daemon=True)
                    w.start()
                    self._ring_workers.append(w)
            self.dispatcher = ContinuousDispatcher(
                self, slo_ms=self.cfg.slo_p99_ms).start()
            _OBS_DISPATCHER_DEGRADED.set(0.0)

    def _bump(self, key: str, n: float = 1) -> None:
        """The pump ledger's single write path (callers hold the
        re-entrant ``self._lock``); mirrors into the obs registry so the
        sidecar scrape surface sees the pump too."""
        _OBS_PUMP.inc(n, key=key)
        self.stats_counters[key] = self.stats_counters.get(key, 0) + n

    # -- tenancy ----------------------------------------------------------
    def tenant(self, tenant_id: str, create: bool = True) -> Tenant:
        with self._lock:
            t = self.tenants.get(tenant_id)
            if t is None:
                if tenant_id in self.migrated_out:
                    raise TenancyError(
                        f"tenant {tenant_id!r} migrated out of this "
                        "replica (route to its new home)")
                if not create:
                    raise KeyError(tenant_id)
                if len(self.tenants) >= self.cfg.max_tenants:
                    raise TenancyError(
                        f"tenant cap reached ({self.cfg.max_tenants}, "
                        "TW_SERVE_MAX_TENANTS): refusing new tenant "
                        f"{tenant_id!r}")
                t = Tenant(tenant_id, self.cfg)
                self.tenants[tenant_id] = t
            return t

    def ingest(self, tenant_id: str, payload) -> Dict[str, int]:
        """Ingest one payload (raw Jaeger-JSON POST ``bytes`` on the
        default wire path, or a decoded dict) for one tenant. Under
        continuous batching
        the POST only seals and KICKS the dispatcher (solve cadence is
        the admission scheduler's, decoupled from ingest); the classic
        mode auto-pumps inline once enough sealed windows are queued
        across tenants (so concurrent tenants' windows accumulate into
        SHARED dispatches instead of each POST solving alone)."""
        with self._lock:
            summary = self.tenant(tenant_id).ingest_payload(payload)
            if self.dispatcher is None:
                if self.total_backlog() >= self.cfg.pump_windows:
                    summary["pumped_windows"] = self.pump()
        if self.dispatcher is not None:
            self.dispatcher.kick()
        return summary

    def ingest_capture(self, tenant_id: str, captures,
                       source: Optional[str] = None) -> Dict[str, int]:
        """Capture ingestion for one tenant (the collector ingress
        behind ``POST /api/v1/tenants/<id>/capture``): raw log text or
        a ``{source: text}`` bundle; same pump/kick discipline as
        :meth:`ingest`."""
        with self._lock:
            summary = self.tenant(tenant_id).ingest_capture(
                captures, source=source)
            if self.dispatcher is None:
                if self.total_backlog() >= self.cfg.pump_windows:
                    summary["pumped_windows"] = self.pump()
        if self.dispatcher is not None:
            self.dispatcher.kick()
        return summary

    def wal_ingest(self, tenant_id: str, payload, raw: bytes,
                   client_seq: Optional[int] = None) -> Dict[str, int]:
        """Ledgered ingest (``TW_WAL``, docs/ROBUSTNESS.md
        "Durability"): the raw wire bytes are WAL-appended BEFORE the
        payload touches tenant state, so by the time the caller writes
        its 200 the spans survive kill -9 (durability per
        ``TW_WAL_SYNC``). A ``client_seq`` already in the dedup window
        is a retry of a lost ack — answered with the ORIGINAL
        application's accounting, no re-append, no re-ingest, so a
        crash between ack and client cannot double-emit. Same
        pump/kick discipline as :meth:`ingest`."""
        with self._lock:
            t = self.tenant(tenant_id)
            seen = t.wal_seen(client_seq)
            if seen is not None:
                t._bump("wal_deduped")
                return dict(
                    ingested_traces=seen, ingested_spans=0,
                    rejected_traces=0,
                    malformed_spans=t.ingest_counters.get(
                        "malformed_spans", 0),
                    backlog=t.backlog, deduped=True,
                    seq=int(client_seq))
            t.wal_append("spans", raw, client_seq=client_seq)
            summary = t.ingest_payload(payload)
            t.wal_note(client_seq, summary.get("ingested_traces", 0))
            if client_seq is not None:
                summary["seq"] = int(client_seq)
            if self.dispatcher is None:
                if self.total_backlog() >= self.cfg.pump_windows:
                    summary["pumped_windows"] = self.pump()
        if self.dispatcher is not None:
            self.dispatcher.kick()
        return summary

    def wal_ingest_capture(self, tenant_id: str, captures, raw: bytes,
                           ctype: Optional[str] = None,
                           source: Optional[str] = None,
                           client_seq: Optional[int] = None
                           ) -> Dict[str, int]:
        """Ledgered capture ingest: the capture-path twin of
        :meth:`wal_ingest` (raw body + source/ctype ride the envelope
        so replay rebuilds the same :meth:`Tenant.ingest_capture`
        call)."""
        with self._lock:
            t = self.tenant(tenant_id)
            seen = t.wal_seen(client_seq)
            if seen is not None:
                t._bump("wal_deduped")
                return dict(ingested_spans=seen, backlog=t.backlog,
                            deduped=True, seq=int(client_seq))
            t.wal_append("capture", raw, client_seq=client_seq,
                         meta=dict(source=source, ctype=ctype))
            summary = t.ingest_capture(captures, source=source)
            t.wal_note(client_seq, summary.get("ingested_spans", 0))
            if client_seq is not None:
                summary["seq"] = int(client_seq)
            if self.dispatcher is None:
                if self.total_backlog() >= self.cfg.pump_windows:
                    summary["pumped_windows"] = self.pump()
        if self.dispatcher is not None:
            self.dispatcher.kick()
        return summary

    def total_backlog(self) -> int:
        with self._lock:
            return sum(t.backlog for t in self.tenants.values())

    def reset_latency_window(self) -> None:
        """Start a fresh seal→emit latency measurement window on every
        tenant (the rolling p99 otherwise reflects cold-start compile
        stalls long after they stop mattering — grade the SLO over the
        steady state, the way the bench leg does)."""
        with self._lock:
            for t in self.tenants.values():
                t.svc.seal_emit_lat_s.clear()

    def in_flight_windows(self) -> int:
        """Windows the continuous dispatcher took off the queues and is
        solving right now (0 in pump mode — drain/quiesce loops must
        wait for backlog AND in-flight)."""
        with self._lock:
            return sum(len(t.in_flight) for t in self.tenants.values())

    def _on_dispatcher_death(self, exc: BaseException) -> None:
        """Crash containment for the continuous dispatcher thread.

        Before this, an uncaught exception in the admission loop died
        silently with serve still accepting spans: every tenant's
        sealed windows queued forever (the seal→emit path wedged) while
        POSTs kept returning 200. Now the dying thread lands here: the
        crash is counted and evented, the degraded gauge flips on
        ``/metrics``, and the service falls back to the FIXED pump —
        ``self.dispatcher = None`` routes every subsequent ingest
        through the inline threshold pump and flush/drain through the
        pump path, so tenants keep emitting (at pre-continuous cadence)
        instead of wedging. The backlog the dispatcher stranded is
        pumped immediately."""
        with self._lock:
            self.dispatcher = None
            self.dispatcher_degraded = True
            self._bump("dispatcher_crashes")
            _OBS_DISPATCHER_DEGRADED.set(1.0)
            _events.emit("serve", "dispatcher_degraded",
                         error="%s: %s" % (type(exc).__name__, exc))
        # retire the ring worker pool (outside the lock — workers need it
        # to complete queued tickets before honoring the stop sentinel);
        # subsequent flush/drain route through the pump path
        self._ring_shutdown()
        try:
            with self._lock:
                self.pump()
        except Exception as drain_exc:  # noqa: BLE001 — best-effort drain
            # the stranded backlog stays queued; the next ingest's
            # inline pump retries it (counted, never silent)
            with self._lock:
                self._bump("dispatcher_drain_errors")
            _events.emit("serve", "dispatcher_drain_error",
                         error="%s: %s" % (type(drain_exc).__name__,
                                           drain_exc))

    def run_adaptations(self) -> int:
        """Execute every tenant's pending drift-adaptation refits
        (adapt/, ``TW_ADAPT``). Out-of-band by construction: each refit
        is its own single-item ``solve_fleet`` call, never merged into
        the admission/pump dispatch — the continuous dispatcher calls
        this AFTER a solve round retires, so SLO dispatches keep
        flowing. Returns refits that landed."""
        with self._lock:
            n = 0
            for tid in sorted(self.tenants):
                n += self.tenants[tid].svc.maybe_adapt()
            if n:
                self._bump("adapt_refits", n)
            return n

    # -- the shared pump --------------------------------------------------
    def pump(self) -> int:
        """Solve every queued micro-batch: healthy tenants merged into
        shared fleet dispatches, fault-spec'd tenants in isolated
        dispatches under their own fault plan. Returns windows solved."""
        with self._lock:
            shared: List[Tuple[Tenant, List]] = []
            isolated: List[Tuple[Tenant, List]] = []
            for tid in sorted(self.tenants):
                t = self.tenants[tid]
                batch = t.pop_batch()
                while batch:
                    (isolated if t.fault_spec else shared).append((t, batch))
                    batch = t.pop_batch()
            n = 0
            if shared:
                n += self._solve_shared(shared)
            for t, batch in isolated:
                n += self._solve_isolated(t, batch)
            for tid in sorted(self.tenants):
                t = self.tenants[tid]
                # WAL group commit rides the pump cadence (the 'batch'
                # sync policy's fsync point)
                t.wal_sync()
                if t.ckpt_path and \
                        t.svc._since_checkpoint >= self.cfg.checkpoint_every:
                    t.checkpoint()
            self._bump("pumped_windows", n)
        # adaptation refits run after the pump retires (idempotent —
        # pending_refits drains), never inside the shared dispatch
        self.run_adaptations()
        return n

    def solve_admitted(self, plan: List[Tuple[Tenant, List]]) -> int:
        """Solve an admission-scheduler batch (``[(tenant, [bufs])]`` —
        serve/continuous.py picked WHICH windows) SERIALLY: submit,
        dispatch on the calling thread, consume. This is the
        ``TW_SERVE_INFLIGHT=1`` path, the drain_backlog path, and the
        byte-exact reference the ring's overlapped composition is
        pinned against (tests/test_continuous.py): the same three
        phases, one ticket, zero outstanding while it runs.

        Unlike the pump, the shared DISPATCH runs OUTSIDE the service
        lock: ingest proceeds while the device executes — the
        throughput half of continuous batching. Windows a concurrent
        flush already drained are skipped (the take is identity-
        matched), so admission races resolve to at-most-once solving.
        Returns windows solved."""
        ticket = self.submit_admitted(plan)
        if ticket is None:
            return 0
        self._ring_dispatch(ticket)
        return self.complete_ticket(ticket)

    # -- the in-flight dispatch ring (ticket lifecycle) -------------------
    # in_flight discipline (twlint TW012): per-tenant ``in_flight`` lists
    # are mutated ONLY here — submit extends, complete/abort retire by
    # ticket identity — and only under the service lock. Everything else
    # (pruning, migration wait-for-retire, checkpoint gating, drain
    # barriers) just READS them.
    def submit_admitted(self,
                        plan: List[Tuple[Tenant, List]]
                        ) -> Optional[_Ticket]:
        """Phase 1, locked: take the admitted windows off their tenants'
        queues (identity-matched — at-most-once vs a racing flush),
        split shared/isolated, mark every taken window in-flight on its
        tenant (retention pruning must not advance past a window whose
        spans are still being solved — isolated windows included, they
        sit in neither queue mid-dispatch too), and build the fleet
        items. Returns the ticket to dispatch, or ``None`` when every
        window was already drained by a concurrent take."""
        with self._lock:
            ticket = _Ticket(self._ring_seq)
            for t, bufs in plan:
                if self.tenants.get(t.id) is not t:
                    # admitted, then migrated out (or evicted) before
                    # the take: the windows rode the transfer checkpoint
                    # to the destination replica — solving them here
                    # would double-emit into a closed tenant
                    continue
                taken = t.svc.scheduler.take(bufs)
                if taken:
                    ticket.taken.append((t, taken))
                    (ticket.isolated if t.fault_spec
                     else ticket.shared).append((t, taken))
            if not ticket.taken:
                return None
            self._ring_seq += 1
            for t, bufs in ticket.taken:
                t.in_flight.extend(bufs)
            ticket.prepared, ticket.items = \
                self._prepare_shared(ticket.shared)
            if _quality.conf_enabled():
                ticket.confidences = [None] * len(ticket.items)
            self._ring_outstanding[ticket.seq] = ticket
            self._bump("ring_submitted")
            _OBS_INFLIGHT.set(float(len(self._ring_outstanding)))
            return ticket

    def launch_ticket(self, ticket: _Ticket) -> None:
        """Hand a submitted ticket to the ring worker pool (dispatch +
        FIFO complete happen there); the dispatcher thread returns to
        admitting immediately. Ring mode only (``ring_enabled``)."""
        ticket.via_ring = True
        q = self._ring_queue
        if q is None:  # ring shut down mid-flight: degrade to serial
            self._ring_dispatch(ticket)
            self.complete_ticket(ticket)
            return
        q.put(ticket)

    def _ring_dispatch(self, ticket: _Ticket) -> None:
        """Phase 2, LOCK-FREE: the device dispatch. The fleet ledger
        accumulates into the ticket's local dict (merged under the lock
        at complete — a concurrent stats() scrape must never iterate a
        dict the solver is growing), and the overlap interval union is
        tracked under its own mutex so concurrent tickets' device walls
        can be decomposed into overlapped vs serial time."""
        t_in = time.monotonic()
        with self._ring_mutex:
            if self._ring_active == 0:
                self._ring_active_since = t_in
            self._ring_active += 1
        try:
            t0 = time.perf_counter()
            ticket.outs = self._dispatch_shared(
                ticket.items, ticket.quarantined, ticket.confidences,
                stats=ticket.local_stats)
            ticket.solve_s = time.perf_counter() - t0
        finally:
            t_out = time.monotonic()
            with self._ring_mutex:
                self._ring_active -= 1
                self._ring_busy_s += t_out - t_in
                if self._ring_active == 0:
                    self._ring_union_s += t_out - self._ring_active_since

    def complete_ticket(self, ticket: _Ticket) -> int:
        """Phase 3, locked, FIFO: wait for the ticket's seq turn (ring
        consumes retire in submission order — per-tenant emission order
        stays identical to the serial loop, which is what makes
        overlapped output deterministic per ordering), then merge the
        fleet ledger, consume/emit the shared results, retire the
        ticket's windows from their tenants' in-flight sets (identity
        removal — other tickets' windows stay protected), run the
        isolated solves, and checkpoint tenants on cadence — SKIPPING
        any tenant that still has windows in flight on another ticket
        (``state_dict`` captures queues, not in-flight windows: a
        checkpoint taken mid-ticket would lose them on resume)."""
        n = 0
        with self._ring_cond:
            while self._ring_done < ticket.seq:
                self._ring_cond.wait(timeout=0.25)
            try:
                _merge_stats(self.fleet_stats, ticket.local_stats)
                if ticket.shared:
                    n = self._consume_shared(
                        ticket.prepared, len(ticket.items),
                        len(ticket.shared), ticket.outs,
                        ticket.quarantined, ticket.confidences,
                        ticket.solve_s)
                self._ring_retire_locked(ticket)
                for t, bufs in ticket.isolated:
                    n += self._solve_isolated(t, bufs)
                for tid in sorted(self.tenants):
                    t = self.tenants[tid]
                    t.wal_sync()  # group commit on the consume cadence
                    if t.in_flight:
                        continue
                    if t.ckpt_path and t.svc._since_checkpoint \
                            >= self.cfg.checkpoint_every:
                        t.checkpoint()
                self._bump("pumped_windows", n)
                self._bump("continuous_dispatches")
                self._bump("ring_completed")
                self._ring_completions.append((time.monotonic(), n))
                if ticket.via_ring and self.dispatcher is not None:
                    self.dispatcher.note_solve(ticket.solve_s, n)
            finally:
                # idempotent: the happy path retired above; an exception
                # mid-consume must still advance the ring (FIFO waiters
                # + migrate_out's wait-for-retire would wedge otherwise)
                self._ring_retire_locked(ticket)
        return n

    def _ring_retire_locked(self, ticket: _Ticket) -> None:
        """Retire one ticket (caller holds the lock; idempotent):
        identity-remove exactly its windows from each tenant's in-flight
        set, advance the FIFO counter, wake ring waiters."""
        # twlint: disable=TW005 — every caller (complete_ticket,
        # _ring_abort) holds the service lock across this helper
        if self._ring_outstanding.pop(ticket.seq, None) is None:
            return
        for t, bufs in ticket.taken:
            drop = {id(b) for b in bufs}
            t.in_flight[:] = [b for b in t.in_flight
                              if id(b) not in drop]
        self._ring_done = ticket.seq + 1
        _OBS_INFLIGHT.set(float(len(self._ring_outstanding)))
        _OBS_OVERLAP.set(self.overlap_pct())
        self._ring_cond.notify_all()

    def _ring_worker(self) -> None:
        """One ring worker: dispatch lock-free, then the FIFO locked
        complete. A dispatch error re-queues the ticket's windows (they
        never reached a sink — solving them again is safe); a complete
        error only retires (results may be partially emitted — a replay
        could double-emit). Either way the error is recorded and raised
        on the DISPATCHER thread (its next throttle/idle check), so
        crash containment degrades serve to the fixed pump exactly like
        a serial dispatcher crash."""
        q = self._ring_queue
        while True:
            ticket = q.get()
            if ticket is None:
                return
            try:
                self._ring_dispatch(ticket)
            except Exception as e:  # noqa: BLE001 — containment
                self._ring_abort(ticket, e, requeue=True)
                continue
            try:
                self.complete_ticket(ticket)
            except Exception as e:  # noqa: BLE001 — containment
                self._ring_abort(ticket, e, requeue=False)

    def _ring_abort(self, ticket: _Ticket, exc: BaseException,
                    requeue: bool) -> None:
        with self._ring_cond:
            while self._ring_done < ticket.seq \
                    and ticket.seq in self._ring_outstanding:
                self._ring_cond.wait(timeout=0.25)
            if requeue and ticket.seq in self._ring_outstanding:
                for t, bufs in ticket.taken:
                    if self.tenants.get(t.id) is t:
                        for b in bufs:
                            t.svc.scheduler.offer(b)
            self._ring_retire_locked(ticket)
            if self._ring_exc is None:
                self._ring_exc = exc
            self._bump("ring_aborted")
        _events.emit("serve", "ring_ticket_aborted", seq=ticket.seq,
                     requeued=requeue,
                     error="%s: %s" % (type(exc).__name__, exc))

    @property
    def ring_enabled(self) -> bool:
        """True while the overlapped ring is live (TW_SERVE_INFLIGHT > 1
        and the worker pool running); the dispatcher falls back to the
        serial solve_admitted path when False."""
        return self._ring_queue is not None

    def ring_throttle(self) -> None:
        """Dispatcher-side back edge: block while the ring is full
        (outstanding == TW_SERVE_INFLIGHT), then surface any worker
        error ON THE DISPATCHER THREAD so its crash containment
        (_on_dispatcher_death → fixed-pump degrade) fires for ring-mode
        failures exactly as for serial ones."""
        with self._ring_cond:
            while (self._ring_exc is None
                   and len(self._ring_outstanding) >= self._ring_limit):
                self._ring_cond.wait(timeout=0.25)
        self.ring_raise_pending()

    def ring_raise_pending(self) -> None:
        """Re-raise (once) the first ring-worker error on the caller's
        thread — the dispatcher polls this even when idle, so a worker
        crash with no further admissions still degrades serve."""
        with self._lock:
            exc, self._ring_exc = self._ring_exc, None
        if exc is not None:
            raise exc

    def wait_idle(self, timeout_s: Optional[float] = None) -> bool:
        """Barrier on ALL outstanding ring tickets (the drain/flush/
        checkpoint contract: a flush that races an in-flight ticket
        undercounts emitted traces; a checkpoint taken mid-ticket loses
        the ticket's windows on resume). Returns False on timeout with
        tickets still outstanding."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._ring_cond:
            while self._ring_outstanding:
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    return False
                self._ring_cond.wait(timeout=0.1)
        return True

    def overlap_pct(self) -> float:
        """Percent of ring device wall that overlapped another ticket:
        ``100*(1 - union/busy)`` over closed dispatch intervals. 0.0
        under the serial dispatcher (union == busy by construction)."""
        with self._ring_mutex:
            busy, union = self._ring_busy_s, self._ring_union_s
        if busy <= 0.0:
            return 0.0
        return max(0.0, 100.0 * (1.0 - union / busy))

    def _ring_shutdown(self, timeout_s: float = 10.0) -> None:
        """Stop the ring worker pool (sentinel per worker; queued
        tickets ahead of the sentinels complete first — nothing taken is
        dropped). Callers barrier via wait_idle before checkpointing."""
        q, self._ring_queue = self._ring_queue, None
        if q is None:
            return
        for _ in self._ring_workers:
            q.put(None)
        for w in self._ring_workers:
            w.join(timeout=timeout_s)
        self._ring_workers = []

    # -- the shared solve, in three phases so solve_admitted can drop
    # -- the lock around the dispatch (pump() composes them locked) -------
    def _prepare_shared(self, batches: List[Tuple[Tenant, List]]):
        """Fleet-item construction for a shared solve (caller holds the
        lock — reads tenant pipeline state)."""
        prepared = []
        items: List = []
        for t, bufs in batches:
            per_buf, t_items, t_owners = t.svc.prepare_batch_items(
                bufs, tenant=t.id)
            lo = len(items)
            items.extend(t_items)
            prepared.append((t, bufs, per_buf, t_owners, lo, len(items)))
        return prepared, items

    def _dispatch_shared(self, items: List, quarantined: List,
                         confidences: Optional[List],
                         stats: Optional[Dict] = None) -> List:
        """The device phase — needs NO service lock (``stats`` defaults
        to the shared ledger for locked callers; lock-free callers pass
        a local dict and merge after)."""
        from traceweaver_tpu.algorithms.fleet import solve_fleet

        if not items:
            return []
        return solve_fleet(items,
                           stats=(self.fleet_stats if stats is None
                                  else stats),
                           precision=self.precision,
                           quarantined=quarantined,
                           confidences=confidences)

    def _consume_shared(self, prepared, n_items: int, n_batches: int,
                        outs, quarantined: List,
                        confidences: Optional[List],
                        solve_s: float) -> int:
        """Decode/emit phase (caller holds the lock — mutates tenant
        pipeline state, rings, sinks)."""
        self._bump("shared_solves")
        self._bump("tenant_batches", n_batches)
        n = 0
        for t, bufs, per_buf, t_owners, lo, hi in prepared:
            share = solve_s * (hi - lo) / max(1, n_items)
            t.svc._bump("solve_s", share)
            results = t.svc.consume_batch_results(
                bufs, per_buf, t_owners, outs[lo:hi],
                [k - lo for k in quarantined if lo <= k < hi], share,
                confidences=(confidences[lo:hi]
                             if confidences is not None else None))
            t.emit_results(results)
            n += len(bufs)
        return n

    def _solve_shared(self, batches: List[Tuple[Tenant, List]]) -> int:
        t0 = time.perf_counter()
        prepared, items = self._prepare_shared(batches)
        quarantined: List[int] = []
        confidences: Optional[List] = (
            [None] * len(items) if _quality.conf_enabled() else None)
        outs = self._dispatch_shared(items, quarantined, confidences)
        solve_s = time.perf_counter() - t0
        return self._consume_shared(prepared, len(items), len(batches),
                                    outs, quarantined, confidences, solve_s)

    def _solve_isolated(self, t: Tenant, bufs: List) -> int:
        """One fault-spec'd tenant's batch in its own dispatch, under its
        own injected fault plan — the storm walks the supervisor's ladder
        inside THIS tenant's solve; neighbors never see it."""
        from traceweaver_tpu.algorithms.fleet import solve_fleet
        from traceweaver_tpu.runtime import faults

        t0 = time.perf_counter()
        per_buf, items, owners = t.svc.prepare_batch_items(bufs, tenant=t.id)
        quarantined: List[int] = []
        outs: List = []
        confidences: Optional[List] = (
            [None] * len(items) if _quality.conf_enabled() else None)
        if items:
            with faults.override_plan(t.fault_plan()):
                outs = solve_fleet(items, stats=t.fleet_stats,
                                   precision=self.precision,
                                   quarantined=quarantined,
                                   confidences=confidences)
        solve_s = time.perf_counter() - t0
        t.svc._bump("solve_s", solve_s)
        self._bump("isolated_solves")
        results = t.svc.consume_batch_results(bufs, per_buf, owners, outs,
                                              quarantined, solve_s,
                                              confidences=confidences)
        t.emit_results(results)
        return len(bufs)

    # -- flush / drain / resume -------------------------------------------
    def flush(self, tenant_id: Optional[str] = None) -> Dict[str, int]:
        """Seal every open window (one tenant, or all) and solve the
        backlog — the deterministic "solve what you have now" hook tests
        and the drain path use. Under continuous batching the backlog
        drains through the dispatcher's admission-sized chunks (one
        giant catch-all pump would dispatch batch shapes outside the
        steady-state lattice); pump mode solves it in one pump as
        before."""
        with self._lock:
            targets = ([self.tenant(tenant_id, create=False)]
                       if tenant_id else list(self.tenants.values()))
            sealed = sum(t.flush() for t in targets)
        if self.dispatcher is not None:
            solved = self.dispatcher.drain_backlog()
            self.run_adaptations()
        else:
            with self._lock:
                solved = self.pump()
        return dict(sealed_windows=sealed, solved_windows=solved)

    def checkpoint_all(self,
                       timeout_s: Optional[float] = None) -> Dict[str, int]:
        """Checkpoint every tenant, time-boxed (``TW_SERVE_DRAIN_S``): a
        drain must not hold SIGTERM forever — tenants past the box are
        counted, their last good checkpoint stays on disk.

        Barriers on the dispatch ring first: ``state_dict`` captures the
        scheduler queues, NOT windows a ticket has taken off them, so a
        checkpoint cut mid-ticket would lose those windows on resume.
        Any tenant still holding in-flight windows after the (bounded)
        barrier is skipped — its last good checkpoint stays current."""
        budget = (self.cfg.drain_timeout_s
                  if timeout_s is None else timeout_s)
        t0 = time.monotonic()
        self.wait_idle(budget)
        done = skipped = timed_out = 0
        with self._lock:
            for tid in sorted(self.tenants):
                if self.tenants[tid].in_flight:
                    # outstanding ticket survived the barrier: this
                    # tenant's last good checkpoint stays current (the
                    # in-flight check outranks the time box — it is
                    # cheap, and "skipped" names the cause)
                    skipped += 1
                    continue
                if time.monotonic() - t0 > budget:
                    timed_out += 1
                    self._bump("drain_timeouts")
                    continue
                if self.tenants[tid].checkpoint():
                    done += 1
                else:
                    skipped += 1
        return dict(checkpointed=done, skipped=skipped,
                    timed_out=timed_out)

    def begin_drain(self) -> None:
        """Mark the service draining: ``/readyz`` answers 503 from this
        instant on. Called by the SIGTERM handler BEFORE the listener
        shuts down (and by :meth:`drain` itself for direct callers), so
        orchestrators and the fleet router stop routing to a dying
        replica while it is still serving in-flight requests."""
        with self._lock:
            if self.draining:
                return
            self.draining = True
        _events.emit("serve", "draining")

    # -- live tenant migration (the fleet tier, fleet_serve/) -------------
    def retry_after(self, tenant_id: str) -> Optional[float]:
        """Suggested client back-off (seconds) when this tenant's
        sealed-window queues are SATURATED — within a small headroom of
        the hard pending+spill bound — else ``None``. The headroom
        exists because sealing is bursty: one accepted POST can advance
        the watermark past several open windows (window/overlap
        geometry), and a flush force-seals every open window, so an
        admission check against the exact bound lets the burst overflow
        into dropped windows.

        The wait is the ring's LIVE drain rate times the tenant's queue
        position (recent ticket completions → seconds-per-window),
        falling back to the dispatcher's solve EWMA and only then to the
        tenant's seal→emit p99. No 1-second floor: the old
        ``max(1.0, …)`` + integer header quantized every closed-loop
        generator in a campaign onto the same retry instant, arriving as
        a lockstep wave that re-saturated the queues it had just been
        bounced off (CAMPAIGN_r18 attributes part of the serve↔direct
        gap to exactly this). Kicks the continuous dispatcher so the
        advertised wait is actually in motion."""
        with self._lock:
            t = self.tenants.get(tenant_id)
            if t is None:
                return None
            sched = t.svc.scheduler
            # headroom ≥ the worst-case seal burst (a monotonic stream
            # keeps ≤2 windows open — owner + overlap neighbor — so one
            # accepted POST can seal 2), capped so the threshold never
            # drops below one queued window
            bound = sched.max_pending + sched.spill_max
            headroom = min(4, bound - 1)
            if sched.backlog < bound - headroom:
                return None
            self._bump("backpressure_429s")
            pace_s = self._drain_pace_locked(t)
            wait = min(max(0.1, sched.backlog * pace_s),
                       self.cfg.drain_timeout_s)
        _OBS_RETRY_AFTER.observe(wait)
        if self.dispatcher is not None:
            self.dispatcher.kick()
        return round(wait, 2)

    def _drain_pace_locked(self, t: "Tenant") -> float:
        """Seconds-per-window the serve drain is ACTUALLY sustaining
        (caller holds the lock). Prefers the ring's recent ticket
        completions (wall span / windows retired — measures the
        overlapped throughput, not one ticket's latency), then the
        dispatcher's solve EWMA spread over its batch fill, then the
        tenant's seal→emit p99 (which includes queue wait — a gross
        overestimate of marginal pace, but the only signal cold)."""
        comps = [c for c in self._ring_completions
                 if c[0] >= time.monotonic() - 30.0]
        if len(comps) >= 2:
            span = comps[-1][0] - comps[0][0]
            windows = sum(n for _, n in comps[1:])
            if span > 0.0 and windows > 0:
                return max(0.001, span / windows)
        if self.dispatcher is not None:
            fill = max(1, min(self.cfg.pump_windows,
                              self.cfg.max_pending))
            return max(0.005, self.dispatcher.solve_ewma_s / fill)
        return max(0.05, (t.svc.seal_emit_p99_ms() or 1000.0) / 1000.0)

    def migrate_out(self, tenant_id: str) -> Dict[str, object]:
        """Source half of live tenant migration: checkpoint the tenant
        (open windows, ring, counters — the SIGTERM-drain durability
        story, nothing sealed early), read back the CRC-verified
        checkpoint plus the sink/dead-letter bytes the checkpoint's
        byte-offset splice refers to, then remove and tombstone the
        tenant here. Returns the JSON-safe transfer payload
        ``migrate_in`` installs on the destination replica.

        Zero loss by construction: every ingested-but-unsolved window
        rides the checkpoint; every emitted byte rides the sink copy;
        the tombstone stops this replica minting a forked twin. Windows
        a dispatch ticket has TAKEN but not yet retired sit in neither
        scheduler queue (the device dispatch runs outside the lock —
        and under the ring, windows from SEVERAL outstanding tickets
        can be out at once), so checkpointing mid-ticket would lose
        them — the wait below holds the migration until the tenant's
        in-flight set is empty (each ticket's complete/abort retires
        exactly its own windows under the lock), bounded by the drain
        budget."""
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        while True:
            with self._lock:
                t = self.tenant(tenant_id, create=False)  # KeyError -> 404
                if not t.in_flight:
                    return self._migrate_out_locked(tenant_id, t)
            if time.monotonic() >= deadline:
                raise TenancyError(
                    f"tenant {tenant_id!r}: in-flight dispatch did not "
                    f"retire within the drain budget "
                    f"({self.cfg.drain_timeout_s:.0f}s, TW_SERVE_DRAIN_S)"
                    "; migration aborted (tenant stays live here)")
            time.sleep(0.02)

    def _migrate_out_locked(self, tenant_id: str,
                            t: "Tenant") -> Dict[str, object]:
        """The checkpoint-and-tombstone half of :meth:`migrate_out`.
        Caller holds the service lock and has verified ``t.in_flight``
        is empty (nothing taken off the queues mid-solve)."""
        if not t.ckpt_path:
            raise TenancyError(
                "live migration requires a state dir (per-tenant "
                "checkpoints are the transfer unit); restart serve "
                "with --state-dir")
        if not t.checkpoint():
            raise RuntimeError(
                f"tenant {tenant_id!r}: checkpoint write failed; "
                "migration aborted (tenant stays live here)")
        ckpt = read_checkpoint_bytes(t.ckpt_path)
        sink_b = b""
        if t.svc.sink is not None:
            with open(t.svc.sink.path, "rb") as f:
                sink_b = f.read()
        dlq_b = b""
        if (t.svc.deadletter is not None
                and os.path.exists(t.svc.deadletter.path)):
            with open(t.svc.deadletter.path, "rb") as f:
                dlq_b = f.read()
        # the checkpoint just written fully covers the WAL (appends are
        # applied synchronously and in_flight is empty), so the log is
        # not part of the transfer — and like the checkpoint files it
        # must not survive here to resurrect a forked twin
        if t.wal is not None:
            t.wal.destroy()
        t.close()
        del self.tenants[tenant_id]
        now = time.time()
        # twlint: disable=TW005 — caller (migrate_out) holds the
        # service lock across this whole helper
        self.migrated_out[tenant_id] = now
        # neutralize the on-disk state: a restart with --resume must
        # NOT resurrect the moved tenant from its leftover checkpoint
        # (a forked twin of the stream now live elsewhere). The
        # checkpoint generations go; a durable tombstone marker stays
        # so resume() re-tombstones instead of forgetting.
        for path in (t.ckpt_path, t.ckpt_path + ".prev"):
            if os.path.exists(path):
                os.remove(path)
        with open(os.path.join(t.dir, MIGRATED_MARKER), "w") as f:
            json.dump({"tenant": tenant_id, "migrated_unix": now}, f)
        self._bump("migrations_out")
        _events.emit("fleet", "migrate_out", tenant=tenant_id,
                     checkpoint_bytes=len(ckpt), sink_bytes=len(sink_b))
        return dict(
            tenant=tenant_id,
            checkpoint_b64=base64.b64encode(ckpt).decode("ascii"),
            sink_b64=base64.b64encode(sink_b).decode("ascii"),
            deadletter_b64=base64.b64encode(dlq_b).decode("ascii"),
        )

    def migrate_in(self, tenant_id: str,
                   transfer: Dict[str, object]) -> Dict[str, object]:
        """Destination half: install the transferred sink/dead-letter
        bytes and the CRC-verified checkpoint under this replica's state
        dir, then resume the tenant exactly like a restart would — the
        checkpoint's offset splice truncates the sink back to the
        checkpointed byte, so the migrated tenant's emitted output stays
        byte-identical to an unmigrated run."""
        if not self.cfg.state_dir:
            raise TenancyError(
                "live migration requires a state dir on the destination "
                "replica too; restart serve with --state-dir")
        try:
            ckpt = base64.b64decode(
                transfer.get("checkpoint_b64", "") or "")
            sink_b = base64.b64decode(transfer.get("sink_b64", "") or "")
            dlq_b = base64.b64decode(
                transfer.get("deadletter_b64", "") or "")
            wal_b = base64.b64decode(transfer.get("wal_b64", "") or "")
        except (TypeError, ValueError) as e:
            raise TenancyError(f"malformed migration transfer: {e}")
        if not ckpt and not wal_b:
            # a graceful migrate always ships a checkpoint; a crash
            # failover may ship only the WAL of a never-checkpointed
            # tenant — but NEITHER means there is nothing to install
            raise TenancyError(
                "malformed migration transfer: neither checkpoint_b64 "
                "nor wal_b64 present")
        with self._lock:
            if tenant_id in self.tenants:
                raise TenancyError(
                    f"tenant {tenant_id!r} already live on this replica: "
                    "refusing migrate_in (forked state)")
            if len(self.tenants) >= self.cfg.max_tenants:
                raise TenancyError(
                    f"tenant cap reached ({self.cfg.max_tenants}, "
                    "TW_SERVE_MAX_TENANTS): refusing migrated tenant "
                    f"{tenant_id!r}")
            tdir = os.path.join(self.cfg.state_dir, tenant_id)
            os.makedirs(tdir, exist_ok=True)
            sink_path = os.path.join(tdir, "traces.jsonl")
            with open(sink_path, "wb") as f:
                f.write(sink_b)
            with open(sink_path + ".deadletter.jsonl", "wb") as f:
                f.write(dlq_b)
            if ckpt:
                write_checkpoint_bytes(os.path.join(tdir, "ckpt.pkl"),
                                       ckpt)
            if wal_b:
                # crash failover: the dead replica's WAL tail rides the
                # transfer — installed before resume so the replay picks
                # up exactly the acked-but-uncheckpointed records (a
                # torn tail in the copy truncates on install, same
                # contract as open)
                _walmod.install_bytes(os.path.join(tdir, "wal"), wal_b)
            # a returning tenant clears any tombstone it left behind here
            marker = os.path.join(tdir, MIGRATED_MARKER)
            if os.path.exists(marker):
                os.remove(marker)
            t = Tenant.recover(tenant_id, self.cfg)
            self.tenants[tenant_id] = t
            self.migrated_out.pop(tenant_id, None)
            self._bump("migrations_in")
            backlog = t.backlog
        if self.dispatcher is not None:
            self.dispatcher.kick()
        _events.emit("fleet", "migrate_in", tenant=tenant_id,
                     backlog=backlog)
        return dict(tenant=tenant_id, backlog=backlog,
                    ring_traces=len(t.ring))

    def drain(self) -> Dict[str, int]:
        """Graceful drain (the SIGTERM path): stop the continuous
        dispatcher (no new admissions), barrier on every outstanding
        ring ticket and retire the worker pool, checkpoint every tenant
        within the drain budget, then close sinks. Open windows ride
        the checkpoints — a restart resumes every tenant with zero lost
        windows (tests/test_stream.py pins byte-identical per-tenant
        resume; tests/test_continuous.py extends the pin to drains cut
        while tickets were still in flight)."""
        self.begin_drain()
        if self.dispatcher is not None:
            self.dispatcher.stop()
        self.wait_idle(self.cfg.drain_timeout_s)
        self._ring_shutdown()
        out = self.checkpoint_all()
        with self._lock:
            for t in self.tenants.values():
                t.close()
            return out

    @classmethod
    def resume(cls, cfg: ServeConfig) -> "TenantService":
        """Restart from ``cfg.state_dir``: every subdirectory with a
        checkpoint becomes a resumed tenant."""
        svc = cls(cfg)
        if cfg.state_dir and os.path.isdir(cfg.state_dir):
            for name in sorted(os.listdir(cfg.state_dir)):
                ckpt = os.path.join(cfg.state_dir, name, "ckpt.pkl")
                marker = os.path.join(cfg.state_dir, name, MIGRATED_MARKER)
                if os.path.isfile(ckpt):
                    with svc._lock:
                        svc.tenants[name] = Tenant.resume(name, cfg)
                elif (knobs.get_bool("TW_WAL") and not os.path.isfile(marker)
                      and _walmod.list_segments(
                          os.path.join(cfg.state_dir, name, "wal"))):
                    # killed before its first checkpoint: the tenant
                    # exists only as a WAL — recover replays it in full
                    with svc._lock:
                        svc.tenants[name] = Tenant.recover(name, cfg)
                elif os.path.isfile(marker):
                    # migrated-out tombstone survives restarts: the
                    # tenant lives on another replica now — requests
                    # here must keep answering 410, not mint a twin
                    try:
                        with open(marker) as f:
                            ts = float(json.load(f).get(
                                "migrated_unix", 0.0))
                    except (ValueError, OSError):
                        ts = 0.0
                    with svc._lock:
                        svc.migrated_out[name] = ts
        return svc

    # -- query surface ----------------------------------------------------
    def query_delay_culprit(self, tenant_id: str, percentile: float = 0.95,
                            after_us: Optional[float] = None,
                            min_confidence: Optional[float] = None) -> Dict:
        with self._lock:
            t = self.tenant(tenant_id, create=False)
            return live_delay_culprit(t.ring.records(), percentile,
                                      after_us,
                                      min_confidence=min_confidence)

    def query_low_confidence(self, tenant_id: str, limit: int = 20,
                             max_conf: Optional[float] = None) -> Dict:
        """The ring's least-trusted reconstructions, ascending by
        confidence (docs/OBSERVABILITY.md "Quality telemetry"): the
        traces an operator should re-examine — or exclude from culprit
        attribution — first. ``max_conf`` defaults to ``TW_CONF_LOW``."""
        if max_conf is None:
            max_conf = _quality.low_threshold()
        with self._lock:
            t = self.tenant(tenant_id, create=False)
            records = t.ring.records()
        scored = [r for r in records if r.get("tw.confidence")]
        scored.sort(key=lambda r: (r["tw.confidence"]["conf"],
                                   r["trace_id"]))
        low = [r for r in scored if r["tw.confidence"]["conf"] <= max_conf]
        return dict(
            n_traces=len(records),
            n_scored=len(scored),
            n_low=len(low),
            max_conf=max_conf,
            traces=[dict(trace_id=r["trace_id"],
                         confidence=r["tw.confidence"]["conf"],
                         mean_confidence=r["tw.confidence"].get("mean"),
                         window=r.get("window"),
                         e2e_us=r.get("e2e_us"),
                         n_spans=r.get("n_spans"))
                    for r in low[:max(0, int(limit))]],
        )

    def trace_ids(self, tenant_id: str) -> List[str]:
        with self._lock:
            return self.tenant(tenant_id, create=False).ring.ids()

    def trace(self, tenant_id: str, trace_id: str) -> Optional[Dict]:
        with self._lock:
            return self.tenant(tenant_id, create=False).ring.get(trace_id)

    #: per-tenant stats() fields exposed on /metrics, name-for-name
    _METRIC_TENANT_FIELDS = (
        "consumed", "emitted_windows", "spans_emitted", "traces_emitted",
        "backlog", "solved_windows", "shed_spilled",
        "shed_dropped_windows", "shed_dropped_spans", "late_rerouted",
        "late_dropped", "deadletter_windows", "deadletter_spans",
        "low_confidence_traces", "seal_emit_p99_ms", "slo_breaches",
        "adapt_refits", "quarantined_windows", "ring_traces",
        "ring_evicted", "parse_s", "stitch_s", "emit_s", "consume_s")

    def metrics_families(self) -> List:
        """Collector-style families for ``GET /metrics``
        (``(name, kind, help, [(labels, value), ...])`` tuples the
        exposition renders after the process registry).

        Derived at scrape time from the SAME :meth:`stats` call the
        ``/api/v1/stats`` endpoint serves, so the exposed per-tenant
        window/dispatch/ladder counters equal the JSON ledger exactly —
        by construction, not by double bookkeeping
        (tests/test_serve.py pins the match under concurrent load)."""
        st = self.stats()
        tenants = st["tenants"]
        fams: List = [
            ("tw_serve_tenants", "gauge", "live tenant count",
             [({}, float(st["n_tenants"]))]),
            ("tw_serve_backlog_windows", "gauge",
             "sealed windows awaiting solve, all tenants",
             [({}, float(st["total_backlog"]))]),
            ("tw_serve_dispatch_total", "counter",
             "service-wide dispatch ledger (= /api/v1/stats .dispatch)",
             [({"kind": k}, float(v))
              for k, v in sorted(st["dispatch"].items())]),
        ]
        tenant_samples = [
            ({"tenant": tid, "key": field}, float(t[field]))
            for tid, t in sorted(tenants.items())
            for field in self._METRIC_TENANT_FIELDS
        ]
        fams.append((
            "tw_serve_tenant_total", "counter",
            "per-tenant window ledger (= /api/v1/stats .tenants.*)",
            tenant_samples))
        fams.append((
            "tw_serve_tenant_faults_total", "counter",
            "per-tenant solve-supervisor ladder (= /api/v1/stats "
            ".tenants.*.faults)",
            [({"tenant": tid, "rung": rung}, float(v))
             for tid, t in sorted(tenants.items())
             for rung, v in sorted(t["faults"].items())]))
        return fams

    def stats(self, tenant_id: Optional[str] = None) -> Dict:
        with self._lock:
            if tenant_id is not None:
                return self.tenant(tenant_id, create=False).stats()
            fleet = {k: v for k, v in self.fleet_stats.items()
                     if not isinstance(v, list)}
            return dict(
                precision=self.precision,
                n_tenants=len(self.tenants),
                max_tenants=self.cfg.max_tenants,
                total_backlog=sum(t.backlog for t in self.tenants.values()),
                dispatch=dict(
                    fleet_dispatches=int(
                        self.fleet_stats.get("fleet_dispatches", 0)),
                    shared_solves=int(
                        self.stats_counters["shared_solves"]),
                    tenant_batches=int(
                        self.stats_counters["tenant_batches"]),
                    isolated_solves=int(
                        self.stats_counters["isolated_solves"]),
                    pumped_windows=int(
                        self.stats_counters["pumped_windows"]),
                    continuous_dispatches=int(
                        self.stats_counters.get(
                            "continuous_dispatches", 0)),
                    adapt_refits=int(
                        self.stats_counters.get("adapt_refits", 0)),
                    dispatcher_crashes=int(
                        self.stats_counters.get("dispatcher_crashes", 0)),
                    migrations_out=int(
                        self.stats_counters.get("migrations_out", 0)),
                    migrations_in=int(
                        self.stats_counters.get("migrations_in", 0)),
                    backpressure_429s=int(
                        self.stats_counters.get("backpressure_429s", 0)),
                ),
                draining=self.draining,
                migrated_out=sorted(self.migrated_out),
                dispatcher_degraded=self.dispatcher_degraded,
                continuous=(self.dispatcher.stats()
                            if self.dispatcher is not None else None),
                ring=dict(
                    inflight_limit=self._ring_limit,
                    enabled=self.ring_enabled,
                    outstanding=len(self._ring_outstanding),
                    submitted=int(
                        self.stats_counters.get("ring_submitted", 0)),
                    completed=int(
                        self.stats_counters.get("ring_completed", 0)),
                    aborted=int(
                        self.stats_counters.get("ring_aborted", 0)),
                    overlap_pct=round(self.overlap_pct(), 2),
                    busy_s=round(self._ring_busy_s, 6),
                    union_s=round(self._ring_union_s, 6),
                ),
                fleet=fleet,
                tenants={tid: t.stats()
                         for tid, t in sorted(self.tenants.items())},
            )


def read_crashed_transfer(tenant_dir: str,
                          tenant_id: str) -> Dict[str, object]:
    """Build a ``migrate_in`` transfer payload from a CRASHED replica's
    on-disk tenant state (the failover half of crash recovery,
    ``fleet_serve/manager.py``). Unlike :meth:`TenantService.migrate_out`
    there is no live service to quiesce: the checkpoint may be stale
    (or absent for a never-checkpointed tenant) — the WAL tail carries
    every payload acked past it, and the destination's resume replays
    that tail through the normal ingest path. A corrupt primary
    checkpoint falls back to the rotated ``.prev`` generation; sink
    bytes past the checkpointed offset are spliced off by resume, same
    as a restart."""
    from traceweaver_tpu.stream.checkpoint import CheckpointCorrupt

    ckpt_b = b""
    ckpt_path = os.path.join(tenant_dir, "ckpt.pkl")
    for path in (ckpt_path, ckpt_path + ".prev"):
        if not os.path.isfile(path):
            continue
        try:
            ckpt_b = read_checkpoint_bytes(path)
            break
        except (CheckpointCorrupt, OSError):
            continue
    sink_b = b""
    sink_path = os.path.join(tenant_dir, "traces.jsonl")
    if os.path.isfile(sink_path):
        with open(sink_path, "rb") as f:
            sink_b = f.read()
    dlq_b = b""
    dlq_path = sink_path + ".deadletter.jsonl"
    if os.path.isfile(dlq_path):
        with open(dlq_path, "rb") as f:
            dlq_b = f.read()
    wal_b = _walmod.read_all_bytes(os.path.join(tenant_dir, "wal"))
    if not ckpt_b and not wal_b:
        raise TenancyError(
            f"tenant {tenant_id!r}: no recoverable state under "
            f"{tenant_dir} (no readable checkpoint, empty WAL)")
    return dict(
        tenant=tenant_id,
        checkpoint_b64=base64.b64encode(ckpt_b).decode("ascii"),
        sink_b64=base64.b64encode(sink_b).decode("ascii"),
        deadletter_b64=base64.b64encode(dlq_b).decode("ascii"),
        wal_b64=base64.b64encode(wal_b).decode("ascii"),
    )


def tombstone_crashed_tenant(tenant_dir: str, tenant_id: str) -> None:
    """Post-failover hygiene on the crashed replica's disk: the tenant
    now lives on a survivor, so its checkpoint generations and WAL go
    and a durable :data:`MIGRATED_MARKER` stays — when the dead replica
    respawns with ``--resume`` it re-tombstones instead of minting a
    forked twin (same rule as a graceful migrate_out)."""
    ckpt_path = os.path.join(tenant_dir, "ckpt.pkl")
    for path in (ckpt_path, ckpt_path + ".prev"):
        if os.path.exists(path):
            os.remove(path)
    for name in _walmod.list_segments(os.path.join(tenant_dir, "wal")):
        try:
            os.remove(os.path.join(tenant_dir, "wal", name))
        except OSError:
            pass
    with open(os.path.join(tenant_dir, MIGRATED_MARKER), "w") as f:
        json.dump({"tenant": tenant_id, "migrated_unix": time.time()}, f)
