"""Bounded per-tenant ring of recently emitted traces.

The live query surface (``GET .../query/delay_culprit``, trace
fetch/list) runs against this ring, not against the sink file: a serving
deployment answers "who is slow right now" from the most recent traces,
and the ring bound is what keeps a tenant's query state O(ring), not
O(stream). Eviction is strictly oldest-first and counted
(``evicted``), so "the query window covers the last N traces" is an
auditable statement, not an approximation.

Records are plain JSON-serializable dicts (the HTTP layer returns them
verbatim and checkpoints pickle them), built by
:func:`build_trace_records` from a window's stitched traces plus the
tenant's live span store. Each span entry carries its *self* time —
duration minus its children's durations — which is what makes the
delay-culprit attribution charge latency to the service that spent it
rather than to every frontend that contained it
(:func:`traceweaver_tpu.query.delay_culprit.live_delay_culprit`).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional


class TraceRing:
    """Insertion-ordered bounded map of ``trace_id -> record``."""

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._records: "OrderedDict[str, dict]" = OrderedDict()
        self.evicted = 0

    def add(self, record: dict) -> None:
        """Insert one emitted-trace record; a re-emitted trace id (a
        window re-solved across a resume splice) replaces its previous
        record in place instead of double-counting."""
        tid = record["trace_id"]
        if tid in self._records:
            del self._records[tid]
        self._records[tid] = record
        while len(self._records) > self.capacity:
            self._records.popitem(last=False)
            self.evicted += 1

    def get(self, trace_id: str) -> Optional[dict]:
        return self._records.get(trace_id)

    def ids(self) -> List[str]:
        """Trace ids, oldest first."""
        return list(self._records)

    def records(self) -> List[dict]:
        """Records, oldest first (the live query's input)."""
        return list(self._records.values())

    def load(self, records: List[dict]) -> None:
        """Bulk restore (checkpoint resume): replay through :meth:`add`
        so the bound and eviction accounting hold on the resumed ring."""
        for rec in records:
            self.add(rec)

    def __len__(self) -> int:
        return len(self._records)


def build_trace_records(traces: Dict[str, List], live,
                        window_k: int,
                        confidence: Optional[Dict] = None) -> List[dict]:
    """Turn one emitted window's stitched traces into ring records.

    ``traces`` is the window's ``trace_id -> [span ids]`` map
    (:meth:`~traceweaver_tpu.stream.service.StreamingReconstructor._stitch`);
    ``live`` is the tenant's
    :class:`~traceweaver_tpu.stream.state.LiveTraceStore`. Spans already
    pruned from the live store are skipped and the record marked
    ``complete: False`` so the query layer can exclude partial traces the
    same way the reference excludes traces with unreconstructed hops.

    ``confidence`` (``{span id: quality record}`` —
    :mod:`traceweaver_tpu.obs.quality`) attaches each trace's
    ``tw.confidence`` summary, which the low-confidence query sorts by
    and the delay-culprit bracket can filter on.
    """
    from traceweaver_tpu.obs import quality as _quality

    records = []
    for tid, span_ids in sorted(traces.items()):
        spans, missing = [], 0
        id_set = set(span_ids)
        for sid in span_ids:
            span = live.all_spans.get(sid)
            if span is None:
                missing += 1
                continue
            child_dur = sum(
                float(live.all_spans[c].duration_mus)
                for c in span.children_spans
                if c in id_set and c in live.all_spans
            )
            spans.append(dict(
                sid=list(sid),
                service=live.service_of(span) or "",
                kind=span.span_kind,
                start_us=float(span.start_mus),
                dur_us=float(span.duration_mus),
                self_us=max(0.0, float(span.duration_mus) - child_dur),
            ))
        if not spans:
            continue
        spans.sort(key=lambda s: (s["start_us"], s["sid"]))
        start = min(s["start_us"] for s in spans)
        end = max(s["start_us"] + s["dur_us"] for s in spans)
        rec = dict(
            trace_id=tid,
            window=window_k,
            root_start_us=start,
            e2e_us=end - start,
            n_spans=len(spans),
            complete=missing == 0,
            spans=spans,
        )
        if confidence:
            tconf = _quality.trace_confidence(span_ids, confidence)
            if tconf is not None:
                rec["tw.confidence"] = tconf
        records.append(rec)
    return records
