"""Multi-tenant reconstruction service: the network-facing layer.

The stream package (``traceweaver_tpu/stream``) made the batch solver an
online service for ONE application; this package makes it a *service* —
the ROADMAP's "heavy traffic from millions of users" precondition:

- :mod:`tenancy` — per-tenant reconstruction pipelines (watermark,
  windows, live store, carried warm-start state, sink/dead-letter,
  emitted-trace ring) multiplexed into **shared** fleet dispatches: the
  packer already batches ``[B, E, W, M]`` blocks across services, so
  tenancy is one more id column carried through pack/compaction/decode
  (``FleetItem.tenant``). Per-tenant backpressure (pending bound ->
  spill -> counted shed), per-tenant quarantine/dead-letter accounting,
  and isolated dispatches for fault-storming tenants keep one tenant's
  trouble out of its neighbors' throughput.
- :mod:`http` — the stdlib ``ThreadingHTTPServer`` front door: Jaeger-
  JSON span POSTs per tenant (reusing the batch loader's parse + its
  malformed-span dead-letter path), a live delay-culprit query API over
  each tenant's ring of recently emitted traces, trace fetch/list,
  stats, and graceful SIGTERM drain (checkpoint every tenant).
- :mod:`ring` — the bounded per-tenant trace ring the query surface
  reads.

CLI: ``python -m traceweaver_tpu.runtime.cli serve --port 8321
--state-dir state/`` (docs/SERVING.md has the endpoint reference, knob
table, and a curl quickstart).
"""

from traceweaver_tpu.serve.ring import (  # noqa: F401
    TraceRing,
    build_trace_records,
)
from traceweaver_tpu.serve.tenancy import (  # noqa: F401
    ServeConfig,
    TenancyError,
    Tenant,
    TenantService,
)
from traceweaver_tpu.serve.http import (  # noqa: F401
    ReconstructionServer,
    make_server,
    run_server,
)
