"""Continuous-batching dispatch scheduler for the multi-tenant service.

The PR 6 serve layer solved on a FIXED pump: every POST checked the
total backlog against ``TW_SERVE_PUMP_WINDOWS`` and, past the
threshold, ran the solve inline on the ingesting request's thread. That
cadence couples dispatch to ingest arrival patterns: a burst solves a
fat well-filled batch, a trickle waits forever (until a flush), and a
latency-sensitive tenant behind a quiet period starves with its windows
sealed but unsolved.

This module replaces the pump with EVENT-DRIVEN ADMISSION
(``ServeConfig.continuous``; the serve CLI defaults it on via
``TW_SERVE_CONTINUOUS``): a dispatcher thread owns the solve loop and
admits sealed windows into the next fleet dispatch as the previous one
retires, trading a per-tenant seal→emit latency SLO
(``TW_SERVE_SLO_P99_MS``) against batch-fill efficiency:

- **SLO-at-risk windows jump the queue**: a window whose seal→now age
  approaches the SLO budget (minus the measured solve-time EWMA — the
  admission must land BEFORE the deadline, not start at it) is admitted
  immediately, whatever the batch fill looks like.
- **Batch-fill with adaptive size classes**: absent urgency, the
  scheduler waits for ``fill_target`` windows, and picks them by the
  LIVE window-size distribution — each window's power-of-two size class
  (:func:`~traceweaver_tpu.runtime.bucketing.pow2_bucket` over its span
  count, the same bucketing every dispatch shape uses) feeds a rolling
  histogram, and the dominant class is admitted together while outlier
  classes wait for their own dispatch (or their SLO): co-batching a
  4096-span window with 64-span windows pays 64× padding for everyone,
  exactly the shape-class arbitration the fleet's merge budget does
  device-side, applied at admission time. The class lattice is the pow2
  lattice the programs already compile against, so steady-state
  admission mints ZERO new compiles (test-pinned).
- **Fairness**: fill picks round-robin across tenants, oldest window
  first per tenant, so one tenant at 100× the rate cannot monopolize
  admission — and the SLO jump bounds every other tenant's worst case
  regardless (tests/test_continuous.py pins no-starvation under a
  100× hot tenant).

The dispatcher serializes with ingest on the service's lock (the device
is a serially-dispatched resource; the fleet call pipelines
internally), but POSTs no longer run solves inline — ingest latency
decouples from dispatch cadence. See docs/PERF.md
"Continuous batching".
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs
from traceweaver_tpu.runtime.bucketing import pow2_bucket

_OBS_ADMIT = _get_registry().counter(
    "tw_serve_admission_total",
    "continuous-batching admission outcomes (urgent/fill/deferred "
    "windows)",
    labels=("outcome",))
_OBS_BATCH_FILL = _get_registry().histogram(
    "tw_serve_dispatch_fill_windows",
    "windows admitted per continuous dispatch")


class ContinuousDispatcher:
    """The continuous-batching solve loop over one
    :class:`~traceweaver_tpu.serve.tenancy.TenantService`."""

    #: urgency floor: even with a pessimistic solve-time estimate a
    #: window is never held past this fraction of the SLO budget
    _MIN_HEADROOM_FRAC = 0.25
    #: solve-time EWMA smoothing (the admission deadline subtracts 2×
    #: the estimate so the solve lands inside the SLO, not starts at it)
    _EWMA = 0.3

    def __init__(self, service, slo_ms: Optional[float] = None,
                 fill_target: Optional[int] = None) -> None:
        self.service = service
        slo_ms = (slo_ms if slo_ms is not None
                  else knobs.get_float("TW_SERVE_SLO_P99_MS"))
        self.slo_s = slo_ms / 1000.0
        self.fill_target = int(fill_target or service.cfg.pump_windows)
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.solve_ewma_s = 0.05
        self.dispatches = 0
        self.urgent_dispatches = 0
        self.crashed = False
        # rolling window-size histogram: pow2 class -> recent count
        # (bounded deque of classes; the distribution the adaptive
        # bucket pick reads)
        self._recent_classes: deque = deque(maxlen=256)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ContinuousDispatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="tw-serve-continuous", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop admitting and JOIN the loop: an in-flight dispatch
        finishes its consume/emit before this returns, so drain can
        close sinks without racing a late emission."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    def kick(self) -> None:
        """Ingest-side nudge: new sealed windows may be admittable."""
        with self._cond:
            self._cond.notify_all()

    # -- admission --------------------------------------------------------
    def _fill_limit(self, n_ready: int) -> int:
        """Admission size cap for this dispatch: the base fill target,
        grown (pow2) up to 4× under a deep backlog — a backlog twice
        the target means admission is the bottleneck, and a fatter
        batch amortizes dispatch overhead without new shapes (counts
        stay on the quantized pow2 lattice; the SLO deadline still
        preempts via the urgency path)."""
        limit = self.fill_target
        while n_ready >= 2 * limit and limit < 4 * self.fill_target:
            limit *= 2
        return limit

    def _deadline_s(self) -> float:
        """Seal→now age past which a window jumps the queue: the SLO
        budget minus twice the solve-time estimate (the dispatch must
        FINISH inside the SLO), floored so a wild estimate can never
        hold windows forever."""
        return max(self.slo_s * self._MIN_HEADROOM_FRAC,
                   self.slo_s - 2.0 * self.solve_ewma_s)

    @staticmethod
    def _size_class(buf) -> int:
        return pow2_bucket(max(1, buf.n_spans))

    def _candidates(self) -> List[Tuple[object, object, float]]:
        """(tenant, buffer, seal-age seconds) of every sealed window
        awaiting solve, oldest first per tenant. Caller holds the
        service lock."""
        now = time.monotonic()
        cands = []
        for tid in sorted(self.service.tenants):
            t = self.service.tenants[tid]
            for buf in t.svc.scheduler.ready():
                sealed = getattr(buf, "sealed_wall", 0.0) or now
                cands.append((t, buf, now - sealed))
        return cands

    def _admit(self) -> Tuple[Optional[List], float]:
        """Pick the next dispatch's windows (or how long to wait).

        Returns ``(plan, wait_s)``: ``plan`` is a ``[(tenant, [bufs])]``
        batch list when a dispatch should run NOW, else None with the
        sleep until the earliest SLO deadline (or a new seal's kick).
        Caller holds the service lock."""
        cands = self._candidates()
        if not cands:
            return None, 0.25
        for _, buf, _ in cands:
            self._recent_classes.append(self._size_class(buf))
        deadline = self._deadline_s()
        urgent = [c for c in cands if c[2] >= deadline]
        if not urgent and len(cands) < self.fill_target:
            # not enough for a well-filled batch and nobody at risk:
            # wait for more seals or the earliest deadline
            wait = min(deadline - age for _, _, age in cands)
            return None, max(0.005, min(wait, 0.25))

        picked: List[Tuple[object, object]] = []
        picked_ids = set()

        def pick(t, buf, outcome):
            picked.append((t, buf))
            picked_ids.add(id(buf))
            _OBS_ADMIT.inc(outcome=outcome)

        # every dispatch is CLASS-COHERENT: one pow2 size class per
        # dispatch, so the device programs compile against the class
        # lattice itself, never against the combinatorics of class
        # MIXTURES (the fleet's shape-class merge would otherwise mint
        # a new merged-group shape per admission composition — the
        # steady state must run at zero compiles). The dispatch class
        # is the oldest urgent window's, else the dominant class of the
        # live size distribution.
        if urgent:
            self.urgent_dispatches += 1
            oldest = max(urgent, key=lambda c: c[2])
            batch_class = self._size_class(oldest[1])
            # other urgent classes dispatch on the immediately-following
            # loop iterations (wait 0 while any urgency remains)
            for t, buf, age in sorted(urgent, key=lambda c: -c[2]):
                if self._size_class(buf) == batch_class:
                    pick(t, buf, "urgent")
        else:
            hist = Counter(self._recent_classes)
            live = {self._size_class(buf) for _, buf, _ in cands}
            batch_class = max(live,
                              key=lambda c: (hist.get(c, 0), -c))
        # batch-fill within the class, round-robin across tenants
        # (fairness: a hot tenant fills at most its share per cycle),
        # oldest first within a tenant
        per_tenant: Dict[str, deque] = {}
        for t, buf, age in cands:
            if id(buf) not in picked_ids \
                    and self._size_class(buf) == batch_class:
                per_tenant.setdefault(t.id, deque()).append((t, buf))
        deferred = len(cands) - len(picked) \
            - sum(len(q) for q in per_tenant.values())
        limit = self._fill_limit(len(cands))
        order = sorted(per_tenant)
        while len(picked) < limit and any(
                per_tenant[tid] for tid in order):
            for tid in order:
                if len(picked) >= limit:
                    break
                if per_tenant[tid]:
                    pick(*per_tenant[tid].popleft(), "fill")
        if deferred:
            _OBS_ADMIT.inc(float(deferred), outcome="deferred")
        if not picked:
            # dominant class momentarily empty (e.g. every candidate is
            # a different class): fall back to the oldest window's class
            return None, 0.02
        return self._group_plan(self._quantize(picked)), 0.0

    @staticmethod
    def _quantize(picked: List) -> List:
        """Truncate an admission to a power-of-two window count (the
        oldest picks keep their slots). Together with class coherence
        this makes the (size class × admission count) pair — the whole
        of what admission timing can vary — a SMALL fixed lattice, so
        the dispatch shapes downstream stop depending on scheduler
        timing at all (the zero-steady-compiles contract)."""
        keep = 1 << (len(picked).bit_length() - 1)
        return picked[:keep]

    @staticmethod
    def _group_plan(picked: List[Tuple[object, object]]) -> List:
        """``[(tenant, buf)]`` admission picks -> the ``[(tenant,
        [bufs])]`` batch list :meth:`TenantService.solve_admitted`
        takes, grouped per tenant in admission order."""
        plan: List[Tuple[object, List]] = []
        by_tenant: Dict[str, int] = {}
        for t, buf in picked:
            if t.id not in by_tenant:
                by_tenant[t.id] = len(plan)
                plan.append((t, []))
            plan[by_tenant[t.id]][1].append(buf)
        return plan

    def drain_backlog(self) -> int:
        """Solve everything currently sealed, in admission-sized chunks
        (round-robin, oldest first) — the continuous-mode flush path.
        One giant catch-all dispatch would mint batch shapes the steady
        state never compiles (a 256-row flush program serves exactly one
        flush); fill-sized chunks keep every dispatch on the same
        bounded shape lattice the admission loop runs on.

        Barriers on the dispatch ring each pass: a flush that raced an
        in-flight ticket used to report drained while the ticket's
        windows were still mid-solve (undercounting emitted traces) —
        drained now means queues empty AND zero outstanding tickets."""
        total = 0
        while True:
            self.service.wait_idle(self.service.cfg.drain_timeout_s)
            with self.service._lock:
                cands = self._candidates()
                if not cands:
                    return total
                # class-coherent chunks here too (see _admit): the
                # oldest window's class drains first, fill-sized,
                # round-robin across tenants
                batch_class = self._size_class(
                    max(cands, key=lambda c: c[2])[1])
                per_tenant: Dict[str, deque] = {}
                for t, buf, _age in cands:
                    if self._size_class(buf) == batch_class:
                        per_tenant.setdefault(t.id, deque()).append(
                            (t, buf))
                picked: List[Tuple[object, object]] = []
                limit = self._fill_limit(len(cands))
                order = sorted(per_tenant)
                while len(picked) < limit and any(
                        per_tenant[tid] for tid in order):
                    for tid in order:
                        if len(picked) >= limit:
                            break
                        if per_tenant[tid]:
                            picked.append(per_tenant[tid].popleft())
                plan = self._group_plan(self._quantize(picked))
            total += self.service.solve_admitted(plan)

    # -- the loop ---------------------------------------------------------
    def _loop(self) -> None:
        # CRASH CONTAINMENT (docs/ROBUSTNESS.md): an uncaught exception
        # here used to die silently with serve still accepting spans —
        # every tenant's sealed windows queued forever while POSTs kept
        # returning 200. Any escape now lands in the service's
        # dispatcher-death handler: counted, evented, the degraded
        # gauge flips on /metrics, and serve falls back to the fixed
        # inline pump so the seal→emit path keeps moving.
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — containment, not logic
            self.crashed = True
            self.service._on_dispatcher_death(e)

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
            # a ring-worker error must crash THIS thread (containment
            # lives here): poll even when idle, so a worker death with
            # no further admissions still degrades serve
            self.service.ring_raise_pending()
            with self.service._lock:
                plan, wait = self._admit()
            if plan:
                if self.service.ring_enabled:
                    # overlapped drain (TW_SERVE_INFLIGHT > 1): submit
                    # takes the windows and launches the ticket on the
                    # worker pool, then THIS thread loops straight back
                    # to admitting batch N+1 while batch N executes —
                    # throttled to the ring bound. EWMA/fill bookkeeping
                    # arrives via note_solve when each ticket completes.
                    ticket = self.service.submit_admitted(plan)
                    if ticket is not None:
                        self.service.launch_ticket(ticket)
                        self.service.ring_throttle()
                    self.service.run_adaptations()
                    continue
                # serial path (TW_SERVE_INFLIGHT=1, the kill switch):
                # solve_admitted still drops the service lock around the
                # device dispatch — ingest keeps flowing while the fleet
                # executes (the throughput half of continuous batching;
                # the fixed pump solves inline on the ingesting
                # request's thread)
                t0 = time.perf_counter()
                n = self.service.solve_admitted(plan)
                if n:
                    self.note_solve(time.perf_counter() - t0, n)
                # drift-adaptation tick: refits the retired solve's
                # emissions scheduled run NOW, as their own dispatches,
                # before the next admission — off the hot batch
                self.service.run_adaptations()
                continue
            with self._cond:
                if not self._stop:
                    self._cond.wait(timeout=wait)

    def note_solve(self, solve_s: float, n: int) -> None:
        """Fold one retired dispatch into the pacing model (EWMA solve
        wall, dispatch count, batch-fill histogram). The serial loop
        calls this inline; ring tickets call it from complete_ticket —
        under the ring the EWMA tracks per-ticket device wall, which is
        exactly what the admission deadline math needs (a ticket's wall
        is the lead time an SLO-at-risk window must be admitted by)."""
        if n <= 0:
            return
        self.solve_ewma_s = ((1 - self._EWMA) * self.solve_ewma_s
                             + self._EWMA * solve_s)
        self.dispatches += 1
        _OBS_BATCH_FILL.observe(float(n))

    # -- introspection ----------------------------------------------------
    def stats(self) -> Dict:
        return dict(
            slo_p99_ms=round(self.slo_s * 1000.0, 1),
            fill_target=self.fill_target,
            dispatches=self.dispatches,
            urgent_dispatches=self.urgent_dispatches,
            solve_ewma_ms=round(self.solve_ewma_s * 1000.0, 2),
        )
