"""HTTP front door for the multi-tenant reconstruction service.

Stdlib only (``http.server.ThreadingHTTPServer`` — no new dependencies):
span ingestion is a Jaeger-JSON POST per tenant, queries are GETs over
the tenant's emitted-trace ring. One handler thread per connection; all
state mutation happens inside :class:`TenantService`'s lock.

Endpoint reference (full table + curl quickstart in docs/SERVING.md)::

    POST /api/v1/tenants/<id>/spans                Jaeger-JSON {"data": [...]}
    POST /api/v1/tenants/<id>/capture              raw strace log (?source=)
    POST /api/v1/tenants/<id>/flush                seal+solve now (one tenant)
    POST /api/v1/tenants/<id>/migrate_out          live migration, source half
    POST /api/v1/tenants/<id>/migrate_in           live migration, dest half
    POST /api/v1/flush                             seal+solve now (all)
    POST /api/v1/reset_latency_window              fresh seal→emit p99 window
    GET  /api/v1/tenants                           tenant list
    GET  /api/v1/tenants/<id>/traces               recent trace ids (ring)
    GET  /api/v1/tenants/<id>/traces/<trace_id>    one reconstructed trace
    GET  /api/v1/tenants/<id>/query/delay_culprit  ?percentile=&after_us=&min_conf=
    GET  /api/v1/tenants/<id>/query/low_confidence ?limit=&max_conf=
    GET  /api/v1/tenants/<id>/stats                per-tenant ledger
    GET  /api/v1/stats                             service-wide ledger
    GET  /metrics                                  Prometheus exposition
    GET  /healthz                                  liveness
    GET  /readyz                                   readiness (rolling restarts):
                                                   503 while the AOT shape
                                                   lattice is compiling, 200
                                                   once the configured tier is
                                                   ready (TW_AOT=off: always
                                                   200 — docs/SERVING.md)

Error mapping: bad JSON / malformed payloads (strict mode) -> 400,
unknown tenant or trace -> 404, tenant cap / invalid tenant id -> 429 /
400 (:class:`TenancyError`), tenant migrated off this replica -> 410
(the fleet router re-resolves its pin), saturated per-tenant queues ->
429 with a ``Retry-After`` header derived from the backlog and drain
pace, everything else -> 500 with the exception name (never a silent
hang).
"""

from __future__ import annotations

import json
import re
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from traceweaver_tpu.ingest.jaeger import MalformedSpan
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.serve.tenancy import TenancyError, TenantService

_TENANT_PATH = re.compile(r"^/api/v1/tenants/([^/]+)(/.*)?$")

#: request body cap (64 MB): a runaway POST must not OOM the service
MAX_BODY_BYTES = 64 << 20

# rendered error-body cache: under load-campaign backpressure the same
# 429 body is serialized thousands of times per second on request
# threads — rendered bytes are reused by exact message. Bounded
# (clear-on-cap beats LRU bookkeeping at this size); the hit/render
# ledger on /metrics measures what the cache actually saves.
_OBS_ERROR_BODY = _get_registry().counter(
    "tw_serve_error_body_total",
    "error replies by body source: hit = cached bytes reused, "
    "render = json.dumps ran on the request thread",
    labels=("event",))
_ERROR_BODY_LOCK = threading.Lock()
_ERROR_BODY_CACHE: dict = {}
_ERROR_BODY_CAP = 256


def _error_body(message: str) -> bytes:
    with _ERROR_BODY_LOCK:
        body = _ERROR_BODY_CACHE.get(message)
    if body is None:
        body = json.dumps({"error": message},
                          sort_keys=True).encode("utf-8")
        _OBS_ERROR_BODY.inc(1.0, event="render")
        with _ERROR_BODY_LOCK:
            if len(_ERROR_BODY_CACHE) >= _ERROR_BODY_CAP:
                _ERROR_BODY_CACHE.clear()
            _ERROR_BODY_CACHE[message] = body
    else:
        _OBS_ERROR_BODY.inc(1.0, event="hit")
    return body


class ServeHandler(BaseHTTPRequestHandler):
    """Routes requests onto the owning :class:`TenantService`."""

    server_version = "traceweaver-serve/1.0"
    protocol_version = "HTTP/1.1"

    # -- plumbing ---------------------------------------------------------
    @property
    def service(self) -> TenantService:
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by default
        if self.service.cfg.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, sort_keys=True).encode("utf-8")
        self._send(code, body, headers)

    def _send(self, code: int, body: bytes,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, message: str,
               headers: Optional[dict] = None) -> None:
        self._send(code, _error_body(message), headers=headers)

    def _tenancy_error(self, e: TenancyError) -> None:
        """TenancyError -> status: migrated-out tenants are 410 Gone
        (the fleet router re-resolves the tenant's pin), the tenant cap
        is 429, everything else (bad id, bad transfer) is 400."""
        msg = str(e)
        if "migrated out" in msg:
            self._error(410, msg)
        else:
            self._error(429 if "cap" in msg else 400, msg)

    def _read_body(self, expected: str) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            self._error(400, "bad Content-Length")
            return None
        if length > MAX_BODY_BYTES:
            self._error(413, f"body exceeds {MAX_BODY_BYTES} bytes")
            return None
        raw = self.rfile.read(length) if length else b""
        if not raw:
            self._error(400, f"empty body (expected {expected})")
            return None
        return raw

    def _read_json(self) -> Optional[dict]:
        raw = self._read_body("Jaeger JSON")
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return None

    def _client_seq(self) -> Optional[int]:
        """Optional ``X-TW-Seq`` idempotency header: the client's
        per-tenant retry cursor, echoed on ledgered ingest responses
        and deduplicated when a retry re-sends a seq whose ack was lost
        (docs/ROBUSTNESS.md "Durability")."""
        hdr = self.headers.get("X-TW-Seq")
        if hdr is None:
            return None
        try:
            return int(hdr)
        except ValueError:
            raise TenancyError(
                f"bad X-TW-Seq header: {hdr!r} (expected an integer)"
            ) from None

    def _tenant_route(self) -> Tuple[Optional[str], str, dict]:
        """(tenant_id | None, subpath, query) of the request path."""
        parsed = urlparse(self.path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        m = _TENANT_PATH.match(parsed.path)
        if m:
            return m.group(1), (m.group(2) or ""), query
        return None, parsed.path, query

    # -- verbs ------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        tenant_id, sub, query = self._tenant_route()
        try:
            if tenant_id is not None and sub in ("/spans", "/capture"):
                # explicit backpressure (docs/SERVING.md): a tenant whose
                # pending+spill queues are saturated would DROP the next
                # sealed window — refuse the POST instead, with a
                # Retry-After derived from the backlog and the tenant's
                # observed drain pace, so closed-loop clients back off
                wait_s = self.service.retry_after(tenant_id)
                if wait_s is not None:
                    # fractional header (RFC 9110 allows only integer
                    # seconds, but every client here parses float — and
                    # rounding sub-second waits up to 1s re-quantizes
                    # the closed-loop generators the drain-rate-derived
                    # wait exists to de-synchronize)
                    self._error(
                        429,
                        f"tenant {tenant_id!r} backpressured: sealed-"
                        "window queues full; retry after "
                        f"{wait_s:.2f}s",
                        headers={"Retry-After": f"{max(0.05, wait_s):.2f}"})
                    return
            if tenant_id is not None and sub == "/spans":
                # default: the raw body goes straight to the columnar
                # wire parse (ingest/wire.py) — no request-thread
                # json.loads of a body the wire layer re-reads anyway;
                # TW_WIRE_COLUMNAR=0 keeps the decoded-dict flow and its
                # exact "invalid JSON: ..." 400 body
                raw = self._read_body("Jaeger JSON")
                if raw is None:
                    return
                if _knobs.get_bool("TW_WIRE_COLUMNAR"):
                    payload = raw
                else:
                    try:
                        payload = json.loads(raw)
                    except json.JSONDecodeError as e:
                        self._error(400, f"invalid JSON: {e}")
                        return
                # ack discipline (twlint TW013): with the WAL armed the
                # 200 is written only after wal_ingest's ledgered append
                # of the raw bytes — the ack means the spans survive
                # kill -9; TW_WAL=0 is the byte-identical pre-WAL path
                if _knobs.get_bool("TW_WAL"):
                    self._reply(200, self.service.wal_ingest(
                        tenant_id, payload, raw=raw,
                        client_seq=self._client_seq()))
                else:
                    self._reply(200, self.service.ingest(
                        tenant_id, payload))
            elif tenant_id is not None and sub == "/capture":
                # the collector ingress (docs/COLLECTOR.md): raw strace
                # -f [-ttt] log text (?source= names the capture host;
                # uncaptured callees synthesize as stubs), or a JSON
                # {"sources": {name: text}} bundle carrying every host's
                # capture of the window so cross-source exchanges join
                # and the skew fit sees its pairs
                raw = self._read_body("an strace log or "
                                      '{"sources": {...}}')
                if raw is None:
                    return
                ctype = (self.headers.get("Content-Type") or "").split(
                    ";")[0].strip()
                if ctype == "application/json":
                    try:
                        bundle = json.loads(raw)
                    except json.JSONDecodeError as e:
                        self._error(400, f"invalid JSON: {e}")
                        return
                    captures = (bundle or {}).get("sources")
                    if not isinstance(captures, dict) or not captures:
                        self._error(400, 'expected {"sources": '
                                         '{name: strace log text}}')
                        return
                else:
                    captures = raw.decode("utf-8", "replace")
                # same ack discipline as /spans (twlint TW013): the raw
                # capture body is WAL-appended before the 200
                if _knobs.get_bool("TW_WAL"):
                    self._reply(200, self.service.wal_ingest_capture(
                        tenant_id, captures, raw=raw,
                        ctype=("json" if ctype == "application/json"
                               else "text"),
                        source=query.get("source"),
                        client_seq=self._client_seq()))
                else:
                    self._reply(200, self.service.ingest_capture(
                        tenant_id, captures, source=query.get("source")))
            elif tenant_id is not None and sub == "/flush":
                self.service.tenant(tenant_id, create=False)
                self._reply(200, self.service.flush(tenant_id))
            elif tenant_id is not None and sub == "/migrate_out":
                # live tenant migration, source half (fleet_serve/):
                # checkpoint + sink bytes out, tenant tombstoned here
                self._reply(200, self.service.migrate_out(tenant_id))
            elif tenant_id is not None and sub == "/migrate_in":
                transfer = self._read_json()
                if transfer is None:
                    return
                self._reply(200, self.service.migrate_in(
                    tenant_id, transfer))
            elif tenant_id is None and sub == "/api/v1/flush":
                self._reply(200, self.service.flush())
            elif tenant_id is None and sub == "/api/v1/reset_latency_window":
                # campaign warmup boundary (fleet_serve/campaign.py):
                # warmup windows sit sealed until the warmup flush, so
                # their seal→emit samples are flush-wait artifacts —
                # reset lets the measured phase report its own p99
                self.service.reset_latency_window()
                self._reply(200, {"ok": True})
            else:
                self._error(404, f"no such endpoint: POST {sub or self.path}")
        except TenancyError as e:
            self._tenancy_error(e)
        except MalformedSpan as e:
            self._error(400, f"malformed payload: {e}")
        except KeyError:
            self._error(404, f"unknown tenant {tenant_id!r}")
        except Exception as e:  # noqa: BLE001 — the 500 surface
            self._error(500, f"{type(e).__name__}: {e}")

    def do_GET(self) -> None:  # noqa: N802
        tenant_id, sub, query = self._tenant_route()
        try:
            if tenant_id is None:
                if sub == "/healthz":
                    self._reply(200, {"ok": True,
                                      "tenants": len(self.service.tenants)})
                elif sub == "/readyz":
                    # the rolling-restart gate (docs/SERVING.md): an
                    # orchestrator keeps the previous replica in rotation
                    # until this flips to 200 — i.e. until the AOT shape
                    # lattice tier is compiled and the first real solve
                    # cannot stall on a cold jit. TW_AOT=off = always
                    # ready (nothing is gated). A DRAINING server is
                    # never ready: the SIGTERM handler flips
                    # service.draining before the listener closes, so
                    # routers stop sending to a dying replica instead of
                    # racing its socket teardown.
                    if self.service.draining:
                        self._reply(503, {"ready": False, "draining": True,
                                          "reason": "drain in progress"})
                        return
                    from traceweaver_tpu.runtime import aot as _aot

                    ready, detail = _aot.readiness()
                    self._reply(200 if ready else 503, detail)
                elif sub == "/metrics":
                    # Prometheus text exposition (docs/OBSERVABILITY.md):
                    # the process registry (fleet/stream mirrors, compile
                    # counters) plus the tenancy collector — the latter
                    # derived from the same stats() dict /api/v1/stats
                    # serves, so the two surfaces can never disagree —
                    # plus TW_PROFILE device-memory gauges when enabled
                    from traceweaver_tpu.obs import profile as _obs_profile
                    from traceweaver_tpu.obs.exposition import (
                        CONTENT_TYPE,
                        render_metrics,
                    )

                    extra = (self.service.metrics_families()
                             + _obs_profile.device_memory_families())
                    self._reply_text(200, render_metrics(extra=extra),
                                     CONTENT_TYPE)
                elif sub == "/api/v1/stats":
                    self._reply(200, self.service.stats())
                elif sub == "/api/v1/tenants":
                    self._reply(200, {
                        "tenants": sorted(self.service.tenants)})
                else:
                    self._error(404, f"no such endpoint: GET {self.path}")
                return
            if sub == "/stats":
                self._reply(200, self.service.stats(tenant_id))
            elif sub == "/traces":
                ids = self.service.trace_ids(tenant_id)
                limit = int(query.get("limit", "100"))
                self._reply(200, {"n_traces": len(ids),
                                  "trace_ids": ids[-limit:]})
            elif sub.startswith("/traces/"):
                trace_id = sub[len("/traces/"):]
                rec = self.service.trace(tenant_id, trace_id)
                if rec is None:
                    self._error(404, f"trace {trace_id!r} not in the ring")
                else:
                    self._reply(200, rec)
            elif sub == "/query/delay_culprit":
                percentile = float(query.get("percentile", "0.95"))
                after = query.get("after_us")
                min_conf = query.get("min_conf")
                self._reply(200, self.service.query_delay_culprit(
                    tenant_id, percentile,
                    float(after) if after is not None else None,
                    min_confidence=(float(min_conf)
                                    if min_conf is not None else None)))
            elif sub == "/query/low_confidence":
                self._reply(200, self.service.query_low_confidence(
                    tenant_id,
                    limit=int(query.get("limit", "20")),
                    max_conf=(float(query["max_conf"])
                              if "max_conf" in query else None)))
            else:
                self._error(404, f"no such endpoint: GET {sub}")
        except KeyError:
            self._error(404, f"unknown tenant {tenant_id!r}")
        except TenancyError as e:
            self._tenancy_error(e)
        except ValueError as e:
            self._error(400, str(e))
        except Exception as e:  # noqa: BLE001
            self._error(500, f"{type(e).__name__}: {e}")


class ReconstructionServer(ThreadingHTTPServer):
    """ThreadingHTTPServer bound to one :class:`TenantService`."""

    daemon_threads = True

    def __init__(self, service: TenantService, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.service = service
        super().__init__((host, port), ServeHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def make_server(service: TenantService, host: str = "127.0.0.1",
                port: int = 0) -> ReconstructionServer:
    """Bind (port 0 = ephemeral, the test mode). Call ``serve_forever``
    on a thread; the tier-1 smoke does exactly that."""
    return ReconstructionServer(service, host, port)


def run_server(service: TenantService, host: str, port: int,
               verbose: bool = True) -> dict:
    """The CLI's blocking entry: serve until SIGTERM/SIGINT, then
    gracefully drain — stop accepting, checkpoint every tenant within
    the drain budget (``TW_SERVE_DRAIN_S``), close sinks. Returns the
    drain summary."""
    server = make_server(service, host, port)
    stop = threading.Event()

    def _signal(signum, _frame):
        if verbose:
            print(f"[serve] signal {signum}: draining "
                  f"({service.cfg.drain_timeout_s:.0f}s budget)")
        # readiness flips FIRST: /readyz answers 503 for every request
        # that still lands while the listener winds down, so a router's
        # health probe (or a rolling-restart gate) stops routing here
        # before the socket disappears
        service.begin_drain()
        stop.set()
        # shutdown() must run off the serve_forever thread
        threading.Thread(target=server.shutdown, daemon=True).start()

    prev = {s: signal.signal(s, _signal)
            for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        if verbose:
            print(f"[serve] listening on http://{host}:{server.port} "
                  f"(max {service.cfg.max_tenants} tenants, "
                  f"prec={service.precision}) — "
                  "POST /api/v1/tenants/<id>/spans")
        server.serve_forever(poll_interval=0.2)
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        server.server_close()
    summary = service.drain()
    if verbose:
        st = service.stats()
        print("[serve] drained: %d tenants checkpointed, %d skipped, "
              "%d past the drain budget; %d windows solved in %d shared "
              "+ %d isolated fleet calls"
              % (summary["checkpointed"], summary["skipped"],
                 summary["timed_out"],
                 st["dispatch"]["pumped_windows"],
                 st["dispatch"]["shared_solves"],
                 st["dispatch"]["isolated_solves"]))
    return summary
