"""Trace repair + Jaeger-JSON conversion for MSCallGraph traces.

Clean-room equivalent of the reference's ``real-parser.py``
(reference alibaba-analysis/real-parser.py:35-359):

- sort a trace's rows by dotted rpc_id (version-style ordering);
- drop oversized traces (>200 spans);
- delete mirrored duplicate rows (the dataset logs some calls twice, once
  with negative rt — ``fixDuplicates``, :35-61);
- fill missing caller/callee ('(?)') from the parent / sibling / child
  rows when unambiguous (``checkNeighbours``/``fixMissingInSpan``,
  :134-187);
- validate the rpc_id hierarchy is a single-rooted tree
  (``buildCallGraph``, :283-306);
- emit Jaeger JSON with a synthetic server+client record pair per non-root
  call sharing the rpc_id as spanID, ``caller``/``callee``/``requestType``
  fields and ms→µs×1000 times (``convertToJaegerFormat``, :308-359).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.alibaba.schema import (
    CallRecord,
    is_missing,
    parent_rpc_id,
    rpc_depth,
)

MAX_TRACE_SPANS = 200


def _rpc_sort_key(rpc_id: str) -> Tuple:
    parts = []
    for p in rpc_id.split("."):
        try:
            parts.append(int(p))
        except ValueError:
            parts.append(0)
    return tuple(parts)


def _dedupe_mirrored(records: List[CallRecord]) -> List[CallRecord]:
    """Drop the second of a mirrored pair: same (trace, rpc_id, caller,
    rpc_type, callee) logged twice, one side with negative rt."""
    seen: Dict[Tuple, CallRecord] = {}
    out: List[CallRecord] = []
    for rec in records:
        key = (rec.trace_id, rec.rpc_id, rec.caller, rec.rpc_type, rec.callee)
        prev = seen.get(key)
        if prev is not None and (prev.rt_ms >= 0) != (rec.rt_ms >= 0):
            # mirrored duplicate: keep the non-negative-rt side
            if prev.rt_ms < 0 <= rec.rt_ms:
                out[out.index(prev)] = rec
                seen[key] = rec
            continue
        seen[key] = rec
        out.append(rec)
    return out


def _fill_missing(records: List[CallRecord]) -> bool:
    """Fill '(?)' caller/callee fields from relatives; False if unfixable."""
    by_rpc: Dict[str, List[CallRecord]] = {}
    for rec in records:
        by_rpc.setdefault(rec.rpc_id, []).append(rec)

    for rec in records:
        if is_missing(rec.caller):
            parent = by_rpc.get(parent_rpc_id(rec.rpc_id), [])
            siblings = [
                r for r in records
                if parent_rpc_id(r.rpc_id) == parent_rpc_id(rec.rpc_id)
                and r.rpc_id != rec.rpc_id
            ]
            if parent and not is_missing(parent[0].callee):
                rec.caller = parent[0].callee
            elif siblings and not is_missing(siblings[0].caller):
                rec.caller = siblings[0].caller
            else:
                return False
        if is_missing(rec.callee):
            children = [
                r for r in records if parent_rpc_id(r.rpc_id) == rec.rpc_id
            ]
            if children and not is_missing(children[0].caller):
                rec.callee = children[0].caller
            else:
                return False
    return True


def _validate_tree(records: List[CallRecord]) -> bool:
    """rpc_ids must form a single-rooted tree with unique ids."""
    if not records:
        return False
    seen = set()
    root_depth = rpc_depth(records[0].rpc_id)
    for i, rec in enumerate(records):
        if rec.rpc_id in seen:
            return False
        seen.add(rec.rpc_id)
        if i != 0:
            if rpc_depth(rec.rpc_id) == root_depth:
                return False  # multiple roots
            if parent_rpc_id(rec.rpc_id) not in seen:
                return False  # orphan
    return True


def repair_trace(records: List[CallRecord]) -> Optional[List[CallRecord]]:
    """Sort, dedupe, fill, validate. None when the trace is unusable."""
    records = sorted(records, key=lambda r: _rpc_sort_key(r.rpc_id))
    if len(records) > MAX_TRACE_SPANS:
        return None
    records = _dedupe_mirrored(records)
    if not _fill_missing(records):
        return None
    if not _validate_tree(records):
        return None
    return records


def convert_trace_to_jaeger(records: List[CallRecord]) -> dict:
    """Jaeger-JSON dict with server+client record pairs per call."""
    root_rpc = records[0].rpc_id
    spans = []
    for rec in records:
        server = {
            "traceID": rec.trace_id,
            "startTime": rec.timestamp_ms * 1000,
            "spanID": rec.rpc_id,
            "caller": rec.caller,
            "requestType": rec.rpc_type,
            "callee": rec.callee,
            "interface": rec.interface,
            "duration": abs(rec.rt_ms) * 1000,
            "tags": [{"key": "span.kind", "value": "server"}],
            "references": [],
            "processID": rec.callee,
        }
        if rec.rpc_id != root_rpc:
            server["references"].append({
                "refType": "CHILD_OF",
                "traceID": rec.trace_id,
                "spanID": parent_rpc_id(rec.rpc_id),
            })
        spans.append(server)
        if rec.rpc_id != root_rpc:
            client = dict(server)
            client["tags"] = [{"key": "span.kind", "value": "client"}]
            client["processID"] = rec.caller
            client["references"] = [dict(r) for r in server["references"]]
            spans.append(client)
    return {"data": [{"traceID": records[0].trace_id, "spans": spans}]}


def write_jaeger_trace(trace: dict, out_dir: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    trace_id = trace["data"][0]["traceID"]
    path = os.path.join(out_dir, f"{trace_id}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, ensure_ascii=False)
    return path
