"""MSCallGraph row schema.

Alibaba's cluster-trace-microservices-v2021 ``MSCallGraph_*.csv`` rows, as
consumed by the reference pipeline (reference alibaba-analysis/
preprocess.py:40-52, real-parser.py:308-359): columns
``[row_index, traceid, timestamp_ms, rpc_id, um, rpctype, dm, interface,
rt_ms]`` where ``rpc_id`` is the dotted call-position id ("0.1.2"), ``um``
the caller microservice, ``dm`` the callee, and ``rt`` the response time in
milliseconds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

# Values the dataset uses for unknown fields (reference real-parser.py's
# ``search_strings``).
MISSING_VALUES = ("(?)", "", "None", "nan")

# column indices (reference code addresses rows positionally)
COL_TRACE_ID = 1
COL_TIMESTAMP = 2
COL_RPC_ID = 3
COL_CALLER = 4
COL_RPC_TYPE = 5
COL_CALLEE = 6
COL_INTERFACE = 7
COL_RT = 8


@dataclass
class CallRecord:
    trace_id: str
    timestamp_ms: int
    rpc_id: str
    caller: str
    rpc_type: str
    callee: str
    interface: str
    rt_ms: int

    @classmethod
    def from_row(cls, row: List[str]) -> "CallRecord":
        return cls(
            trace_id=row[COL_TRACE_ID],
            timestamp_ms=int(float(row[COL_TIMESTAMP])),
            rpc_id=row[COL_RPC_ID],
            caller=row[COL_CALLER],
            rpc_type=row[COL_RPC_TYPE],
            callee=row[COL_CALLEE],
            interface=row[COL_INTERFACE],
            rt_ms=int(float(row[COL_RT])),
        )

    def to_row(self, index: int = 0) -> List[str]:
        return [str(index), self.trace_id, str(self.timestamp_ms), self.rpc_id,
                self.caller, self.rpc_type, self.callee, self.interface,
                str(self.rt_ms)]


def is_missing(value: str) -> bool:
    return value in MISSING_VALUES


def parent_rpc_id(rpc_id: str) -> str:
    return ".".join(rpc_id.split(".")[:-1])


def rpc_depth(rpc_id: str) -> int:
    return len(rpc_id.split("."))
