"""Call-graph-signature grouping.

Clean-room equivalent of the reference's ``analysis.py``
(reference alibaba-analysis/analysis.py:99-126, 214-265): every trace gets
a hash signature over its depth-ordered service multiset; traces sharing a
signature form one call-graph dataset (the ``call_graph_0..14`` dirs exp5
sweeps).
"""

from __future__ import annotations

import hashlib
import os
import shutil
from collections import Counter, defaultdict
from typing import Dict, List

from traceweaver_tpu.alibaba.schema import CallRecord, rpc_depth


def call_graph_signature(records: List[CallRecord]) -> str:
    """Hash of the depth-ordered sorted (caller, callee, rpc_type) multiset —
    stable under span reordering within a depth, sensitive to topology."""
    by_depth: Dict[int, List[str]] = defaultdict(list)
    for rec in records:
        by_depth[rpc_depth(rec.rpc_id)].append(
            f"{rec.caller}->{rec.callee}:{rec.rpc_type}"
        )
    parts = []
    for depth in sorted(by_depth):
        parts.append(f"{depth}|" + ",".join(sorted(by_depth[depth])))
    return hashlib.md5(";".join(parts).encode()).hexdigest()


def group_traces(
    traces: Dict[str, List[CallRecord]],
    out_root: str,
    top_n: int = 15,
    min_traces: int = 2,
    writer=None,
) -> List[str]:
    """Group repaired traces by signature; write the ``top_n`` most common
    call graphs as ``call_graph_<i>/`` Jaeger dirs under ``out_root``.

    ``writer(records, out_dir)`` defaults to Jaeger conversion+write.
    Returns the list of produced dirs.
    """
    from traceweaver_tpu.alibaba.convert import (
        convert_trace_to_jaeger,
        write_jaeger_trace,
    )

    if writer is None:
        def writer(records, out_dir):
            write_jaeger_trace(convert_trace_to_jaeger(records), out_dir)

    by_sig: Dict[str, List[str]] = defaultdict(list)
    for trace_id, records in traces.items():
        by_sig[call_graph_signature(records)].append(trace_id)

    ranked = [
        (sig, tids) for sig, tids in
        sorted(by_sig.items(), key=lambda kv: -len(kv[1]))
        if len(tids) >= min_traces
    ][:top_n]

    out_dirs = []
    for i, (_sig, trace_ids) in enumerate(ranked):
        out_dir = os.path.join(out_root, f"call_graph_{i}")
        if os.path.isdir(out_dir):
            shutil.rmtree(out_dir)
        os.makedirs(out_dir, exist_ok=True)
        for tid in trace_ids:
            writer(traces[tid], out_dir)
        out_dirs.append(out_dir)
    return out_dirs
