"""Shard MSCallGraph CSVs per trace.

Streaming splitter (reference alibaba-analysis/preprocess.py:27-113): read
each ``MSCallGraph_<k>.csv`` shard, group rows by trace id, and append each
trace's rows into its origin shard's directory. Rows of a trace can
straddle shard files; a bounded lookback resolves stragglers into the shard
where the trace first appeared (reference uses a 5-shard lookback).
"""

from __future__ import annotations

import csv
import os
from collections import defaultdict
from typing import Dict, Iterable, List

from traceweaver_tpu.alibaba.schema import COL_TRACE_ID


def split_shard_csv(
    csv_path: str,
    out_root: str,
    shard_id: int,
    trace_origin: Dict[str, int],
    lookback: int = 5,
) -> int:
    """Split one shard CSV into per-trace CSV files.

    ``trace_origin`` maps trace ids to the shard where they first appeared;
    it is shared across calls so straddling rows land with their trace.
    Returns the number of traces touched.
    """
    groups: Dict[str, List[List[str]]] = defaultdict(list)
    with open(csv_path, newline="") as f:
        for row in csv.reader(f):
            if not row or row[COL_TRACE_ID] == "traceid":
                continue
            tid = row[COL_TRACE_ID]
            trace_origin.setdefault(tid, shard_id)
            groups[tid].append(row)

    for tid, rows in groups.items():
        origin = trace_origin[tid]
        if origin < shard_id - lookback:
            origin = shard_id  # beyond lookback: keep local (counted as error
            # in the reference, preprocess.py num_lookback_errors)
        shard_dir = os.path.join(out_root, f"shard{origin}")
        os.makedirs(shard_dir, exist_ok=True)
        with open(os.path.join(shard_dir, f"{tid}.csv"), "a", newline="") as f:
            csv.writer(f).writerows(rows)
    return len(groups)


def split_all(csv_paths: Iterable[str], out_root: str, lookback: int = 5) -> int:
    trace_origin: Dict[str, int] = {}
    total = 0
    for shard_id, path in enumerate(csv_paths):
        total += split_shard_csv(path, out_root, shard_id, trace_origin,
                                 lookback)
    return total
