"""Alibaba cluster-trace (MSCallGraph) pipeline.

Mirrors the reference's offline preprocessing chain (reference:
src/trace_reconstructor/ports/python/alibaba-analysis/): shard the
clusterdata CSVs per trace, repair and convert each trace to Jaeger JSON
with synthetic server/client record pairs, and group traces into
call-graph-signature datasets (``call_graph_0..14``) that exp5 sweeps.

Because the reference release ships ``call_graph_data`` only as a git-LFS
pointer and the clusterdata CSVs are external (BASELINE.md artifact gaps),
:mod:`traceweaver_tpu.alibaba.synthesize` can generate MSCallGraph-format
rows for 15 synthetic topologies and push them through the *same* repair /
convert / group pipeline to produce exp5-ready inputs.
"""

from traceweaver_tpu.alibaba.convert import (  # noqa: F401
    convert_trace_to_jaeger,
    repair_trace,
)
from traceweaver_tpu.alibaba.grouping import call_graph_signature, group_traces  # noqa: F401
from traceweaver_tpu.alibaba.schema import CallRecord  # noqa: F401
