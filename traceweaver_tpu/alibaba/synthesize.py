"""Synthetic MSCallGraph generator — regenerates the exp5 input artifact.

The reference release ships ``data/alibaba_microservices/call_graph_data``
only as a 134-byte git-LFS pointer and the upstream clusterdata CSVs are
external downloads (BASELINE.md artifact gaps), so exp5 cannot run from the
repo alone. This generator produces MSCallGraph-format call records for a
configurable number of service topologies — trees with Alibaba-like shape
(fan-out 1-3, depth 2-4, occasional self-calls that exercise the ``-loop``
remapping, executor.py:386-399) — and pushes them through the *real*
repair → convert → group pipeline so the output exercises the same code
paths real clusterdata would.

Usage::

    python -m traceweaver_tpu.alibaba.synthesize --out DIR \
        [--n-graphs 15] [--traces-per-graph 1000] [--seed 10]
"""

from __future__ import annotations

import argparse
import random
from typing import Dict, List

from traceweaver_tpu.alibaba.convert import repair_trace
from traceweaver_tpu.alibaba.grouping import group_traces
from traceweaver_tpu.alibaba.schema import CallRecord


def _random_topology(rng: random.Random, n_services: int,
                     multi_invoke_rate: float = 0.0):
    """A call tree as a list of (rpc_id, caller_idx, callee_idx).

    By default upholds the invariant the reference's signature-grouped
    Alibaba data holds (and its transforms/plugin contract assume,
    reference transforms.py:26-29): every service is the callee of AT
    MOST ONE call per trace, so each per-service partition carries
    exactly one span per trace. Self-calls (exercising the ``-loop``
    remap of the ingester, reference executor.py:386-399) are emitted
    only as childless leaves — the remapped ``svc-loop`` callee then has
    no outgoing spans and is skipped by the per-service partitioner
    rather than creating a multi-incoming grading ambiguity.

    ``multi_invoke_rate`` > 0 VIOLATES that invariant the way real
    MSCallGraph data does: with that probability an expansion step
    re-invokes an already-used service (as a leaf) instead of a fresh
    one. Such services carry several server spans per trace; the
    pipeline must respond exactly as the reference does on real data —
    services called from multiple distinct upstreams are skipped by the
    partitioner (reference executor.py:949-950), same-upstream repeats
    stay and are graded under the first-match ground-truth join
    (helpers/utils.py:22-32).
    """
    depth = rng.randint(2, 4)
    calls = []
    root_svc = 0
    available = [s for s in range(n_services) if s != root_svc]
    rng.shuffle(available)
    used = [root_svc]

    def expand(rpc_id: str, svc: int, level: int) -> None:
        if level >= depth:
            return
        fanout = rng.randint(1, 3) if level < depth - 1 else rng.randint(0, 2)
        self_called = False
        for i in range(fanout):
            child_id = f"{rpc_id}.{i + 1}"
            # occasional self-call (caller == callee) to exercise -loop
            # logic; always a leaf, at most one per service (see docstring)
            if rng.random() < 0.08 and not self_called:
                calls.append((child_id, svc, svc))
                self_called = True
                continue
            if (multi_invoke_rate > 0 and len(used) > 1
                    and rng.random() < multi_invoke_rate):
                # re-invoke an existing service (leaf, not this caller):
                # a multi-invocation callee
                again = rng.choice([u for u in used if u != svc] or [svc])
                if again != svc:
                    calls.append((child_id, svc, again))
                    continue
            if not available:
                return
            child_svc = available.pop()
            used.append(child_svc)
            calls.append((child_id, svc, child_svc))
            expand(child_id, child_svc, level + 1)

    calls.append(("0", -1, root_svc))
    expand("0", root_svc, 0)
    return calls


#: defect-injection profile for the "hard" corpus (VERDICT r4 #5): rates
#: are per-trace probabilities of each defect class real MSCallGraph data
#: exhibits (reference real-parser.py:134-187 missing-field fill,
#: :35-61 mirrored duplicates, :254-281 orphan/multi-root rejection).
MESSY_DEFAULT = {
    "multi_invoke": 0.15,  # service re-invoked within a trace (topology)
    "missing": 0.20,       # '(?)' caller/callee, neighbour-repairable
    "missing_hard": 0.03,  # '(?)' callee on a leaf — unrepairable, dropped
    "dup": 0.15,           # mirrored duplicate row with negative rt
    "orphan": 0.04,        # row under a nonexistent parent — dropped
    "multiroot": 0.03,     # second depth-0 row — dropped
}


def synthesize_corpus(
    out_root: str,
    n_graphs: int = 15,
    traces_per_graph: int = 1000,
    seed: int = 10,
    base_gap_ms: int = 2000,
    messy: Dict[str, float] = None,
    replica_dist: str = "loguniform-16-128",
    stats: Dict[str, int] = None,
    n_services: int = 60,
) -> List[str]:
    # base_gap_ms defaults to ~2s between trace arrivals: clusterdata traces
    # spread over hours, and exp5's compress_factor=15000 sweep only makes
    # sense if the compressed inter-arrival (gap/15000 ~ 130-260us) stays
    # above timestamp resolution while sitting far below the ms-scale edge
    # delays — the "hundreds of interleaved requests" regime the reference
    # stresses (exp5/run_experiment.sh:270-284). A 40ms gap would compress
    # to ~3us, under the per-edge jitter, making every method (including
    # the reference's V3) statistically unable to distinguish candidates.
    #
    # That floor is exactly why the reference's own executor divides the
    # compress factor by the service's REPLICA COUNT
    # (executor.py:922-929, loading data/misc/service_to_replica_new.pickle
    # — absent from the release, SURVEY §6 artifact gap): a 15000x corpus
    # load spread over ~a hundred replicas stresses each replica at
    # ~100-1000x, the "hard but physically identifiable" regime of fig6a.
    # This generator therefore also regenerates the replica-table artifact
    # (Alibaba-like log-uniform 16..128 replicas per microservice) next to
    # the corpus; without it every service defaults to 1 replica and the
    # top rungs measure an unidentifiability floor, not solver quality.
    """Generate, repair, convert, and group; returns the call_graph dirs.

    ``messy`` (a rate dict, see :data:`MESSY_DEFAULT`) injects the defect
    classes real clusterdata carries BEFORE the repair pipeline runs, so
    the corpus exercises ``convert.repair_trace`` the way real-parser.py
    faces real shards: repairable defects (fillable '(?)' fields,
    mirrored duplicates) must survive repair; structural corruption
    (orphans, multi-roots, unrepairable '(?)') must be rejected.
    ``stats`` (optional dict) receives emitted/repaired/dropped counters.
    ``replica_dist`` parameterizes the regenerated replica table
    (``loguniform-A-B`` or ``fixed-N``) — the exp5 top-rung absolute
    accuracies scale with this assumption (see BASELINE.md), so the knob
    exists to measure sensitivity.

    ``n_services`` sizes the cluster-wide microservice pool the call
    graphs sample from (default 60, the historical corpus). The campaign
    corpus ladder (``traceweaver_tpu/campaign/corpus.py``) widens it on
    the top rungs so service-count scaling is measured, not held fixed.
    """
    rng = random.Random(seed)
    messy = messy or {}
    services = [f"MS_{i:05d}" for i in range(n_services)]
    traces: Dict[str, List[CallRecord]] = {}
    counters = stats if stats is not None else {}
    counters.update(emitted=0, kept=0, dropped=0, defect_injected=0)

    t_now = 1_600_000_000_000  # epoch ms
    for g in range(n_graphs):
        # clamp to the pool: a narrow campaign rung (n_services < 12)
        # must not over-sample; the default 60-service pool draws the
        # historical randint(3, 12) sequence unchanged
        n_services = rng.randint(3, min(12, len(services)))
        svc_ids = rng.sample(range(len(services)), n_services)
        topology = _random_topology(
            rng, n_services,
            multi_invoke_rate=messy.get("multi_invoke", 0.0))
        # per-edge base latency in ms (int; the dataset is ms-resolution)
        edge_delay = {
            rpc_id: rng.randint(2, 25) for rpc_id, _, _ in topology
        }
        for t in range(traces_per_graph):
            tid = f"cg{g}_{t:06d}_{rng.randrange(1 << 32):08x}"
            t_now += rng.randint(base_gap_ms // 2, base_gap_ms * 2)
            records: List[CallRecord] = []

            def emit(rpc_id: str, caller: int, callee: int,
                     start_ms: int) -> int:
                """Returns the call's duration (ms)."""
                kids = [c for c in topology if
                        ".".join(c[0].split(".")[:-1]) == rpc_id]
                cursor = start_ms + edge_delay[rpc_id] + rng.randint(0, 4)
                child_total = 0
                for (kid_id, kc, kd) in kids:
                    dur = emit(kid_id, kc, kd, cursor)
                    cursor += dur + rng.randint(1, 6)
                    child_total = cursor - start_ms
                own = rng.randint(2, 12)
                total = max(edge_delay[rpc_id] + child_total + own, 1)
                records.append(CallRecord(
                    trace_id=tid,
                    timestamp_ms=start_ms,
                    rpc_id=rpc_id,
                    caller=services[svc_ids[caller]] if caller >= 0 else "USER",
                    rpc_type="rpc",
                    callee=services[svc_ids[callee]],
                    interface=f"if_{rpc_id}",
                    rt_ms=total,
                ))
                return total

            _, root_caller, root_callee = topology[0]
            emit("0", root_caller, root_callee, t_now)
            counters["emitted"] += 1
            counters["defect_injected"] += _inject_defects(
                rng, records, messy)
            repaired = repair_trace(records)
            if repaired is not None:
                traces[tid] = repaired
                counters["kept"] += 1
            else:
                counters["dropped"] += 1

    write_replica_table(out_root, services, seed, dist=replica_dist)
    return group_traces(traces, out_root, top_n=n_graphs, min_traces=2)


def _inject_defects(rng: random.Random, records, messy: Dict[str, float]) -> int:
    """Corrupt one emitted trace in place per the ``messy`` rate dict.

    Repairable classes (``missing``, ``dup``) must survive
    ``convert.repair_trace``; structural classes (``missing_hard``,
    ``orphan``, ``multiroot``) must be rejected by it — both asserted by
    tests/test_alibaba.py. Returns the number of defects injected.
    """
    from dataclasses import replace

    if not messy or len(records) < 2:
        return 0
    n = 0
    non_root = [r for r in records if r.rpc_id != "0"]
    with_children = [
        r for r in records
        if any(o.rpc_id.startswith(r.rpc_id + ".") for o in records)
    ]
    leaves = [r for r in non_root if r not in with_children]

    if non_root and rng.random() < messy.get("missing", 0.0):
        # repairable: caller fillable from the parent row's callee
        # (real-parser.py:134-177 checkNeighbours)
        replace_in = rng.choice(non_root)
        replace_in.caller = "(?)"
        n += 1
    if with_children and rng.random() < messy.get("missing", 0.0):
        # repairable: callee fillable from a child row's caller
        rec = rng.choice(with_children)
        if rec.rpc_id != "0":
            rec.callee = "(?)"
            n += 1
    if leaves and rng.random() < messy.get("missing_hard", 0.0):
        # unrepairable: a leaf's callee has no child to fill from —
        # the repairer must reject the whole trace
        rng.choice(leaves).callee = "(?)"
        n += 1
    if non_root and rng.random() < messy.get("dup", 0.0):
        # mirrored duplicate row with negative rt (fixDuplicates :35-61)
        rec = rng.choice(non_root)
        records.append(replace(rec, rt_ms=-abs(rec.rt_ms)))
        n += 1
    if leaves and rng.random() < messy.get("orphan", 0.0):
        # row under a nonexistent parent (orphan detection :254-281)
        rec = rng.choice(leaves)
        records.append(replace(rec, rpc_id=rec.rpc_id + ".7.7"))
        n += 1
    if rng.random() < messy.get("multiroot", 0.0):
        # a second depth-0 row — multi-rooted trace, rejected
        rec = records[-1]
        records.append(replace(rec, rpc_id="1"))
        n += 1
    return n


def replica_counts(services: List[str], seed: int = 10,
                   dist: str = "loguniform-16-128") -> Dict[str, int]:
    """Per-service replica counts under a named distribution.

    ``loguniform-A-B`` draws log-uniform in [A, B] (default 16..128 —
    Alibaba microservices run tens to hundreds of replicas); ``fixed-N``
    gives every service N replicas. The real artifact's contents are
    unknown (the release ships no ``data/misc/``), so the distribution
    is an ASSUMPTION the exp5 top-rung accuracies inherit — the knob
    exists so the sensitivity can be measured (see BASELINE.md).
    """
    import math

    rng = random.Random(seed + 1)
    kind, _, rest = dist.partition("-")
    if kind == "fixed":
        n = int(rest)
        return {svc: n for svc in services}
    if kind == "loguniform":
        lo, hi = (int(x) for x in rest.split("-"))
        return {
            svc: int(round(2 ** rng.uniform(math.log2(lo), math.log2(hi))))
            for svc in services
        }
    raise ValueError(f"unknown replica distribution {dist!r}")


def write_replica_table(out_root: str, services: List[str],
                        seed: int = 10,
                        dist: str = "loguniform-16-128") -> str:
    """Regenerate the ``service_to_replica_new.pickle`` artifact.

    The reference loads it unconditionally (executor.py:912) and scales
    each service's compress factor by its replica count (:922-929), but
    the release ships no ``data/misc/`` at all. Counts come from
    :func:`replica_counts`, deterministically from ``seed`` so the
    corpus and table regenerate together.

    Location: when ``out_root`` sits in the reference layout
    (``<data_root>/alibaba_microservices/call_graph_data``) the table
    goes to ``<data_root>/misc`` (the reference's path anchor,
    executor.py:912); for any other ``--out`` it stays INSIDE the output
    tree at ``<out_root>/misc`` — never above it. The CLI checks
    repo-root ``data/misc``, then ``<dataset>/../misc``, then
    ``<dataset>/../../../misc`` (runtime/cli.py).
    """
    import os
    import pickle

    counts = replica_counts(services, seed, dist)
    table = {
        svc: [f"{svc}.r{i}" for i in range(n)] for svc, n in counts.items()
    }
    root = os.path.abspath(out_root)
    parent = os.path.dirname(root)
    if (os.path.basename(root) == "call_graph_data"
            and os.path.basename(parent) == "alibaba_microservices"):
        misc = os.path.join(os.path.dirname(parent), "misc")
    else:
        misc = os.path.join(root, "misc")
    os.makedirs(misc, exist_ok=True)
    path = os.path.join(misc, "service_to_replica_new.pickle")
    with open(path, "wb") as f:
        pickle.dump(table, f, protocol=pickle.HIGHEST_PROTOCOL)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", required=True)
    p.add_argument("--n-graphs", type=int, default=15)
    p.add_argument("--traces-per-graph", type=int, default=1000)
    p.add_argument("--seed", type=int, default=10)
    p.add_argument("--messy", action="store_true",
                   help="inject the MESSY_DEFAULT defect profile (real-"
                        "clusterdata realism: multi-invocation callees, "
                        "'(?)' fields, mirrored dups, orphans, multi-roots)")
    p.add_argument("--replica-dist", default="loguniform-16-128",
                   help="replica-table distribution: loguniform-A-B or "
                        "fixed-N (sensitivity knob for the exp5 ladder)")
    args = p.parse_args(argv)
    stats: Dict[str, int] = {}
    dirs = synthesize_corpus(args.out, args.n_graphs, args.traces_per_graph,
                             args.seed,
                             messy=MESSY_DEFAULT if args.messy else None,
                             replica_dist=args.replica_dist, stats=stats)
    print(f"wrote {len(dirs)} call-graph datasets under {args.out} "
          f"({stats})")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
