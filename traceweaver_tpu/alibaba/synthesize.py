"""Synthetic MSCallGraph generator — regenerates the exp5 input artifact.

The reference release ships ``data/alibaba_microservices/call_graph_data``
only as a 134-byte git-LFS pointer and the upstream clusterdata CSVs are
external downloads (BASELINE.md artifact gaps), so exp5 cannot run from the
repo alone. This generator produces MSCallGraph-format call records for a
configurable number of service topologies — trees with Alibaba-like shape
(fan-out 1-3, depth 2-4, occasional self-calls that exercise the ``-loop``
remapping, executor.py:386-399) — and pushes them through the *real*
repair → convert → group pipeline so the output exercises the same code
paths real clusterdata would.

Usage::

    python -m traceweaver_tpu.alibaba.synthesize --out DIR \
        [--n-graphs 15] [--traces-per-graph 1000] [--seed 10]
"""

from __future__ import annotations

import argparse
import random
from typing import Dict, List

from traceweaver_tpu.alibaba.convert import repair_trace
from traceweaver_tpu.alibaba.grouping import group_traces
from traceweaver_tpu.alibaba.schema import CallRecord


def _random_topology(rng: random.Random, n_services: int):
    """A call tree as a list of (rpc_id, caller_idx, callee_idx).

    Upholds the invariant the reference's signature-grouped Alibaba data
    holds (and its transforms/plugin contract assume, reference
    transforms.py:26-29): every service is the callee of AT MOST ONE call
    per trace, so each per-service partition carries exactly one span per
    trace. Self-calls (exercising the ``-loop`` remap of the ingester,
    reference executor.py:386-399) are emitted only as childless leaves —
    the remapped ``svc-loop`` callee then has no outgoing spans and is
    skipped by the per-service partitioner rather than creating a
    multi-incoming grading ambiguity.
    """
    depth = rng.randint(2, 4)
    calls = []
    root_svc = 0
    available = [s for s in range(n_services) if s != root_svc]
    rng.shuffle(available)

    def expand(rpc_id: str, svc: int, level: int) -> None:
        if level >= depth:
            return
        fanout = rng.randint(1, 3) if level < depth - 1 else rng.randint(0, 2)
        self_called = False
        for i in range(fanout):
            child_id = f"{rpc_id}.{i + 1}"
            # occasional self-call (caller == callee) to exercise -loop
            # logic; always a leaf, at most one per service (see docstring)
            if rng.random() < 0.08 and not self_called:
                calls.append((child_id, svc, svc))
                self_called = True
                continue
            if not available:
                return
            child_svc = available.pop()
            calls.append((child_id, svc, child_svc))
            expand(child_id, child_svc, level + 1)

    calls.append(("0", -1, root_svc))
    expand("0", root_svc, 0)
    return calls


def synthesize_corpus(
    out_root: str,
    n_graphs: int = 15,
    traces_per_graph: int = 1000,
    seed: int = 10,
    base_gap_ms: int = 2000,
) -> List[str]:
    # base_gap_ms defaults to ~2s between trace arrivals: clusterdata traces
    # spread over hours, and exp5's compress_factor=15000 sweep only makes
    # sense if the compressed inter-arrival (gap/15000 ~ 130-260us) stays
    # above timestamp resolution while sitting far below the ms-scale edge
    # delays — the "hundreds of interleaved requests" regime the reference
    # stresses (exp5/run_experiment.sh:270-284). A 40ms gap would compress
    # to ~3us, under the per-edge jitter, making every method (including
    # the reference's V3) statistically unable to distinguish candidates.
    #
    # That floor is exactly why the reference's own executor divides the
    # compress factor by the service's REPLICA COUNT
    # (executor.py:922-929, loading data/misc/service_to_replica_new.pickle
    # — absent from the release, SURVEY §6 artifact gap): a 15000x corpus
    # load spread over ~a hundred replicas stresses each replica at
    # ~100-1000x, the "hard but physically identifiable" regime of fig6a.
    # This generator therefore also regenerates the replica-table artifact
    # (Alibaba-like log-uniform 16..128 replicas per microservice) next to
    # the corpus; without it every service defaults to 1 replica and the
    # top rungs measure an unidentifiability floor, not solver quality.
    """Generate, repair, convert, and group; returns the call_graph dirs."""
    rng = random.Random(seed)
    services = [f"MS_{i:05d}" for i in range(60)]
    traces: Dict[str, List[CallRecord]] = {}

    t_now = 1_600_000_000_000  # epoch ms
    for g in range(n_graphs):
        n_services = rng.randint(3, 12)
        svc_ids = rng.sample(range(len(services)), n_services)
        topology = _random_topology(rng, n_services)
        # per-edge base latency in ms (int; the dataset is ms-resolution)
        edge_delay = {
            rpc_id: rng.randint(2, 25) for rpc_id, _, _ in topology
        }
        for t in range(traces_per_graph):
            tid = f"cg{g}_{t:06d}_{rng.randrange(1 << 32):08x}"
            t_now += rng.randint(base_gap_ms // 2, base_gap_ms * 2)
            records: List[CallRecord] = []

            def emit(rpc_id: str, caller: int, callee: int,
                     start_ms: int) -> int:
                """Returns the call's duration (ms)."""
                kids = [c for c in topology if
                        ".".join(c[0].split(".")[:-1]) == rpc_id]
                cursor = start_ms + edge_delay[rpc_id] + rng.randint(0, 4)
                child_total = 0
                for (kid_id, kc, kd) in kids:
                    dur = emit(kid_id, kc, kd, cursor)
                    cursor += dur + rng.randint(1, 6)
                    child_total = cursor - start_ms
                own = rng.randint(2, 12)
                total = max(edge_delay[rpc_id] + child_total + own, 1)
                records.append(CallRecord(
                    trace_id=tid,
                    timestamp_ms=start_ms,
                    rpc_id=rpc_id,
                    caller=services[svc_ids[caller]] if caller >= 0 else "USER",
                    rpc_type="rpc",
                    callee=services[svc_ids[callee]],
                    interface=f"if_{rpc_id}",
                    rt_ms=total,
                ))
                return total

            _, root_caller, root_callee = topology[0]
            emit("0", root_caller, root_callee, t_now)
            repaired = repair_trace(records)
            if repaired is not None:
                traces[tid] = repaired

    write_replica_table(out_root, services, seed)
    return group_traces(traces, out_root, top_n=n_graphs, min_traces=2)


def write_replica_table(out_root: str, services: List[str],
                        seed: int = 10) -> str:
    """Regenerate the ``service_to_replica_new.pickle`` artifact.

    The reference loads it unconditionally (executor.py:912) and scales
    each service's compress factor by its replica count (:922-929), but
    the release ships no ``data/misc/`` at all. Replica counts are drawn
    log-uniform in [16, 128] per service (Alibaba microservices run tens
    to hundreds of replicas), deterministically from ``seed`` so the
    corpus and table regenerate together. Written beside the corpus at
    ``<out_root>/../../misc/service_to_replica_new.pickle``; the CLI
    checks the repo-root ``data/misc`` location first (the reference's
    path, executor.py:912) and then this dataset-relative one
    (runtime/cli.py).
    """
    import os
    import pickle

    rng = random.Random(seed + 1)
    table = {
        svc: [f"{svc}.r{i}" for i in range(
            int(round(2 ** rng.uniform(4.0, 7.0))))]
        for svc in services
    }
    assert all(16 <= len(v) <= 128 for v in table.values())
    misc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(out_root))), "misc")
    os.makedirs(misc, exist_ok=True)
    path = os.path.join(misc, "service_to_replica_new.pickle")
    with open(path, "wb") as f:
        pickle.dump(table, f, protocol=pickle.HIGHEST_PROTOCOL)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", required=True)
    p.add_argument("--n-graphs", type=int, default=15)
    p.add_argument("--traces-per-graph", type=int, default=1000)
    p.add_argument("--seed", type=int, default=10)
    args = p.parse_args(argv)
    dirs = synthesize_corpus(args.out, args.n_graphs, args.traces_per_graph,
                             args.seed)
    print(f"wrote {len(dirs)} call-graph datasets under {args.out}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
