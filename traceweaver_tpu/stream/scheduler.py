"""Micro-batch scheduling of sealed windows onto the fleet solve path.

Sealed windows queue here and are solved in micro-batches: every window
in a batch contributes one :class:`~traceweaver_tpu.algorithms.fleet.FleetItem`
per solvable service, and the whole batch rides ONE
:func:`~traceweaver_tpu.algorithms.fleet.solve_fleet` call — windows with
similar geometry land in the same padded shape class (power-of-two
bucketing), so the XLA programs compiled for the first few windows are
reused for the rest of the stream and device dispatches stay O(shape
classes), not O(windows x services).

Backpressure is explicit and quantified:

- at most ``max_pending`` sealed windows may be queued for the next
  micro-batch (the bound on in-flight device buffers);
- when the producer outruns the solver, excess windows shed to a spill
  queue of at most ``spill_max`` (counted in ``shed_spilled``); spilled
  windows are solved later, oldest first — shed, not lost;
- when even the spill queue is full, the offered window is dropped and
  its spans counted (``shed_dropped_windows`` / ``shed_dropped_spans``)
  — the only lossy outcome, and it is the operator-visible signal that
  the deployment is under-provisioned.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, List, Optional

from traceweaver_tpu.stream.window import WindowBuffer


class MicroBatchScheduler:
    """Bounded queue + spill in front of a window-batch solve function.

    ``solve_fn(batch: List[WindowBuffer]) -> List[result]`` solves a
    micro-batch of sealed windows and returns one result per window, in
    order. The scheduler owns no solver state itself, so checkpointing
    only needs its two queues.
    """

    def __init__(self, solve_fn: Callable[[List[WindowBuffer]], List],
                 max_pending: int = 4, spill_max: int = 64) -> None:
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.solve_fn = solve_fn
        self.max_pending = int(max_pending)
        self.spill_max = int(spill_max)
        self.pending: Deque[WindowBuffer] = deque()
        self.spill: Deque[WindowBuffer] = deque()
        self.shed_spilled = 0
        self.shed_dropped_windows = 0
        self.shed_dropped_spans = 0
        self.solved_windows = 0

    # -- producer side ----------------------------------------------------
    def offer(self, buf: WindowBuffer) -> str:
        """Enqueue one sealed window. Returns "queued", "spilled", or
        "dropped"."""
        if len(self.pending) < self.max_pending:
            self.pending.append(buf)
            return "queued"
        if len(self.spill) < self.spill_max:
            self.spill.append(buf)
            self.shed_spilled += 1
            return "spilled"
        self.shed_dropped_windows += 1
        self.shed_dropped_spans += buf.n_spans
        return "dropped"

    @property
    def backlog(self) -> int:
        return len(self.pending) + len(self.spill)

    # -- consumer side ----------------------------------------------------
    def pump(self, max_batches: Optional[int] = None) -> List:
        """Solve queued windows in micro-batches of ``max_pending``,
        refilling from the spill queue between batches, until the backlog
        is empty (or ``max_batches`` batches have run — the throttle used
        to model a slow consumer). Returns the solved results in
        submission order."""
        results: List = []
        batches = 0
        while self.pending or self.spill:
            if max_batches is not None and batches >= max_batches:
                break
            while self.spill and len(self.pending) < self.max_pending:
                self.pending.append(self.spill.popleft())
            batch = list(self.pending)
            self.pending.clear()
            out = self.solve_fn(batch)
            if len(out) != len(batch):
                raise RuntimeError(
                    f"solve_fn returned {len(out)} results for a "
                    f"{len(batch)}-window batch")
            results.extend(out)
            self.solved_windows += len(batch)
            batches += 1
        return results
