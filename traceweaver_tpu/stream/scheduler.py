"""Micro-batch scheduling of sealed windows onto the fleet solve path.

Sealed windows queue here and are solved in micro-batches: every window
in a batch contributes one :class:`~traceweaver_tpu.algorithms.fleet.FleetItem`
per solvable service, and the whole batch rides ONE
:func:`~traceweaver_tpu.algorithms.fleet.solve_fleet` call — windows with
similar geometry land in the same padded shape class (power-of-two
bucketing), so the XLA programs compiled for the first few windows are
reused for the rest of the stream and device dispatches stay O(shape
classes), not O(windows x services).

Backpressure is explicit and quantified:

- at most ``max_pending`` sealed windows may be queued for the next
  micro-batch (the bound on in-flight device buffers);
- when the producer outruns the solver, excess windows shed to a spill
  queue of at most ``spill_max`` (counted in ``shed_spilled``); spilled
  windows are solved later, oldest first — shed, not lost;
- when even the spill queue is full, the offered window is dropped and
  its spans counted (``shed_dropped_windows`` / ``shed_dropped_spans``)
  — the only lossy outcome, and it is the operator-visible signal that
  the deployment is under-provisioned.

Failure is explicit and quantified too (the stream consumer side of the
solve supervisor, docs/ROBUSTNESS.md): each micro-batch solve runs under
an optional WATCHDOG timeout (``watchdog_s``) and a bounded retry
(``solve_retries``); a batch that exhausts both is handed to
``poison_fn`` — the service's dead-letter constructor — so a poisoned
batch becomes counted poison-window results, never a lost micro-batch or
an aborted stream. Without a ``poison_fn`` the final error propagates
(the pre-supervisor behavior).
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Callable, Deque, List, Optional

from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.stream.window import WindowBuffer

# obs mirror of the backpressure/watchdog outcomes (the scheduler's own
# integer attributes keep their names — summaries/checkpoints read them)
_OBS_BACKPRESSURE = _get_registry().counter(
    "tw_stream_backpressure_total",
    "sealed-window admission outcomes (offer(): queued/spilled/dropped)",
    labels=("outcome",))
_OBS_WATCHDOG = _get_registry().counter(
    "tw_stream_watchdog_total",
    "micro-batch watchdog outcomes (timeouts/retries/poisoned windows)",
    labels=("outcome",))


class SolveTimeout(RuntimeError):
    """A micro-batch solve exceeded the watchdog timeout. Classified as
    transient (a hung device dispatch is exactly what the retry exists
    for); the hung attempt's thread is abandoned, not interrupted —
    device work cannot be cancelled — and its eventual result is
    discarded."""


class MicroBatchScheduler:
    """Bounded queue + spill in front of a window-batch solve function.

    ``solve_fn(batch: List[WindowBuffer]) -> List[result]`` solves a
    micro-batch of sealed windows and returns one result per window, in
    order. The scheduler owns no solver state itself, so checkpointing
    only needs its two queues (the watchdog/retry counters ride the
    service's stats dict).
    """

    def __init__(self, solve_fn: Callable[[List[WindowBuffer]], List],
                 max_pending: int = 4, spill_max: int = 64,
                 watchdog_s: Optional[float] = None,
                 solve_retries: int = 1,
                 poison_fn: Optional[Callable] = None) -> None:
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.solve_fn = solve_fn
        self.max_pending = int(max_pending)
        self.spill_max = int(spill_max)
        self.watchdog_s = watchdog_s
        self.solve_retries = max(0, int(solve_retries))
        self.poison_fn = poison_fn
        self.pending: Deque[WindowBuffer] = deque()
        self.spill: Deque[WindowBuffer] = deque()
        self.shed_spilled = 0
        self.shed_dropped_windows = 0
        self.shed_dropped_spans = 0
        self.solved_windows = 0
        self.solve_timeouts = 0
        self.solve_retried = 0
        self.poisoned_windows = 0
        self._watchdog_pool: Optional[ThreadPoolExecutor] = None

    # -- producer side ----------------------------------------------------
    def offer(self, buf: WindowBuffer) -> str:
        """Enqueue one sealed window. Returns "queued", "spilled", or
        "dropped"."""
        if len(self.pending) < self.max_pending:
            self.pending.append(buf)
            _OBS_BACKPRESSURE.inc(outcome="queued")
            return "queued"
        if len(self.spill) < self.spill_max:
            self.spill.append(buf)
            self.shed_spilled += 1
            _OBS_BACKPRESSURE.inc(outcome="spilled")
            return "spilled"
        self.shed_dropped_windows += 1
        self.shed_dropped_spans += buf.n_spans
        _OBS_BACKPRESSURE.inc(outcome="dropped")
        return "dropped"

    @property
    def backlog(self) -> int:
        return len(self.pending) + len(self.spill)

    def pop_batch(self) -> List[WindowBuffer]:
        """Take the next micro-batch off the queues: refill pending from
        spill (oldest first) up to the pending bound, then hand the whole
        pending queue over. One definition shared by :meth:`pump` and the
        serve layer's tenancy manager (which merges several tenants'
        popped batches into one shared fleet dispatch and counts
        ``solved_windows`` itself once the shared solve lands)."""
        while self.spill and len(self.pending) < self.max_pending:
            self.pending.append(self.spill.popleft())
        batch = list(self.pending)
        self.pending.clear()
        return batch

    def ready(self) -> List[WindowBuffer]:
        """Sealed windows awaiting solve, oldest first (pending then
        spill) — the continuous-batching scheduler's admission view: it
        PICKS windows (SLO-at-risk first, then batch-fill by size
        class) instead of draining whole queues."""
        return list(self.pending) + list(self.spill)

    def take(self, bufs: List[WindowBuffer]) -> List[WindowBuffer]:
        """Remove exactly the given buffers from the queues (identity
        match) and return them in the given admission order — the
        consume half of :meth:`ready`. Buffers no longer queued (e.g.
        drained by a concurrent flush) are skipped, so admission races
        resolve to at-most-once solving."""
        chosen = {id(b): k for k, b in enumerate(bufs)}
        taken: List[WindowBuffer] = []
        for q in (self.pending, self.spill):
            kept = [b for b in q if id(b) not in chosen]
            taken.extend(b for b in q if id(b) in chosen)
            q.clear()
            q.extend(kept)
        taken.sort(key=lambda b: chosen[id(b)])
        return taken

    # -- consumer side ----------------------------------------------------
    def _solve_once(self, batch: List[WindowBuffer]) -> List:
        """One solve attempt, under the watchdog when configured. The
        watchdog runs the solve on a single persistent worker thread and
        bounds the WAIT — a timed-out solve keeps running detached (its
        thread is not interruptible) and its late result is dropped."""
        if not self.watchdog_s:
            return self.solve_fn(batch)
        if self._watchdog_pool is None:
            self._watchdog_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tw-stream-watchdog")
        fut = self._watchdog_pool.submit(self.solve_fn, batch)
        try:
            return fut.result(timeout=self.watchdog_s)
        except FutureTimeout:
            self.solve_timeouts += 1
            _OBS_WATCHDOG.inc(outcome="timeout")
            fut.cancel()  # best effort; a running solve is abandoned
            # a hung worker would serialize behind the abandoned solve:
            # detach the pool so the retry gets a fresh thread
            self._watchdog_pool = None
            raise SolveTimeout(
                f"micro-batch solve of {len(batch)} window(s) exceeded "
                f"the {self.watchdog_s:.1f}s watchdog") from None

    def _solve_guarded(self, batch: List[WindowBuffer]) -> List:
        """Watchdog + bounded retry + poison hand-off for one batch."""
        from traceweaver_tpu.runtime import faults

        err: Optional[BaseException] = None
        for attempt in range(1 + self.solve_retries):
            if attempt:
                self.solve_retried += 1
                _OBS_WATCHDOG.inc(outcome="retried")
            try:
                return self._solve_once(batch)
            except SolveTimeout as e:
                err = e
            except Exception as e:  # noqa: BLE001 — classified below
                if not faults.is_transient_fault(e):
                    raise
                err = e
        self.poisoned_windows += len(batch)
        _OBS_WATCHDOG.inc(len(batch), outcome="poisoned")
        if self.poison_fn is not None:
            return self.poison_fn(batch, err)
        raise err

    def pump(self, max_batches: Optional[int] = None) -> List:
        """Solve queued windows in micro-batches of ``max_pending``,
        refilling from the spill queue between batches, until the backlog
        is empty (or ``max_batches`` batches have run — the throttle used
        to model a slow consumer). Returns the solved results in
        submission order."""
        results: List = []
        batches = 0
        while self.pending or self.spill:
            if max_batches is not None and batches >= max_batches:
                break
            batch = self.pop_batch()
            out = self._solve_guarded(batch)
            if len(out) != len(batch):
                raise RuntimeError(
                    f"solve_fn returned {len(out)} results for a "
                    f"{len(batch)}-window batch")
            results.extend(out)
            self.solved_windows += len(batch)
            batches += 1
        return results

    def close(self) -> None:
        if self._watchdog_pool is not None:
            self._watchdog_pool.shutdown(wait=False)
            self._watchdog_pool = None
