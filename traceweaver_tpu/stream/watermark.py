"""Event-time watermark tracking.

The watermark is the stream's completeness frontier: "no span with event
time below this should still be in flight". With collectors that deliver
at most ``bound_us`` late (the replay source's ``ooo_us`` models this),
``watermark = max(event_time seen) - bound_us`` is a correct frontier;
spans that violate it anyway are *late* and are handled by the windowing
engine (rerouted into a still-open window or counted as dropped).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class WatermarkTracker:
    """Monotone watermark over observed event times.

    ``bound_us`` is the allowed out-of-orderness. The tracker also keeps
    the lateness statistics the stats surface reports: how many events
    arrived behind the watermark (late), and the maximum skew between an
    event and the frontier at its arrival.
    """

    bound_us: float = 0.0
    max_event_us: float = field(default=float("-inf"), init=False)
    n_events: int = field(default=0, init=False)
    n_late: int = field(default=0, init=False)
    max_skew_us: float = field(default=0.0, init=False)

    @property
    def value(self) -> float:
        """Current watermark (-inf until the first event)."""
        if self.max_event_us == float("-inf"):
            return float("-inf")
        return self.max_event_us - self.bound_us

    def observe(self, event_us: float) -> bool:
        """Fold one event time in. Returns True when the event is late
        (behind the watermark as of *before* this observation)."""
        late = event_us < self.value
        if late:
            self.n_late += 1
        if self.max_event_us != float("-inf"):
            self.max_skew_us = max(self.max_skew_us,
                                   self.max_event_us - event_us)
        self.max_event_us = max(self.max_event_us, event_us)
        self.n_events += 1
        return late

    def delay_of(self, event_us: float) -> float:
        """How far behind the frontier an event time sits (0 if ahead)."""
        if self.max_event_us == float("-inf"):
            return 0.0
        return max(0.0, self.max_event_us - event_us)
