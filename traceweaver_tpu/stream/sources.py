"""Span event sources for the streaming reconstructor.

A source is anything that yields :class:`SpanEvent` in *arrival* order.
The production ingress would be a collector subscription; for testing and
benchmarking, :class:`ReplaySource` turns a recorded corpus (the exp1/exp5
datasets, or any directory :func:`~traceweaver_tpu.ingest.load_corpus`
understands) into a timestamped stream, optionally with deterministic
out-of-order arrival jitter so the watermark/late-span machinery is
exercised the way a real collector fan-in would.

Replay is deterministic for a given ``(corpus, ooo_us, seed)``: the same
spec always yields the same events in the same order. The checkpoint
machinery relies on this — resuming skips the first ``consumed`` events
instead of persisting raw spans that were already folded into windows.
"""

from __future__ import annotations

import urllib.parse
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional

import numpy as np

from traceweaver_tpu.spans import Span, TraceStore


@dataclass
class SpanEvent:
    """One span arriving at the reconstructor.

    ``event_us`` is event time (the span's start timestamp — the time the
    instrumented call happened); ``arrival_us`` is when the collector
    delivered it. The gap between the two is what watermarks bound.
    ``processes`` is the owning trace's ``process_id -> service`` table
    (Jaeger ships it per trace; collectors forward it with each span).

    This is also the serve layer's ingress unit: the HTTP front door
    (``traceweaver_tpu/serve``) parses each posted Jaeger-JSON payload
    and feeds every span as one SpanEvent into the owning tenant's
    pipeline, so network ingestion and replay share one event contract.

    **Capture-derived spans** (``collector:`` sources,
    ``traceweaver_tpu/collector/source.py``) carry one extra semantic:
    ``capture_us`` is the span's RAW capture timestamp on its source's
    own clock, while ``event_us`` is solver event time — the same stamp
    *after* per-source clock-skew correction. The two differ by the
    source's fitted offset (``tw_clock_skew_us{source}``); consumers
    that need the original capture clock (debugging a capture, joining
    back to an strace log) must read ``capture_us``, and everything
    event-time ordered (watermarks, windows, the solver) must keep
    using ``event_us``. None on instrumented/replay sources, where the
    two clocks are the same thing.
    """

    span: Span
    event_us: float
    arrival_us: float
    trace_id: str
    processes: Dict[str, str]
    capture_us: Optional[float] = None


class ReplaySource:
    """Replay a loaded :class:`TraceStore` as an arrival-ordered stream.

    ``ooo_us > 0`` delays each span by a deterministic uniform jitter in
    ``[0, ooo_us)`` (seeded RNG), then re-sorts by arrival — spans reach
    the service out of event-time order, bounded by ``ooo_us``, which is
    exactly the contract a watermark with ``bound_us >= ooo_us`` covers.
    """

    def __init__(self, store: TraceStore, ooo_us: float = 0.0,
                 seed: int = 0) -> None:
        self.store = store
        self.ooo_us = float(ooo_us)
        self.seed = int(seed)
        self._events: List[SpanEvent] = self._build()

    def _build(self) -> List[SpanEvent]:
        spans = sorted(
            self.store.all_spans.values(),
            key=lambda s: (float(s.start_mus), s.trace_id, s.sid),
        )
        rng = np.random.default_rng(self.seed)
        jitter = (rng.uniform(0.0, self.ooo_us, size=len(spans))
                  if self.ooo_us > 0 else np.zeros(len(spans)))
        events = [
            SpanEvent(
                span=s,
                event_us=float(s.start_mus),
                arrival_us=float(s.start_mus) + float(j),
                trace_id=s.trace_id,
                processes=self.store.all_processes.get(s.trace_id, {}),
            )
            for s, j in zip(spans, jitter)
        ]
        events.sort(key=lambda e: (e.arrival_us, e.trace_id, e.span.sid))
        return events

    def __len__(self) -> int:
        return len(self._events)

    def events(self, skip: int = 0) -> Iterator[SpanEvent]:
        """Yield events in arrival order, skipping the first ``skip``
        (checkpoint resume fast-forwards through already-consumed
        events)."""
        return iter(self._events[skip:])

    @classmethod
    def from_directory(cls, path: str, fix: int, max_traces: int = 1000,
                       ooo_us: float = 0.0, seed: int = 0,
                       strict: bool = False) -> "ReplaySource":
        import random

        from traceweaver_tpu.ingest import load_corpus

        # corpus loading must be reproducible ACROSS PROCESSES: Alibaba
        # self-loop remapping mints synthetic "<random>-loop" service
        # names from the global RNG, and a resumed run re-loads the
        # corpus in a fresh process whose names must match the
        # checkpointed state byte-for-byte. Same convention as the batch
        # executor (run_experiment seeds 10 before its load).
        random.seed(10)
        store = load_corpus(path, fix=fix, max_traces=max_traces,
                            cache=False, strict=strict)
        return cls(store, ooo_us=ooo_us, seed=seed)


class IterableSource:
    """Adapter for tests / external ingress: any iterable of SpanEvents,
    already in arrival order. ``events(skip=n)`` consumes and discards
    the first n (resume support for deterministic iterables)."""

    def __init__(self, events: Iterable[SpanEvent]) -> None:
        self._events = list(events)
        self.store: Optional[TraceStore] = None

    def __len__(self) -> int:
        return len(self._events)

    def events(self, skip: int = 0) -> Iterator[SpanEvent]:
        return iter(self._events[skip:])


def parse_source_spec(spec: str, fix: int = 0, max_traces: int = 1000,
                      ooo_us: float = 0.0, seed: int = 0,
                      strict: bool = False):
    """Parse a ``--source`` spec into a source.

    ``replay:<dir>`` replays a recorded Jaeger-style corpus, with
    optional query parameters overriding the defaults, e.g.::

        replay:data/hotel_reservation/hotel_load25?fix=2&max_traces=200
        replay:/abs/path?fix=5&ooo_ms=50&seed=3

    Recognized query keys: ``fix``, ``max_traces``, ``ooo_ms`` /
    ``ooo_us``, ``seed``.

    ``collector:<path|fifo>`` is the live-capture ingress
    (docs/COLLECTOR.md): ``<path>`` is one recorded ``strace -f -ttt``
    log (one capture source), a directory of per-source logs
    (``*.log``/``*.txt``/``*.strace``, one clock each — cross-source
    skew is fitted and corrected), or a FIFO fed by a live ``strace``
    (single-source incremental mode). Query key ``service`` names the
    single-file source's service (default ``TW_COLLECTOR_SERVICE``,
    then the file stem). The replay knobs (``fix``/``ooo_ms``/...) do
    not apply: arrival order and out-of-orderness come from the capture
    itself.
    """
    if spec.startswith("collector:"):
        from traceweaver_tpu.collector.source import CollectorSource

        rest = spec[len("collector:"):]
        path, _, query = rest.partition("?")
        params = dict(urllib.parse.parse_qsl(query))
        return CollectorSource.from_spec(path,
                                         service=params.get("service"))
    if not spec.startswith("replay:"):
        raise ValueError(
            f"unknown source spec {spec!r}: expected "
            "'replay:<corpus-dir>' (recorded Jaeger corpus) or "
            "'collector:<strace-log|dir|fifo>' (live-capture ingress, "
            "docs/COLLECTOR.md); arbitrary in-process streams plug in "
            "via stream.sources.IterableSource)")
    rest = spec[len("replay:"):]
    path, _, query = rest.partition("?")
    params = dict(urllib.parse.parse_qsl(query))
    if "fix" in params:
        fix = int(params["fix"])
    if "max_traces" in params:
        max_traces = int(params["max_traces"])
    if "ooo_us" in params:
        ooo_us = float(params["ooo_us"])
    elif "ooo_ms" in params:
        ooo_us = float(params["ooo_ms"]) * 1000.0
    if "seed" in params:
        seed = int(params["seed"])
    return ReplaySource.from_directory(path, fix=fix, max_traces=max_traces,
                                      ooo_us=ooo_us, seed=seed,
                                      strict=strict)
