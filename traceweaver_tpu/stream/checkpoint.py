"""Checkpointed resume for the streaming reconstructor.

A checkpoint is one atomically-written pickle of the service's mutable
state: the replay offset (``consumed`` events), the open window buffers,
the live span store, the watermark, the scheduler's queued/spilled
windows, the carried per-service statistics, the grader accumulators,
the stats counters, and the sink's byte offset.

Resume contract (tested by ``tests/test_stream.py``):

- the source is NOT pickled — replay sources are deterministic, so the
  resumed service re-opens the source and skips the first ``consumed``
  events;
- the sink is truncated back to the checkpointed byte offset before the
  resumed run appends — windows that were emitted after the last
  checkpoint are re-solved from identical state and re-emitted
  byte-identically, so the final emitted trace set equals the
  uninterrupted run's exactly: no loss, no double-emit.

Integrity contract (version 2, tested by ``tests/test_faults.py``):

- every checkpoint carries a CRC32 trailer (``MAGIC + crc32 + length``
  over the pickle payload), so truncation and bit rot are DETECTED at
  load instead of surfacing as an unpickling crash or, worse, silently
  corrupt state;
- ``save_checkpoint`` rotates the previous checkpoint to ``path.prev``
  before replacing, so there is always a last-known-good file;
- ``load_checkpoint`` falls back to ``path.prev`` when the primary is
  corrupt or truncated — counted and warned (the returned state carries
  ``_recovered_from_prev``), never silent, and only *fatal* when both
  generations are unreadable;
- version-1 checkpoints (no trailer) are still readable, so a deployed
  service upgrades in place.

Everything in the state dict is plain pickle material (Span dataclasses,
numpy arrays inside EdgeDists, networkx-free); sharing is preserved
because the whole dict rides one pickle (the live store's span objects
and the window buffers reference the same copies).

The serve layer's per-tenant checkpoints (``traceweaver_tpu/serve``)
ride the same ``save_checkpoint``/``load_checkpoint`` machinery — one
file per tenant, wrapping the service's ``state_dict()`` with tenancy
bookkeeping (trace ring, counters, the Alibaba self-loop map). Those
checkpoints have no replayable source, so the still-open window buffers
in the pickled windower ARE the durability story: a drained-and-resumed
tenant loses zero windows (tests/test_stream.py,
``test_multi_tenant_checkpoint_kill_resume_no_leakage``).
"""

from __future__ import annotations

import os
import pickle
import struct
import sys
import zlib
from typing import Dict

CHECKPOINT_VERSION = 2

#: trailer = MAGIC + u32 crc32(payload) + u64 len(payload), little-endian
_MAGIC = b"TWCK"
_TRAILER = struct.Struct("<4sIQ")


class CheckpointCorrupt(ValueError):
    """The checkpoint file failed its integrity check (bad CRC, short
    payload, or unreadable pickle) and no fallback generation worked."""


def _maybe_fail(site: str) -> None:
    # lazy import: checkpoint.py stays importable without pulling the
    # runtime package (and jax) in at module-import time
    from traceweaver_tpu.runtime import faults

    faults.maybe_fail(site)


def save_checkpoint(path: str, state: Dict) -> None:
    """Atomic write with integrity trailer and keep-last-good rotation:
    pickle to a sibling temp file, append the CRC trailer, fsync, rotate
    the current checkpoint to ``path.prev``, rename into place."""
    _maybe_fail("checkpoint")
    payload_dict = dict(state)
    payload_dict["version"] = CHECKPOINT_VERSION
    payload = pickle.dumps(payload_dict, protocol=pickle.HIGHEST_PROTOCOL)
    trailer = _TRAILER.pack(_MAGIC, zlib.crc32(payload), len(payload))
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(payload)
        f.write(trailer)
        f.flush()
        os.fsync(f.fileno())
    if os.path.exists(path):
        # keep-last-good: the generation being replaced becomes .prev so
        # a corrupt/truncated primary never strands the service
        os.replace(path, path + ".prev")
    os.replace(tmp, path)


def verify_checkpoint_bytes(raw: bytes, label: str = "<bytes>") -> bytes:
    """Trailer integrity check over in-memory checkpoint bytes; returns
    the pickle payload (trailer stripped). The checkpoint-transfer
    surface for live tenant migration (``traceweaver_tpu/fleet_serve``):
    both ends of a cross-process checkpoint copy run this, so a torn
    read is refused at the SOURCE and a torn transfer at the
    DESTINATION — never installed as a replica's resume state.
    Version-1 bytes (no trailer) pass through unverified, same as
    :func:`load_checkpoint`."""
    if len(raw) >= _TRAILER.size and raw[-_TRAILER.size:][:4] == _MAGIC:
        magic, crc, length = _TRAILER.unpack(raw[-_TRAILER.size:])
        payload = raw[:-_TRAILER.size]
        if length != len(payload):
            raise CheckpointCorrupt(
                f"checkpoint {label}: trailer says {length} payload bytes, "
                f"got {len(payload)} (truncated or overwritten)")
        if zlib.crc32(payload) != crc:
            raise CheckpointCorrupt(
                f"checkpoint {label}: CRC mismatch (bit rot or torn write)")
        return payload
    # no trailer: either a version-1 checkpoint (legal, pre-integrity
    # format) or a truncation that ate the trailer — a pickle load
    # distinguishes (a truncated pickle cannot load)
    return raw


def read_checkpoint_bytes(path: str) -> bytes:
    """Read a checkpoint file verbatim for transfer, verifying the CRC
    trailer first (the migrate_out half of the transfer surface)."""
    with open(path, "rb") as f:
        raw = f.read()
    verify_checkpoint_bytes(raw, label=path)
    return raw


def write_checkpoint_bytes(path: str, raw: bytes) -> None:
    """Install transferred checkpoint bytes (the migrate_in half):
    verify the trailer, then the same fsync + keep-last-good rotation +
    atomic rename discipline as :func:`save_checkpoint`."""
    verify_checkpoint_bytes(raw, label=path)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(raw)
        f.flush()
        os.fsync(f.fileno())
    if os.path.exists(path):
        os.replace(path, path + ".prev")
    os.replace(tmp, path)


def _load_one(path: str) -> Dict:
    """Read + verify one checkpoint file (v2 trailer or bare v1 pickle).
    Raises :class:`CheckpointCorrupt` on any integrity failure."""
    with open(path, "rb") as f:
        raw = f.read()
    payload = verify_checkpoint_bytes(raw, label=path)
    try:
        state = pickle.loads(payload)
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path}: unreadable pickle "
            f"({type(e).__name__}: {e})") from e
    version = state.get("version")
    if version not in (1, CHECKPOINT_VERSION):
        raise ValueError(
            f"checkpoint {path} has version {version}, "
            f"this build reads versions 1..{CHECKPOINT_VERSION}")
    return state


def load_checkpoint(path: str) -> Dict:
    """Load a checkpoint, falling back to the rotated ``path.prev`` when
    the primary fails its integrity check. A recovered load is warned on
    stderr and marked in the returned state (``_recovered_from_prev``)
    so the service can count it; only primary+fallback both failing is
    fatal."""
    _maybe_fail("checkpoint")
    try:
        return _load_one(path)
    except CheckpointCorrupt as primary_err:
        prev = path + ".prev"
        if not os.path.exists(prev):
            raise
        try:
            state = _load_one(prev)
        except (CheckpointCorrupt, ValueError) as prev_err:
            raise CheckpointCorrupt(
                f"checkpoint {path} is corrupt ({primary_err}) and the "
                f"last-good fallback failed too ({prev_err})"
            ) from primary_err
        print(f"[checkpoint] WARNING: {primary_err}; resumed from "
              f"last-good {prev}", file=sys.stderr)
        state["_recovered_from_prev"] = True
        return state
