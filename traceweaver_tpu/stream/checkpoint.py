"""Checkpointed resume for the streaming reconstructor.

A checkpoint is one atomically-written pickle of the service's mutable
state: the replay offset (``consumed`` events), the open window buffers,
the live span store, the watermark, the scheduler's queued/spilled
windows, the carried per-service statistics, the grader accumulators,
the stats counters, and the sink's byte offset.

Resume contract (tested by ``tests/test_stream.py``):

- the source is NOT pickled — replay sources are deterministic, so the
  resumed service re-opens the source and skips the first ``consumed``
  events;
- the sink is truncated back to the checkpointed byte offset before the
  resumed run appends — windows that were emitted after the last
  checkpoint are re-solved from identical state and re-emitted
  byte-identically, so the final emitted trace set equals the
  uninterrupted run's exactly: no loss, no double-emit.

Everything in the state dict is plain pickle material (Span dataclasses,
numpy arrays inside EdgeDists, networkx-free); sharing is preserved
because the whole dict rides one pickle (the live store's span objects
and the window buffers reference the same copies).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict

CHECKPOINT_VERSION = 1


def save_checkpoint(path: str, state: Dict) -> None:
    """Atomic write: pickle to a sibling temp file, fsync, rename."""
    payload = dict(state)
    payload["version"] = CHECKPOINT_VERSION
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checkpoint(path: str) -> Dict:
    with open(path, "rb") as f:
        state = pickle.load(f)
    version = state.get("version")
    if version != CHECKPOINT_VERSION:
        raise ValueError(
            f"checkpoint {path} has version {version}, "
            f"this build reads version {CHECKPOINT_VERSION}")
    return state
