"""Streaming reconstruction: the online execution mode.

Everything else in the repo is batch — ``runtime/executor.py`` loads a
fixed corpus, solves each service once, and writes pickles (the same
offline shape as the reference artifact, which hard-caps a run at 1000
traces). A deployed reconstructor instead receives spans as an unbounded,
out-of-order stream from collectors. This package is that missing
subsystem:

- :mod:`sources` — span event streams (replay of a recorded corpus with
  deterministic out-of-order arrival, or any iterator of
  :class:`~traceweaver_tpu.stream.sources.SpanEvent`);
- :mod:`watermark` — event-time watermark tracking (bounded
  out-of-orderness, lateness accounting);
- :mod:`window` — overlapping event-time windows with single-owner
  emission semantics and late-span routing;
- :mod:`scheduler` — micro-batch scheduling of sealed windows onto the
  existing fleet solve path (shared shape classes across windows so XLA
  recompiles amortize) with bounded in-flight work and a spill queue for
  backpressure;
- :mod:`state` — the incremental trace store, per-service carried
  GMM/score statistics (warm-start EM between windows), and the
  streamed-vs-batch accuracy grader;
- :mod:`checkpoint` — atomic checkpoints of source offset + carried
  state so a killed service resumes without reprocessing or
  double-emitting;
- :mod:`service` — the driver that wires all of the above and emits
  stitched traces incrementally with a live stats surface.

CLI: ``python -m traceweaver_tpu.runtime.cli stream --source
replay:<corpus-dir> ...`` (see docs/STREAMING.md).
"""

from traceweaver_tpu.stream.sources import (  # noqa: F401
    ReplaySource,
    SpanEvent,
    parse_source_spec,
)
from traceweaver_tpu.stream.watermark import WatermarkTracker  # noqa: F401
from traceweaver_tpu.stream.window import (  # noqa: F401
    WindowBuffer,
    WindowingEngine,
)
from traceweaver_tpu.stream.scheduler import MicroBatchScheduler  # noqa: F401
from traceweaver_tpu.stream.state import (  # noqa: F401
    CarriedState,
    LiveTraceStore,
    StreamGrader,
)
from traceweaver_tpu.stream.checkpoint import (  # noqa: F401
    load_checkpoint,
    save_checkpoint,
)
from traceweaver_tpu.stream.service import (  # noqa: F401
    StreamConfig,
    StreamingReconstructor,
    TraceSink,
)
