"""The streaming reconstruction service.

Wires source -> watermark -> windowing -> micro-batch scheduler (fleet
solve) -> incremental stitching/emission, with carried per-service state,
periodic checkpoints, and a live stats surface. See the package docstring
and docs/STREAMING.md for the model; tests/test_stream.py for the
contracts.

The inner loop is the existing warm fleet path: each sealed window
contributes one FleetItem per solvable service and a micro-batch of
windows rides one :func:`~traceweaver_tpu.algorithms.fleet.solve_fleet`
call, so padded shape classes (and the XLA programs compiled for them)
are shared across the whole stream. Micro-batches therefore also ride
the fleet's pipelined dispatcher: shape-class groups within a
micro-batch pack/dispatch/decode concurrently (``TW_PIPELINE=0`` for
the serial flow), and the summary's ``pipeline`` block reports the
observed depth plus the D2H byte ledger (flag-only compaction fetches
vs total transfers).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from traceweaver_tpu import adapt as _adapt
from traceweaver_tpu.algorithms import plancache as _plancache
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs import quality as _quality
from traceweaver_tpu.obs import selftrace as _selftrace
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.ops.precision import precision_from_env
from traceweaver_tpu.runtime import aot as _aot
from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.spans import NA, SKIP, Span, SpanArray
from traceweaver_tpu.stream.checkpoint import load_checkpoint, save_checkpoint
from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
from traceweaver_tpu.stream.state import (
    CarriedState,
    LiveTraceStore,
    StreamGrader,
)
from traceweaver_tpu.stream.watermark import WatermarkTracker
from traceweaver_tpu.stream.window import WindowBuffer, WindowingEngine


# obs registry mirrors (docs/OBSERVABILITY.md): the stream ledger's
# scrape surface. The stats dict keeps its field names (summaries,
# checkpoints, tests); every _bump ALSO lands here with a key label.
_OBS = _get_registry()
_OBS_STREAM = _OBS.counter(
    "tw_stream_ledger_total",
    "stream service ledger mirror (one series per stats counter key)",
    labels=("key",))
_OBS_SOLVE_S = _OBS.histogram(
    "tw_solve_seconds",
    "micro-batch solve wall time (stream + serve pump dispatches)")
_OBS_SEAL_EMIT_S = _OBS.histogram(
    "tw_seal_emit_seconds",
    "per-window seal→emit latency (the quantity the continuous-batching "
    "SLO TW_SERVE_SLO_P99_MS bounds at p99)",
    labels=("tenant",))
_OBS_SLO_BREACH = _OBS.counter(
    "tw_slo_breach_total",
    "seal→emit p99 excursions past TW_SERVE_SLO_P99_MS, one per "
    "excursion (re-armed when the p99 falls back under the SLO) — the "
    "pressure signal the admission scheduler failed to absorb",
    labels=("tenant",))


@dataclass
class StreamConfig:
    """Streaming knobs (all event-time values in microseconds)."""

    window_us: float = 60e6        # event-time window size
    overlap_us: float = 5e6        # shared margin between windows
    ooo_bound_us: float = 2e6      # watermark out-of-order allowance
    grace_us: float = 0.0          # allowed lateness past the watermark
    max_pending: int = 4           # in-flight sealed-window bound
    spill_max: int = 64            # spill queue bound (backpressure)
    solve_min_batch: int = 1       # pump once this many windows are sealed
    warm_start: bool = True        # carry per-service dists between windows
    grade: bool = True             # ground-truth grading (replay only)
    prune: bool = True             # retention-prune the live store
    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 8      # emitted windows between checkpoints
    verbose: bool = True
    # seal→emit latency SLO (ms, p99). None = pure batch-fill pacing
    # (the historical behavior). When set, the run loop admits a
    # backlog below solve_min_batch anyway once a sealed window's age
    # crosses half the budget — the single-tenant form of the serve
    # layer's continuous-batching admission (serve/continuous.py).
    slo_p99_ms: Optional[float] = None
    # robustness (docs/ROBUSTNESS.md): dead-letter sidecar for poison
    # windows (default: <sink>.deadletter.jsonl when a sink is set),
    # micro-batch watchdog timeout + bounded retry
    deadletter_path: Optional[str] = None
    solve_watchdog_s: Optional[float] = None
    solve_retries: int = 1


class TraceSink:
    """Append-only JSONL sink with a byte offset the checkpoints record.

    ``truncate(offset)`` rewinds to a checkpointed offset on resume so
    re-solved windows re-emit over their previous bytes — the no-loss,
    no-double-emit half of the resume contract.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a+b")
        self._f.seek(0, os.SEEK_END)
        self.offset = self._f.tell()

    def write_line(self, line: str) -> None:
        data = (line + "\n").encode("utf-8")
        self._f.write(data)
        self._f.flush()
        self.offset += len(data)

    def write_lines(self, lines: List[str]) -> None:
        """One buffered write for a whole micro-batch of records —
        byte-identical to the equivalent :meth:`write_line` sequence
        (same lines, same order, one flush instead of one per record)."""
        if not lines:
            return
        data = "".join(line + "\n" for line in lines).encode("utf-8")
        self._f.write(data)
        self._f.flush()
        self.offset += len(data)

    def truncate(self, offset: int) -> None:
        self._f.truncate(offset)
        self._f.seek(offset)
        self.offset = offset

    def close(self) -> None:
        self._f.close()


@dataclass
class _WindowProblem:
    """One (window, service) solve request plus its decode context.

    ``in_cols``/``out_cols`` are the partitions' :class:`SpanArray`
    columns (built once here, at window-assembly time, from the same
    sort the lists carry) — the fleet packer consumes THESE, so a pump's
    pack path never re-walks span objects (``TW_COLUMNAR``)."""

    service: str
    in_ep: str
    in_spans: List[Span]
    out_parts: Dict[str, List[Span]]
    truth: Dict[str, Dict]
    dag: object
    in_cols: object = None
    out_cols: object = None


@dataclass
class WindowResult:
    """One solved window, ready for emission — or a POISON window that
    exhausted the solve supervisor's ladder / the micro-batch watchdog
    and must be dead-lettered instead of emitted."""

    buf: WindowBuffer
    assignments: Dict[str, Dict[str, Dict]]  # svc -> ep -> {in: out}
    problems: List[_WindowProblem]
    traces: Dict[str, List]
    accuracy: Optional[float]
    n_rows: int = 0
    solve_share_s: float = 0.0
    poisoned: bool = False
    poison_reason: str = ""
    quarantined_services: Tuple[str, ...] = ()
    # per-span reconstruction-quality records (obs/quality.py):
    # svc -> {in span id: {conf, not_best, cands, support, ...}}
    confidence: Optional[Dict[str, Dict]] = None


def _sid(span_id) -> List[str]:
    return [span_id[0], span_id[1]]


class StreamingReconstructor:
    """Consume an unbounded span stream, emit stitched traces per window."""

    def __init__(self, source, cfg: Optional[StreamConfig] = None,
                 sink: Optional[TraceSink] = None) -> None:
        self.source = source
        self.cfg = cfg or StreamConfig()
        self.sink = sink
        c = self.cfg
        self.watermark = WatermarkTracker(bound_us=c.ooo_bound_us)
        self.windower = WindowingEngine(
            c.window_us, overlap_us=c.overlap_us, grace_us=c.grace_us)
        self.scheduler = MicroBatchScheduler(
            self._solve_batch, max_pending=c.max_pending,
            spill_max=c.spill_max, watchdog_s=c.solve_watchdog_s,
            solve_retries=c.solve_retries, poison_fn=self._poison_batch)
        # dead-letter sidecar for poison windows: an append-only JSONL
        # file with the same offset/truncate resume semantics as the
        # sink, so a kill/resume can never double-record (or lose) a
        # dead-lettered window
        dlq_path = c.deadletter_path or (
            sink.path + ".deadletter.jsonl" if sink is not None else None)
        self.deadletter = TraceSink(dlq_path) if dlq_path else None
        self.live = LiveTraceStore()
        self.carried = CarriedState()
        self.grader = StreamGrader() if c.grade else None
        self.consumed = 0
        self.emitted_windows = 0
        self.stats: Dict[str, float] = {}
        self.fleet_stats: Dict[str, float] = {}
        self._since_checkpoint = 0
        # self-trace identity (obs/selftrace.py): window keys are
        # "<prefix><window k>"; the serve layer sets "<tenant>:" so one
        # tracer can hold many tenants' journeys apart
        self.trace_prefix = ""
        # reconstruction-quality telemetry (obs/quality.py,
        # docs/OBSERVABILITY.md "Quality telemetry"): per-service
        # confidence-distribution drift watcher, ground-truth-free; the
        # whole path is inert under TW_CONFIDENCE=0
        self.drift = _quality.ConfidenceDrift() \
            if _quality.conf_enabled() else None
        # drift→adapt controller (traceweaver_tpu/adapt, TW_ADAPT,
        # docs/ROBUSTNESS.md "The adaptation ladder"): consumes the
        # drift watcher's excursions and actuates the refit/fallback
        # ladder. None (TW_ADAPT=0, the default) is fully inert; it
        # also requires the quality sensors (no signal, no control).
        self.adapt = (_adapt.AdaptationController()
                      if self.drift is not None
                      and _adapt.adapt_enabled() else None)
        # amortized plan cache (algorithms/plancache.py, TW_PLAN_CACHE):
        # the per-micro-batch carried-dist refit is the stream's residual
        # host plan stage — a cache hit skips it entirely; admissions
        # happen on every refit that runs anyway (hot path + out-of-band
        # adapt refits), and the drift controller's actuations invalidate
        # exactly the drifting service. Rides state_dict/apply_state so
        # kill/resume with a warm cache stays byte-identical.
        self.plan_cache = _plancache.PlanCache()
        if self.adapt is not None:
            self.adapt.invalidate_cb = self._plan_invalidate
        # per-service refit material: the most recently SOLVED window
        # problem, retained so an out-of-band refit has a post-shift
        # window to re-fit from (one window per service — bounded;
        # regenerates after a resume, so it never rides checkpoints)
        self.adapt_material: Dict[str, _WindowProblem] = {}
        # capture-quality hook (docs/COLLECTOR.md): a source that knows
        # its own capture loss (CollectorSource.capture_quality) — or an
        # external feeder like the serve capture endpoint, via this
        # attribute — discounts every emitted trace's confidence by the
        # observed loss rate and lands a capture block in the summary.
        # None (every instrumented/replay source) is fully inert.
        self.capture_quality_ext = None
        # SLO-breach excursion arming (one event per excursion,
        # re-armed when the p99 falls back under the budget)
        self._slo_breached = False
        # consume re-entrancy tripwire: carried-state/grader/plan-cache
        # updates in consume_batch_results are sequenced per service —
        # the serve ring's FIFO complete guarantees one consume at a
        # time per tenant, and this flag turns a future violation into
        # a loud error instead of silently interleaved carried stats
        self._consuming = False
        # seal→emit latencies of recent emitted windows (seconds; the
        # live p99 the continuous-batching SLO is graded against —
        # bounded so a long-lived tenant tracks RECENT latency, not its
        # whole history)
        self.seal_emit_lat_s = deque(maxlen=512)
        # score-path precision (TW_PRECISION, read at service start) —
        # labels every micro-batch/window line and rides the checkpoint
        # so a resume under a DIFFERENT precision is visible, not silent
        # (it is safe: all checkpointed state — carried EdgeDist
        # statistics, window buffers, offsets — is host-side f32 and
        # precision-independent; only the device score blocks change)
        self.precision = precision_from_env()

    # -- per-window problem construction ----------------------------------
    def _window_problems(self, buf: WindowBuffer) -> List[_WindowProblem]:
        from traceweaver_tpu.ingest.order import infer_dag_from_predictions
        from traceweaver_tpu.metrics import get_ground_truth

        by_service: Dict[str, Tuple[List[Span], List[Span]]] = {}
        for span in buf.spans:
            svc = self.live.service_of(span)
            if svc is None or span.span_kind not in ("server", "client"):
                self._bump("unresolved_spans")
                continue
            ins, outs = by_service.setdefault(svc, ([], []))
            (ins if span.span_kind == "server" else outs).append(span)

        problems = []
        for svc in sorted(by_service):
            ins, outs = by_service[svc]
            if not outs:
                continue  # leaf service: nothing to reconstruct
            in_parts: Dict[str, List[Span]] = {}
            for s in ins:
                ep = self.live.parent_service_of(s)
                if ep is None:
                    self._bump("unresolved_spans")
                    continue
                in_parts.setdefault(ep, []).append(s)
            out_parts: Dict[str, List[Span]] = {}
            for s in outs:
                ep = self.live.child_service_of(s)
                if ep is None:
                    self._bump("unresolved_spans")
                    continue
                out_parts.setdefault(ep, []).append(s)
            if len(in_parts) != 1 or not out_parts:
                # same skip rule as the batch executor's service problems
                self._bump("skipped_service_windows")
                continue
            # partition sort + column build in one move (TW_COLUMNAR):
            # sort keys come from the float columns (one lexsort per
            # partition instead of a key tuple per span), the reordered
            # columns ride the _WindowProblem into the fleet packer, and
            # the span lists are reordered by the same permutation so the
            # object view stays the sorted one graders/truth expect
            use_cols = _knobs.get_bool("TW_COLUMNAR")
            in_cols = None
            out_cols = {} if use_cols else None
            for parts, is_in in ((in_parts, True), (out_parts, False)):
                for ep, part in parts.items():
                    if not use_cols:
                        part.sort(key=lambda s: (s.start_mus, s.end_mus))
                        continue
                    arr = SpanArray.from_spans(part)
                    order = np.lexsort((arr.end, arr.start))
                    if not np.array_equal(order,
                                          np.arange(len(part))):
                        parts[ep] = part = [part[i] for i in order]
                        arr = arr.take(order)
                    if is_in:
                        in_cols = arr
                    else:
                        out_cols[ep] = arr
            (in_ep, in_spans), = in_parts.items()
            truth = get_ground_truth(in_parts, out_parts)
            # strict (tol=0) prediction-shaped pruning over the window's
            # truth reproduces the batch GT DAG inference exactly while
            # tolerating split traces (missing truth entries)
            dag = infer_dag_from_predictions(
                in_parts, out_parts, truth, self.live, tol=0.0)
            problems.append(_WindowProblem(
                service=svc, in_ep=in_ep, in_spans=in_spans,
                out_parts=out_parts, truth=truth, dag=dag,
                in_cols=in_cols, out_cols=out_cols))
        return problems

    # -- solve ------------------------------------------------------------
    def prepare_batch_items(self, bufs: List[WindowBuffer], tenant=None):
        """Build the fleet items for a micro-batch of sealed windows.

        Returns ``(per_buf, items, owners)`` — the per-window problem
        lists, the flat :class:`FleetItem` list, and each item's owning
        buffer index. Split out of :meth:`_solve_batch` so the serve
        layer's tenancy manager can merge several tenants' batches into
        ONE shared :func:`solve_fleet` dispatch (``tenant`` tags the
        items with their owning tenant id; the single-tenant stream path
        leaves it None — the pinned no-tenant default)."""
        from traceweaver_tpu.algorithms.fleet import FleetItem

        per_buf: List[List[_WindowProblem]] = []
        items, owners = [], []
        for b, buf in enumerate(bufs):
            probs = self._window_problems(buf)
            per_buf.append(probs)
            for wp in probs:
                warm = (self.carried.get(wp.service)
                        if self.cfg.warm_start else None)
                if self.adapt is not None:
                    # adaptation fallback rung: a service on wide-prior
                    # fallback scores every edge under the packer's
                    # near-flat Gaussian instead of its (possibly
                    # poisoned) carried statistics — reversible, and
                    # single-pass like any warm solve (adapt/)
                    warm = self.adapt.warm_dists(
                        self.trace_prefix + wp.service, warm)
                items.append(FleetItem(
                    wp.service, {wp.in_ep: wp.in_spans}, wp.out_parts,
                    wp.truth, wp.dag, store=self.live, warm_dists=warm,
                    tenant=tenant, in_cols=wp.in_cols,
                    out_cols=wp.out_cols,
                    # host-side trace context: the fleet's pack thread,
                    # dispatch flows, and decode workers stamp this
                    # window's self-trace through the item (obs/selftrace)
                    trace_key=self._trace_key(buf.k)))
                owners.append(b)
        return per_buf, items, owners

    def _solve_batch(self, bufs: List[WindowBuffer]) -> List[WindowResult]:
        from traceweaver_tpu.algorithms.fleet import solve_fleet

        t0 = time.perf_counter()
        per_buf, items, owners = self.prepare_batch_items(bufs)
        outs = []
        quarantined: List[int] = []
        confidences: List[Optional[Dict]] = (
            [None] * len(items) if _quality.conf_enabled() else None)
        if items:
            from traceweaver_tpu.runtime.jax_cache import (
                compile_counters,
                counters_delta,
            )

            counters_before = compile_counters()
            outs = solve_fleet(items, all_spans=self.live.all_spans,
                               all_processes=self.live.all_processes,
                               stats=self.fleet_stats,
                               precision=self.precision,
                               quarantined=quarantined,
                               confidences=confidences)
            delta = counters_delta(counters_before)
            self._bump("micro_batches")
            # per-dispatch compile/cache visibility: a warm stream runs at
            # zero compiles per micro-batch; any nonzero line here is a new
            # shape class (or a cold persistent cache) — exactly the
            # regression the batch bench's recompile counter watches for
            if self.cfg.verbose and (delta["backend_compiles"]
                                     or delta["persistent_cache_hits"]):
                print("[stream] micro-batch %d [%s]: %d windows, %d XLA "
                      "compiles (%d persistent-cache hits, %d misses)"
                      % (self.stats["micro_batches"], self.precision,
                         len(bufs), delta["backend_compiles"],
                         delta["persistent_cache_hits"],
                         delta["persistent_cache_misses"]))
        solve_s = time.perf_counter() - t0
        self._bump("solve_s", solve_s)
        _OBS_SOLVE_S.observe(solve_s)
        return self.consume_batch_results(bufs, per_buf, owners, outs,
                                          quarantined, solve_s,
                                          confidences=confidences)

    def consume_batch_results(self, bufs: List[WindowBuffer], per_buf,
                              owners: List[int], outs,
                              quarantined: List[int],
                              solve_s: float,
                              confidences=None) -> List[WindowResult]:
        """Decode one micro-batch's fleet results into
        :class:`WindowResult`\\ s (the second half of :meth:`_solve_batch`,
        split out for the serve layer's shared multi-tenant dispatches:
        the manager splits a shared ``solve_fleet`` call's outputs back
        per tenant and hands each tenant its slice here). ``quarantined``
        indexes into THIS batch's item list; carried-state/grader updates
        skip quarantined items exactly as the single-tenant path does.

        NOT re-entrant per service: the carried-state/plan-cache/grader
        updates below are order-dependent folds. The serve ring's FIFO
        complete serializes consumes (tickets retire in submission
        order, under the service lock); this guard makes any future
        violation a loud error, and the ``consume_s`` ledger separates
        host-side decode/fold wall from device ``solve_s``."""
        if self._consuming:
            raise RuntimeError(
                "consume_batch_results re-entered: concurrent consumes "
                "would interleave carried-state folds (serve ring FIFO "
                "contract violated)")
        self._consuming = True
        t_consume = time.perf_counter()
        try:
            return self._consume_batch_results(
                bufs, per_buf, owners, outs, quarantined, solve_s,
                confidences)
        finally:
            self._consuming = False
            self._bump("consume_s", time.perf_counter() - t_consume)

    def _consume_batch_results(self, bufs: List[WindowBuffer], per_buf,
                               owners: List[int], outs,
                               quarantined: List[int],
                               solve_s: float,
                               confidences=None) -> List[WindowResult]:
        from traceweaver_tpu.algorithms import timing

        results: List[WindowResult] = []
        by_buf_outs: List[List] = [[] for _ in bufs]
        by_buf_idx: List[List[int]] = [[] for _ in bufs]
        for idx, (b, out) in enumerate(zip(owners, outs)):
            by_buf_outs[b].append(out)
            by_buf_idx[b].append(idx)
        qset = set(quarantined)
        total_rows = max(1, sum(len(wp.in_spans)
                                for probs in per_buf for wp in probs))
        for buf, probs, buf_outs, buf_idx in zip(bufs, per_buf, by_buf_outs,
                                                 by_buf_idx):
            assignments: Dict[str, Dict[str, Dict]] = {}
            conf_by_svc: Dict[str, Dict] = {}
            n_rows = 0
            quarantined_svcs = tuple(
                wp.service for wp, idx in zip(probs, buf_idx) if idx in qset)
            for wp, out, idx in zip(probs, buf_outs, buf_idx):
                amap = out[0]
                assignments[wp.service] = amap
                n_rows += len(wp.in_spans)
                if confidences is not None and confidences[idx]:
                    conf_by_svc[wp.service] = confidences[idx]
                if idx in qset:
                    # a quarantined item's all-NA result must not feed
                    # the carried statistics or the grader — the window
                    # is dead-lettered, not emitted, and poisoned data
                    # must not warm later windows
                    continue
                if self.adapt is not None:
                    # retain the freshest solved window as refit
                    # material (the out-of-band refit re-solves it COLD
                    # when this service's drift excursion fires)
                    self.adapt_material[wp.service] = wp
                if self.cfg.warm_start:
                    # amortized plan refit: a cache hit means this
                    # service's carried plan is current (admitted by an
                    # earlier refit, not yet drift-invalidated) — skip
                    # the per-micro-batch host refit entirely. Three
                    # guards keep the adaptation dynamics intact:
                    # fallback services re-teach every window (that is
                    # what earns the restore, adapt/controller.py),
                    # services in a live drift EXCURSION keep refitting
                    # until the PSI re-arms under the threshold, and
                    # only a plan fitted from a full window of evidence
                    # is ever admitted (plancache.admissible — freezing
                    # a handful-of-samples fit starves the warm loop
                    # and turns the PSI sensor into atom noise; the
                    # chaos-adapt leg reproduces both). The cache
                    # amortizes the high-volume quiet steady state only.
                    akey = self.trace_prefix + wp.service
                    on_fallback = (self.adapt is not None
                                   and self.adapt.fallback_active(akey))
                    in_excursion = (self.drift is not None
                                    and self.drift.in_excursion(akey))
                    if (on_fallback or in_excursion
                            or self.plan_cache.lookup(wp.service) is None):
                        t_fit = time.perf_counter()
                        dists = timing.refit_from_assignments(
                            {wp.in_ep: wp.in_spans}, wp.out_parts, wp.dag,
                            amap, self.live.all_spans)
                        self.carried.update(wp.service, dists)
                        self._bump("plan_fit_s",
                                   time.perf_counter() - t_fit)
                        if _plancache.admissible(len(wp.in_spans)):
                            self.plan_cache.admit(wp.service, dists)
                if self.grader is not None and not quarantined_svcs:
                    owned = [s for s in wp.in_spans
                             if s.GetId() in buf.owned_ids]
                    self.grader.accumulate(wp.service, wp.in_ep, owned,
                                           wp.out_parts, amap)
            poisoned = bool(quarantined_svcs)
            acc = (self._window_accuracy(buf, probs, assignments)
                   if self.cfg.grade and not poisoned else None)
            results.append(WindowResult(
                buf=buf, assignments=assignments, problems=probs,
                traces=self._stitch(buf, assignments),
                accuracy=acc, n_rows=n_rows,
                solve_share_s=solve_s * n_rows / total_rows,
                poisoned=poisoned,
                poison_reason=("quarantined service(s): %s"
                               % ", ".join(quarantined_svcs)
                               if poisoned else ""),
                quarantined_services=quarantined_svcs,
                confidence=conf_by_svc or None))
        return results

    def _poison_batch(self, bufs: List[WindowBuffer],
                      err: Optional[BaseException]) -> List[WindowResult]:
        """Dead-letter constructor for a micro-batch that exhausted the
        scheduler's watchdog+retry budget: every window becomes a counted
        poison window (consumed by :meth:`_emit` into the dead-letter
        queue) instead of aborting the stream."""
        reason = f"{type(err).__name__}: {err}" if err else "solve failed"
        return [WindowResult(
            buf=buf, assignments={}, problems=[], traces={}, accuracy=None,
            poisoned=True, poison_reason=reason) for buf in bufs]

    def _window_accuracy(self, buf: WindowBuffer,
                         probs: List[_WindowProblem],
                         assignments) -> Optional[float]:
        """Fraction of this window's OWNED incoming spans whose service
        got every endpoint right (window-local exact-match grading)."""
        total = correct = 0
        for wp in probs:
            amap = assignments.get(wp.service, {})
            for s in wp.in_spans:
                if s.GetId() not in buf.owned_ids:
                    continue
                total += 1
                ok = True
                for ep in wp.out_parts:
                    truth = wp.truth.get(ep, {}).get(s.GetId(), SKIP)
                    if amap.get(ep, {}).get(s.GetId(), NA) != truth:
                        ok = False
                        break
                correct += int(ok)
        return correct / total if total else None

    # -- stitching --------------------------------------------------------
    def _stitch(self, buf: WindowBuffer, assignments) -> Dict[str, List]:
        """Assemble predicted traces from this window's owned roots:
        follow each service's predicted outgoing span to its server half
        downstream and recurse through the window's assignments.

        Dispatches on ``TW_WIRE_COLUMNAR``: the default is the array
        path (:meth:`_stitch_arrays` — interned span ids, CSR adjacency,
        one batched numpy BFS over every root at once); ``0`` keeps the
        per-root object DFS (:meth:`_stitch_objects`). Both produce the
        identical trace map (tests/test_wire.py property-tests the
        equivalence on randomized DAGs), so the knob only moves time —
        counted in the ``stitch_s`` stage ledger either way."""
        t0 = time.perf_counter()
        if _knobs.get_bool("TW_WIRE_COLUMNAR"):
            traces = self._stitch_arrays(buf, assignments)
        else:
            traces = self._stitch_objects(buf, assignments)
        self._bump("stitch_s", time.perf_counter() - t0)
        return traces

    def _stitch_roots(self, buf: WindowBuffer) -> List[Span]:
        # owned server roots were flagged at buffer-add time (WindowBuffer
        # collects them as spans arrive), so stitching starts from the
        # root list instead of re-scanning every span of the window; the
        # getattr covers window buffers restored from pre-roots
        # checkpoints, which fall back to the scan once
        roots = getattr(buf, "roots", None)
        if roots is None:
            roots = [s for s in buf.spans
                     if s.GetId() in buf.owned_ids
                     and s.span_kind == "server" and s.IsRoot()]
        return roots

    def _stitch_objects(self, buf: WindowBuffer,
                        assignments) -> Dict[str, List]:
        traces: Dict[str, List] = {}
        for span in self._stitch_roots(buf):
            collected = {span.GetId()}
            stack, visited = [span], set()
            while stack:
                cur = stack.pop()
                if cur.GetId() in visited:
                    continue
                visited.add(cur.GetId())
                svc = self.live.service_of(cur)
                by_ep = assignments.get(svc)
                if not by_ep:
                    continue
                for ep in sorted(by_ep):
                    out_id = by_ep[ep].get(cur.GetId())
                    if (not isinstance(out_id, tuple)
                            or out_id in (NA, SKIP)):
                        continue
                    collected.add(out_id)
                    out_span = self.live.all_spans.get(out_id)
                    if out_span is None:
                        continue
                    for child_id in out_span.children_spans:
                        child = self.live.all_spans.get(child_id)
                        if child is not None and child.span_kind == "server":
                            collected.add(child.GetId())
                            stack.append(child)
            traces[span.trace_id] = sorted(collected)
        return traces

    def _stitch_arrays(self, buf: WindowBuffer,
                       assignments) -> Dict[str, List]:
        """Array form of :meth:`_stitch_objects`: one shared traversal
        interns every reachable node and its edges into CSR arrays, then
        a single numpy BFS advances ALL roots' frontiers at once over
        (R, N) boolean masks. A subgraph shared by many roots is walked
        once here instead of once per root, and the per-root bookkeeping
        is bitmap writes instead of Python set ops. Output is the
        identical trace map: collected ids are sets sorted at the end on
        both paths, so edge/visit order never shows through."""
        roots = self._stitch_roots(buf)
        if not roots:
            return {}
        idx: Dict = {}          # span id -> node index
        table: List = []        # node index -> span id
        span_of: Dict[int, Span] = {}

        def intern(sid) -> int:
            j = idx.get(sid)
            if j is None:
                j = len(table)
                idx[sid] = j
                table.append(sid)
            return j

        root_js: List[int] = []
        work: List[int] = []
        for s in roots:
            j = intern(s.GetId())
            root_js.append(j)
            if j not in span_of:
                span_of[j] = s
                work.append(j)
        # shared traversal: each node's outgoing assignment edges are a
        # property of the node alone (its service's assignment map), so
        # they are computed exactly once no matter how many roots reach
        # it. coll rows carry everything the node adds to a collected
        # set (predicted out ids — present in all_spans or not — plus
        # their server children); next rows carry only the server
        # children the walk continues through, mirroring the object DFS.
        coll_map: Dict[int, List[int]] = {}
        next_map: Dict[int, List[int]] = {}
        while work:
            j = work.pop()
            span = span_of[j]
            by_ep = assignments.get(self.live.service_of(span))
            if not by_ep:
                continue
            sid = span.GetId()
            c_row: List[int] = []
            n_row: List[int] = []
            for ep_map in by_ep.values():
                out_id = ep_map.get(sid)
                if (not isinstance(out_id, tuple)
                        or out_id in (NA, SKIP)):
                    continue
                c_row.append(intern(out_id))
                out_span = self.live.all_spans.get(out_id)
                if out_span is None:
                    continue
                for child_id in out_span.children_spans:
                    child = self.live.all_spans.get(child_id)
                    if child is not None and child.span_kind == "server":
                        cj = intern(child.GetId())
                        c_row.append(cj)
                        n_row.append(cj)
                        if cj not in span_of:
                            span_of[cj] = child
                            work.append(cj)
            if c_row:
                coll_map[j] = c_row
            if n_row:
                next_map[j] = n_row
        n = len(table)
        r = len(roots)
        coll_indptr = np.zeros(n + 1, np.int64)
        next_indptr = np.zeros(n + 1, np.int64)
        coll_flat: List[int] = []
        next_flat: List[int] = []
        for j in range(n):
            coll_flat.extend(coll_map.get(j, ()))
            next_flat.extend(next_map.get(j, ()))
            coll_indptr[j + 1] = len(coll_flat)
            next_indptr[j + 1] = len(next_flat)
        coll_cols = np.asarray(coll_flat, np.int64)
        next_cols = np.asarray(next_flat, np.int64)

        def gather(indptr, cols, fr_r, fr_n):
            # rows fr_r expand to their CSR slices: (row, col) pairs for
            # every edge out of every frontier node, fully vectorized
            counts = indptr[fr_n + 1] - indptr[fr_n]
            total = int(counts.sum())
            if not total:
                return (np.empty(0, np.int64),) * 2
            rows = np.repeat(fr_r, counts)
            cum = np.cumsum(counts)
            offs = np.arange(total, dtype=np.int64) \
                - np.repeat(cum - counts, counts)
            return rows, cols[np.repeat(indptr[fr_n], counts) + offs]

        visited = np.zeros((r, n), bool)
        collected = np.zeros((r, n), bool)
        fr_r = np.arange(r, dtype=np.int64)
        fr_n = np.asarray(root_js, np.int64)
        collected[fr_r, fr_n] = True
        while fr_r.size:
            visited[fr_r, fr_n] = True
            c_rows, c_cols = gather(coll_indptr, coll_cols, fr_r, fr_n)
            if c_rows.size:
                collected[c_rows, c_cols] = True
            n_rows, n_cols = gather(next_indptr, next_cols, fr_r, fr_n)
            if not n_rows.size:
                break
            keep = ~visited[n_rows, n_cols]
            n_rows, n_cols = n_rows[keep], n_cols[keep]
            if not n_rows.size:
                break
            _, uniq = np.unique(n_rows * n + n_cols, return_index=True)
            fr_r, fr_n = n_rows[uniq], n_cols[uniq]
        traces: Dict[str, List] = {}
        for i, span in enumerate(roots):
            traces[span.trace_id] = sorted(
                table[j] for j in np.nonzero(collected[i])[0])
        return traces

    # -- emission ---------------------------------------------------------
    def _deadletter(self, res: WindowResult) -> None:
        """Record a poison window in the dead-letter queue: counted in
        the stats AND persisted as one JSONL record in the sidecar file
        (when configured) — a quarantined window is never silently
        dropped. Conservation invariant (tests/test_faults.py): every
        sealed-and-solved window is either emitted or dead-lettered."""
        buf = res.buf
        rec = dict(
            window=buf.k, start_us=buf.start_us, end_us=buf.end_us,
            n_spans=buf.n_spans, n_owned=buf.n_owned,
            reason=res.poison_reason,
            quarantined_services=sorted(res.quarantined_services),
        )
        line = json.dumps(rec, sort_keys=True)
        if self.deadletter is not None:
            self.deadletter.write_line(line)
            self._bump("deadletter_bytes", len(line) + 1)
        elif self.cfg.verbose:
            print("[stream] WARNING: no dead-letter path configured; "
                  "poison window %d counted but not persisted" % buf.k)
        self._bump("deadletter_windows")
        self._bump("deadletter_spans", buf.n_owned)
        tr = _selftrace.active()
        if tr is not None:
            tr.finish(self._trace_key(buf.k))
        self._since_checkpoint += 1
        if self.cfg.verbose:
            print("[stream] win=%d DEAD-LETTERED spans=%d owned=%d (%s)"
                  % (buf.k, buf.n_spans, buf.n_owned, res.poison_reason))

    def _conf_tenant(self) -> str:
        """Tenant label of the quality metrics: the serve layer's tenant
        id (the trace prefix it installs), "default" on the
        single-tenant stream path."""
        return self.trace_prefix.rstrip(":") or "default"

    def _capture_quality(self) -> Optional[Dict]:
        """The source's capture ledger, when one exists: a collector
        source's own ``capture_quality()`` wins, else the external
        feeder hook (``capture_quality_ext``, the serve capture
        endpoint). None everywhere else — zero cost on the default
        instrumented/replay paths."""
        fn = getattr(self.source, "capture_quality", None)
        if fn is None:
            fn = self.capture_quality_ext
        return fn() if fn is not None else None

    def window_confidence(self, res: WindowResult) -> Optional[Dict]:
        """The window's ``tw.confidence`` payload: the per-window summary
        plus one per-trace summary per stitched trace (min over the
        trace's solved spans — a trace is right only if every span is).
        None when the quality path is off or the solve produced no
        records (docs/OBSERVABILITY.md "Quality telemetry").

        Capture-derived streams additionally discount every confidence
        by ``1 - loss_rate`` of the capture (docs/COLLECTOR.md): a
        solver that never SAW the dropped spans can be arbitrarily
        confident about a wrong containment, so trust in the emitted
        traces must fall with observed capture loss even while the
        solver's own margins stay high. The discount and the rate ride
        the payload (``capture`` block), so consumers can tell solver
        doubt from capture doubt."""
        if not res.confidence:
            return None
        merged: Dict = {}
        for recs in res.confidence.values():
            merged.update(recs)
        out = dict(
            window=_quality.window_confidence_summary(merged),
            traces={tid: _quality.trace_confidence(ids, merged)
                    for tid, ids in sorted(res.traces.items())},
        )
        cap = self._capture_quality()
        if cap is not None:
            rate = float(cap.get("loss_rate", 0.0))
            disc = max(0.0, 1.0 - rate)
            if disc < 1.0:
                for tconf in out["traces"].values():
                    if tconf is not None:
                        tconf["conf"] = round(tconf["conf"] * disc, 4)
                        tconf["mean"] = round(tconf["mean"] * disc, 4)
                w = out["window"]
                for k in ("min", "mean"):
                    if k in w:
                        w[k] = round(w[k] * disc, 4)
            out["capture"] = dict(loss_rate=round(rate, 4),
                                  discount=round(disc, 4))
        return out

    def _observe_confidence(self, res: WindowResult,
                            conf: Optional[Dict]) -> None:
        """Land one emitted window's quality telemetry: per-trace
        histogram + low-confidence counters (per tenant) and the
        per-service drift watcher. The trace-level surfaces consume the
        payload's (capture-discounted) values — trust falls with loss;
        the drift watcher consumes the RAW solver records, so a lossy
        capture cannot masquerade as score-model drift and trip the
        adaptation ladder into refits that cannot help it (capture loss
        has its own counters)."""
        if conf is None:
            return
        tenant = self._conf_tenant()
        n_low = 0
        for tconf in conf["traces"].values():
            if tconf is not None:
                n_low += _quality.observe_trace(tconf["conf"], tenant)
        if n_low:
            self._bump("low_confidence_traces", n_low)
        if self.drift is not None:
            low = _quality.low_threshold()
            for svc, recs in sorted(res.confidence.items()):
                vals = [r["conf"] for r in recs.values()]
                key = self.trace_prefix + svc
                stat = self.drift.update(key, vals)
                if self.adapt is not None and vals:
                    # sensor → decision: the controller sees the drift
                    # statistic the gauge exports — but only once the
                    # rolling window is MATURE (a freshly-frozen
                    # reference compares against a handful of rolling
                    # values; acting on that sampling noise would burn
                    # the hysteresis cooldown before any real shift) —
                    # plus this window's low-confidence rate, and walks
                    # the adaptation ladder (every actuation evented)
                    self.adapt.observe(
                        key,
                        psi=stat if self.drift.mature(key) else None,
                        low_rate=sum(v <= low for v in vals) / len(vals))

    def emit_batch(self, results: List[WindowResult]) -> None:
        """Emit one pump's worth of window results. Default
        (``TW_WIRE_COLUMNAR``): every record is rendered first and the
        whole batch lands in ONE buffered sink write — the same bytes
        in the same order as the per-record flow (``0``), so checkpoint
        truncate-splice, kill/resume, and migration byte-identity hold
        unchanged (tests/test_wire.py pins the sink bytes across the
        knob). Dead-letter windows keep their own per-record sidecar
        writes on both paths. Wall time lands in the ``emit_s`` stage
        ledger either way."""
        if not results:
            return
        t0 = time.perf_counter()
        if _knobs.get_bool("TW_WIRE_COLUMNAR") and self.sink is not None:
            lines: List[str] = []
            for res in results:
                self._emit(res, _batch=lines)
            self.sink.write_lines(lines)
        else:
            for res in results:
                self._emit(res)
        self._bump("emit_s", time.perf_counter() - t0)

    def _emit(self, res: WindowResult,
              _batch: Optional[List[str]] = None) -> None:
        if res.poisoned:
            self._deadletter(res)
            return
        buf = res.buf
        conf = self.window_confidence(res)
        self._observe_confidence(res, conf)
        if self.sink is not None:
            services = {}
            for wp in res.problems:
                amap = res.assignments.get(wp.service, {})
                eps = {}
                for ep in sorted(wp.out_parts):
                    rows = []
                    for s in wp.in_spans:
                        if s.GetId() not in buf.owned_ids:
                            continue
                        out_id = amap.get(ep, {}).get(s.GetId(), NA)
                        rows.append([_sid(s.GetId()), _sid(out_id)])
                    rows.sort()
                    eps[ep] = rows
                services[wp.service] = eps
            rec = dict(
                window=buf.k, start_us=buf.start_us, end_us=buf.end_us,
                services=services,
                traces={tid: [_sid(x) for x in ids]
                        for tid, ids in sorted(res.traces.items())},
            )
            if conf is not None:
                # every emitted trace carries its reconstruction
                # confidence (obs/quality.py): consumers can exclude
                # low-trust reconstructions the way the culprit query
                # does, straight off the record
                rec["tw.confidence"] = conf
            line = json.dumps(rec, sort_keys=True)
            if _batch is None:
                self.sink.write_line(line)
            else:
                _batch.append(line)
        self.emitted_windows += 1
        sealed_wall = getattr(buf, "sealed_wall", 0.0)
        if sealed_wall:
            # the SLO quantity: wall time from seal to emission (queue
            # wait + admission + solve + decode), per tenant
            lat = max(0.0, time.monotonic() - sealed_wall)
            self.seal_emit_lat_s.append(lat)
            _OBS_SEAL_EMIT_S.observe(lat, tenant=self._conf_tenant())
            self._observe_slo()
        tr = _selftrace.active()
        if tr is not None:
            tr.finish(self._trace_key(buf.k))
        self._since_checkpoint += 1
        self._bump("spans_emitted", buf.n_owned)
        self._bump("traces_emitted", len(res.traces))
        if res.accuracy is not None:
            self.stats["last_window_acc"] = res.accuracy
        if self.cfg.verbose:
            acc = ("%.3f" % res.accuracy) if res.accuracy is not None \
                else "n/a"
            rate = (res.n_rows / res.solve_share_s
                    if res.solve_share_s > 0 else 0.0)
            print(
                "[stream] win=%d prec=%s spans=%d owned=%d traces=%d "
                "svc=%d acc=%s wm_delay=%.2fs late=%d/%d shed=%d "
                "backlog=%d %.1f spans/s"
                % (buf.k, self.precision, buf.n_spans, buf.n_owned,
                   len(res.traces), len(res.problems), acc,
                   buf.seal_delay_us / 1e6,
                   self.windower.late_rerouted, self.windower.late_dropped,
                   self.scheduler.shed_spilled
                   + self.scheduler.shed_dropped_windows,
                   self.scheduler.backlog, rate))

    def _observe_slo(self) -> None:
        """SLO-breach telemetry: ONE counted + evented excursion when
        the rolling seal→emit p99 crosses the configured SLO budget,
        re-armed when it falls back under — the pressure signal the
        scheduler failed to absorb, visible to operators and the
        adaptation controller alike. Inert with no SLO configured (the
        historical single-tenant stream default)."""
        slo = self.cfg.slo_p99_ms
        if not slo:
            return
        p99 = self.seal_emit_p99_ms()
        if p99 is None:
            return
        if p99 > slo and not self._slo_breached:
            self._slo_breached = True
            tenant = self._conf_tenant()
            self._bump("slo_breaches")
            _OBS_SLO_BREACH.inc(1.0, tenant=tenant)
            _events.emit("slo_breach", "excursion", tenant=tenant,
                         p99_ms=round(p99, 2), slo_ms=slo)
        elif p99 <= slo:
            self._slo_breached = False

    def maybe_adapt(self) -> int:
        """Execute pending out-of-band adaptation refits (the ladder's
        first rung, :mod:`traceweaver_tpu.adapt.refit`). Called off the
        hot pump — the stream run loop's tail, the serve dispatcher's
        post-solve tick — so the refit's two-pass dispatch never rides
        an SLO admission batch. Returns refits that landed."""
        if self.adapt is None:
            return 0
        n = 0
        for key in self.adapt.pending_refits():
            if _adapt.refit.execute_refit(self, key):
                n += 1
                self._bump("adapt_refits")
        return n

    def _bump(self, key: str, n: float = 1) -> None:
        _OBS_STREAM.inc(n, key=key)
        self.stats[key] = self.stats.get(key, 0) + n

    def _plan_invalidate(self, key: str) -> None:
        """Adapt-controller actuation hook: a drift excursion scheduling
        a refit (or a fallback/failed-refit transition) voids exactly
        that service's cached plan, so the next micro-batch refits it —
        targeted invalidation, not cadence refit. ``key`` is the
        controller's key (``trace_prefix + service``)."""
        svc = key
        if self.trace_prefix and key.startswith(self.trace_prefix):
            svc = key[len(self.trace_prefix):]
        self.plan_cache.invalidate(svc)

    def seal_emit_p99_ms(self) -> Optional[float]:
        """p99 of the recent seal→emit latencies (ms; None before the
        first emission) — the number the continuous-batching SLO
        (``TW_SERVE_SLO_P99_MS``) is graded against."""
        if not self.seal_emit_lat_s:
            return None
        return float(np.percentile(
            np.asarray(self.seal_emit_lat_s, dtype=np.float64), 99)) * 1e3

    def _slo_pressure(self) -> bool:
        """Is any sealed window's age past half the seal→emit SLO
        budget? The single-tenant admission rule: a quiet stream must
        not hold a sealed window hostage to batch fill
        (``StreamConfig.slo_p99_ms``; inert when unset)."""
        if not self.cfg.slo_p99_ms:
            return False
        ready = self.scheduler.ready()
        if not ready:
            return False
        now = time.monotonic()
        budget_s = self.cfg.slo_p99_ms / 2e3
        return any(
            now - (getattr(b, "sealed_wall", 0.0) or now) >= budget_s
            for b in ready)

    # -- self-tracing hooks (obs/selftrace.py; all no-ops when no tracer
    # is installed — one global read per call) ---------------------------
    def _trace_key(self, k: int) -> str:
        return self.trace_prefix + str(k)

    def _trace_touch(self) -> None:
        """First sight of any newly opened window buffers (the ingest
        stage's start clock). Called after ``windower.add``."""
        tr = _selftrace.active()
        if tr is None:
            return
        for k in self.windower.open:
            tr.touch(self._trace_key(k))

    def _trace_seal(self, sealed) -> None:
        """Sealed windows close their ingest stage and stamp the seal
        instant. Called wherever ``windower.poll``/``flush`` hands
        buffers to the scheduler."""
        tr = _selftrace.active()
        if tr is None or not sealed:
            return
        now = _selftrace.now_us()
        for buf in sealed:
            tr.seal(self._trace_key(buf.k), now)

    # -- checkpointing ----------------------------------------------------
    def state_dict(self) -> Dict:
        """Everything a checkpoint must capture to rebuild this service:
        offsets, windowing/watermark state (including still-open window
        buffers), the live span store, carried statistics, the grader,
        and every counter. One definition shared by :meth:`_checkpoint`
        and the serve layer's per-tenant checkpoints (which wrap this
        dict with tenant bookkeeping)."""
        return dict(
            cfg=self.cfg,
            precision=self.precision,
            consumed=self.consumed,
            emitted_windows=self.emitted_windows,
            emit_offset=self.sink.offset if self.sink else 0,
            sink_path=self.sink.path if self.sink else None,
            deadletter_offset=(self.deadletter.offset
                               if self.deadletter else 0),
            deadletter_path=(self.deadletter.path
                             if self.deadletter else None),
            watermark=self.watermark,
            windower=self.windower,
            live=self.live,
            carried=self.carried,
            grader=self.grader,
            conf_drift=self.drift.state() if self.drift else None,
            adapt=self.adapt.state() if self.adapt else None,
            plan_cache=self.plan_cache.state(),
            stats=self.stats,
            fleet_stats=self.fleet_stats,
            pending=list(self.scheduler.pending),
            spill=list(self.scheduler.spill),
            scheduler_counters=(self.scheduler.shed_spilled,
                                self.scheduler.shed_dropped_windows,
                                self.scheduler.shed_dropped_spans,
                                self.scheduler.solved_windows,
                                self.scheduler.solve_timeouts,
                                self.scheduler.solve_retried,
                                self.scheduler.poisoned_windows),
        )

    def _checkpoint(self) -> None:
        if not self.cfg.checkpoint_path:
            return
        try:
            save_checkpoint(self.cfg.checkpoint_path, self.state_dict())
        except (OSError, RuntimeError) as e:
            from traceweaver_tpu.runtime import faults

            if not (isinstance(e, (OSError, faults.FaultError))
                    or faults.is_transient_fault(e)):
                raise
            # a failed checkpoint write must not kill the stream: the
            # rotation in save_checkpoint means the last good generation
            # is still on disk — count, warn, keep serving (the next
            # cadence retries)
            self._bump("checkpoint_failures")
            if self.cfg.verbose:
                print("[stream] WARNING: checkpoint write failed "
                      "(%s: %s) — continuing on the last good checkpoint"
                      % (type(e).__name__, e))
            return
        self._since_checkpoint = 0

    @classmethod
    def resume(cls, checkpoint_path: str, source,
               sink: Optional[TraceSink] = None) -> "StreamingReconstructor":
        """Rebuild a service from its last checkpoint. ``source`` must be
        the same deterministic source the killed run used; the sink (if
        any) is truncated back to the checkpointed offset so the resumed
        run's bytes splice exactly where the checkpoint left off."""
        state = load_checkpoint(checkpoint_path)
        cfg: StreamConfig = state["cfg"]
        cfg.checkpoint_path = checkpoint_path
        if sink is None and state.get("sink_path"):
            sink = TraceSink(state["sink_path"])
        svc = cls(source, cfg, sink=sink)
        # precision compatibility: checkpoints are precision-portable by
        # construction (every checkpointed value — carried EdgeDist
        # statistics, spans, offsets — is host-side f32; the score
        # precision only affects device blocks built AFTER resume), so a
        # cross-precision resume is legal. It changes the solver the
        # re-solved windows run under, so say so rather than resume
        # silently; pre-precision checkpoints carry "f32" implicitly.
        ckpt_precision = state.get("precision", "f32")
        if ckpt_precision != svc.precision and cfg.verbose:
            print("[stream] resume: checkpoint was written under "
                  "precision=%s, resuming under %s (carried state is "
                  "precision-independent)"
                  % (ckpt_precision, svc.precision))
        if state.pop("_recovered_from_prev", False):
            # the primary checkpoint was corrupt/truncated and the load
            # fell back to the rotated last-good generation — counted so
            # the summary says the run survived a checkpoint corruption
            # twlint: disable=TW007 — checkpoint-dict fixup before
            # apply_state, not a live counter (the dict is not self.stats
            # yet; mirroring happens on every _bump after resume)
            state["stats"]["checkpoint_recovered"] = (
                state["stats"].get("checkpoint_recovered", 0) + 1)
        svc.apply_state(state)
        return svc

    def apply_state(self, state: Dict) -> None:
        """Restore a :meth:`state_dict` onto this service (the field half
        of :meth:`resume`, shared with the serve layer's per-tenant
        resume): offsets, windowing state, live store, carried stats,
        counters, scheduler queues, and the sink/dead-letter truncation
        splice."""
        svc = self
        svc.consumed = state["consumed"]
        svc.emitted_windows = state["emitted_windows"]
        svc.watermark = state["watermark"]
        svc.windower = state["windower"]
        svc.live = state["live"]
        svc.carried = state["carried"]
        svc.grader = state["grader"]
        # pre-quality checkpoints carry no drift state: keep the fresh
        # watcher (it re-freezes a reference from post-resume windows)
        if state.get("conf_drift") and svc.drift is not None:
            svc.drift = _quality.ConfidenceDrift.from_state(
                state["conf_drift"])
        # controller state survives kill/resume: probation timers,
        # active fallbacks, refit generations (cooldowns re-stamped as
        # remaining durations — monotonic instants die with the
        # process). Pre-adapt checkpoints (no key) keep the fresh
        # controller; a checkpoint written under TW_ADAPT=1 resumed
        # under TW_ADAPT=0 stays inert by the constructor gate.
        if state.get("adapt") and svc.adapt is not None:
            svc.adapt = _adapt.AdaptationController.from_state(
                state["adapt"])
            # callbacks never ride checkpoint state (they close over the
            # dead process's service): re-attach the invalidation hook
            svc.adapt.invalidate_cb = svc._plan_invalidate
        # warm plan cache survives kill/resume (pre-plan-cache
        # checkpoints carry no key and keep the fresh empty cache), so
        # the resumed run's refit-or-skip decisions — and therefore its
        # emitted bytes — match the uninterrupted run's exactly
        if state.get("plan_cache"):
            svc.plan_cache = _plancache.PlanCache.from_state(
                state["plan_cache"])
        svc.stats = state["stats"]
        svc.fleet_stats = state["fleet_stats"]
        # checkpointed seal stamps are time.monotonic() values from the
        # DEAD process — meaningless here; re-stamp at resume so the
        # SLO admission doesn't read the restart gap as queue age
        now = time.monotonic()
        for buf in list(state["pending"]) + list(state["spill"]):
            buf.sealed_wall = now
        svc.scheduler.pending.extend(state["pending"])
        svc.scheduler.spill.extend(state["spill"])
        counters = state["scheduler_counters"]
        (svc.scheduler.shed_spilled, svc.scheduler.shed_dropped_windows,
         svc.scheduler.shed_dropped_spans,
         svc.scheduler.solved_windows) = counters[:4]
        if len(counters) >= 7:  # v2 checkpoints carry the watchdog ledger
            (svc.scheduler.solve_timeouts, svc.scheduler.solve_retried,
             svc.scheduler.poisoned_windows) = counters[4:7]
        if svc.sink is not None:
            svc.sink.truncate(state["emit_offset"])
        if svc.deadletter is None and state.get("deadletter_path"):
            svc.deadletter = TraceSink(state["deadletter_path"])
        if svc.deadletter is not None:
            # same no-loss/no-double-record splice as the sink: windows
            # dead-lettered after the checkpoint re-poison (or emit) from
            # identical state on the resumed run
            svc.deadletter.truncate(state.get("deadletter_offset", 0))

    # -- main loop --------------------------------------------------------
    def run(self, max_windows: Optional[int] = None) -> Dict:
        """Consume the source to exhaustion (or until ``max_windows``
        windows have been emitted — the kill/test hook) and return the
        final summary. Safe to call on a resumed service: it continues
        from the checkpointed offset."""
        from traceweaver_tpu.runtime import faults

        c = self.cfg
        it = self.source.events(skip=self.consumed)
        while True:
            try:
                # fault-injection site "source": a failed read retries
                # the SAME position (the draw happens before next(), so
                # no event is consumed by a fault) — the transient-ingress
                # model a collector subscription would need
                faults.maybe_fail("source")
                ev = next(it)
            except StopIteration:
                break
            except faults.FaultError:
                self._bump("source_read_retries")
                continue
            self.consumed += 1
            self.watermark.observe(ev.event_us)
            span = self.live.add(ev)
            self.windower.add(span, ev.event_us)
            self._trace_touch()
            sealed = self.windower.poll(self.watermark.value)
            self._trace_seal(sealed)
            for buf in sealed:
                self.scheduler.offer(buf)
            if self.scheduler.backlog >= c.solve_min_batch \
                    or self._slo_pressure():
                self.emit_batch(list(self.scheduler.pump()))
                # adaptation refits run OFF the pump, between pumps:
                # the hot micro-batch dispatch never carries the
                # out-of-band two-pass refit load
                self.maybe_adapt()
            if sealed and c.prune:
                # retention horizon: two windows behind the watermark,
                # never ahead of the oldest window still waiting in the
                # backlog (a long spill backlog must not lose its spans'
                # parent/child context before it gets solved)
                backlog = list(self.scheduler.pending) \
                    + list(self.scheduler.spill)
                oldest = min((b.start_us for b in backlog),
                             default=self.watermark.value)
                horizon = min(self.watermark.value - 2 * c.window_us,
                              oldest - c.window_us) - c.grace_us
                self.live.prune(horizon)
            if self._since_checkpoint >= c.checkpoint_every:
                self._checkpoint()
            if max_windows is not None \
                    and self.emitted_windows >= max_windows:
                return self._summary(final=False)
        return self.finish()

    def finish(self) -> Dict:
        """End of stream: seal and solve everything left, emit, final
        checkpoint, and (in grading mode) compute the end-to-end streamed
        accuracy with the batch metrics."""
        flushed = self.windower.flush()
        self._trace_seal(flushed)
        for buf in flushed:
            self.scheduler.offer(buf)
        self.emit_batch(list(self.scheduler.pump()))
        self.maybe_adapt()
        self._checkpoint()
        return self._summary(final=True)

    def _summary(self, final: bool) -> Dict:
        out = dict(
            final=final,
            precision=self.precision,
            consumed=self.consumed,
            emitted_windows=self.emitted_windows,
            late_rerouted=self.windower.late_rerouted,
            late_dropped=self.windower.late_dropped,
            shed_spilled=self.scheduler.shed_spilled,
            shed_dropped_windows=self.scheduler.shed_dropped_windows,
            shed_dropped_spans=self.scheduler.shed_dropped_spans,
            deadletter_windows=int(self.stats.get("deadletter_windows", 0)),
            deadletter_spans=int(self.stats.get("deadletter_spans", 0)),
            deadletter_bytes=int(self.stats.get("deadletter_bytes", 0)),
            faults=dict(
                retries=int(self.fleet_stats.get("fault_retries", 0)),
                bisections=int(self.fleet_stats.get("fault_bisections", 0)),
                xla_fallbacks=int(
                    self.fleet_stats.get("fault_xla_fallbacks", 0)),
                host_fallbacks=int(
                    self.fleet_stats.get("fault_host_fallbacks", 0)),
                quarantined=int(self.fleet_stats.get("fault_quarantined", 0)),
                injected=int(self.fleet_stats.get("faults_injected", 0)),
                solve_timeouts=self.scheduler.solve_timeouts,
                solve_retried=self.scheduler.solve_retried,
                poisoned_windows=self.scheduler.poisoned_windows,
                checkpoint_failures=int(
                    self.stats.get("checkpoint_failures", 0)),
                checkpoint_recovered=int(
                    self.stats.get("checkpoint_recovered", 0)),
                source_read_retries=int(
                    self.stats.get("source_read_retries", 0)),
            ),
            pruned_spans=self.live.n_pruned,
            watermark_max_skew_us=self.watermark.max_skew_us,
            confidence=dict(
                enabled=self.drift is not None,
                low_traces=int(self.stats.get("low_confidence_traces", 0)),
                drift_alerts=self.drift.alerts if self.drift else 0,
            ),
            adapt=(self.adapt.summary() if self.adapt is not None
                   else dict(enabled=False)),
            slo_breaches=int(self.stats.get("slo_breaches", 0)),
            stats=dict(self.stats),
            fleet=dict(self.fleet_stats),
            pipeline=dict(
                groups=int(self.fleet_stats.get("pipeline_groups", 0)),
                depth=int(self.fleet_stats.get("pipeline_depth", 0)),
                d2h_bytes_fetched=float(
                    self.fleet_stats.get("d2h_bytes_fetched", 0.0)),
                d2h_bytes_flags=float(
                    self.fleet_stats.get("d2h_bytes_flags", 0.0)),
                # H2D split (docs/PERF.md "Device-resident span
                # columns"): shipped host tensors vs resident-ring
                # appends vs gather index arrays — a TW_DEVCOLS run
                # must show ring+index traffic, never a silent zero
                h2d_bytes_shipped=float(
                    self.fleet_stats.get("h2d_bytes_shipped", 0.0)),
                h2d_bytes_ring=float(
                    self.fleet_stats.get("h2d_bytes_ring", 0.0)),
                h2d_bytes_index=float(
                    self.fleet_stats.get("h2d_bytes_index", 0.0)),
                devcols_fallbacks=int(
                    self.fleet_stats.get("devcols_fallbacks", 0)),
            ),
            seal_emit_p99_ms=self.seal_emit_p99_ms(),
        )
        aot_status = _aot.status()
        if aot_status["phase"] != "idle":
            # AOT warmup ledger (runtime/aot.py): present only when a
            # warmup armed the lattice — the cold-start bench child and
            # the serve layer both read progress + misses from here
            out["aot"] = dict(
                mode=aot_status["mode"], phase=aot_status["phase"],
                planned=aot_status["planned"],
                compiled=aot_status["compiled"],
                compile_s=round(float(aot_status["compile_s"]), 3),
                misses=aot_status["misses"])
        cap = self._capture_quality()
        if cap is not None:
            # capture ingress ledger (docs/COLLECTOR.md): per-source
            # loss/churn counters and the fitted skew offsets — present
            # only when the source IS a capture
            out["capture"] = cap
        if final and self.grader is not None:
            out["accuracy"] = self.grader.finish()
        return out
