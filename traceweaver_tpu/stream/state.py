"""Incremental state for the streaming reconstructor.

Three pieces:

- :class:`LiveTraceStore` — the unbounded-stream replacement for the
  batch loader's :class:`~traceweaver_tpu.spans.TraceStore`: spans are
  folded in one event at a time (private copies — replay never mutates
  the source corpus), parent/child links resolve as both ends arrive
  (with a pending index for children that outrun their parents), and
  spans older than a retention horizon are pruned so memory stays bounded
  by window geometry, not stream length.

- :class:`CarriedState` — per-service GMM/score statistics carried
  between windows. A window solved for a service leaves behind its
  refit distributions; the next window warm-starts from them (a
  single-pass solve) instead of re-fitting from scratch — the streaming
  analogue of the batch path's two-pass EM.

- :class:`StreamGrader` — accumulates owned predictions and span
  partitions across windows so the end-of-stream accuracy is computed
  with the *batch* metrics on the *streamed* assignments, making the
  streamed-vs-batch delta an apples-to-apples number
  (docs/STREAMING.md).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Set, Tuple

from traceweaver_tpu.spans import Span, SpanId, TraceStore


class LiveTraceStore(TraceStore):
    """A TraceStore grown incrementally from span events."""

    def __init__(self) -> None:
        super().__init__()
        # children that arrived before their parent: parent_id -> [child_id]
        self._pending_children: Dict[SpanId, List[SpanId]] = {}
        self._spans_by_trace: Dict[str, Set[SpanId]] = {}
        self.n_pruned = 0

    def add(self, event) -> Span:
        """Fold one event in; returns the store's private span copy."""
        # private copy: windows/solves must never mutate the replay
        # corpus's span objects (children links differ between the batch
        # loader's view and the live view)
        span = copy.copy(event.span)
        span.children_spans = []
        sid = span.GetId()
        self.all_spans[sid] = span
        if event.trace_id not in self.all_processes:
            self.all_processes[event.trace_id] = dict(event.processes)
        self._spans_by_trace.setdefault(event.trace_id, set()).add(sid)

        # link to parent (or park in the pending index until it arrives)
        if span.references:
            parent_id = span.references[0]
            parent = self.all_spans.get(parent_id)
            if parent is not None:
                parent.AddChild(sid)
            else:
                self._pending_children.setdefault(parent_id, []).append(sid)
        # adopt any children that arrived first
        for child_id in self._pending_children.pop(sid, []):
            span.AddChild(child_id)
        return span

    # -- endpoint resolution (the live analogues of Span.GetChildProcess /
    # GetParentProcess, returning None instead of asserting when the far
    # end has not arrived or was pruned) --------------------------------
    def child_service_of(self, client_span: Span) -> Optional[str]:
        if len(client_span.children_spans) != 1:
            return None
        child = self.all_spans.get(client_span.children_spans[0])
        if child is None:
            return None
        return self.all_processes.get(child.trace_id, {}).get(
            child.process_id)

    def parent_service_of(self, server_span: Span) -> Optional[str]:
        if server_span.IsRoot():
            return "client_" + str(server_span.op_name)
        parent = self.all_spans.get(server_span.references[0])
        if parent is None:
            return None
        return self.all_processes.get(parent.trace_id, {}).get(
            parent.process_id)

    def service_of(self, span: Span) -> Optional[str]:
        return self.all_processes.get(span.trace_id, {}).get(span.process_id)

    # -- retention --------------------------------------------------------
    def prune(self, before_us: float) -> int:
        """Drop spans that ended before ``before_us`` (and trace tables
        that emptied). Returns how many spans were dropped."""
        dropped = 0
        for tid in list(self._spans_by_trace):
            ids = self._spans_by_trace[tid]
            for sid in list(ids):
                span = self.all_spans.get(sid)
                if span is not None and float(span.end_mus) < before_us:
                    del self.all_spans[sid]
                    ids.discard(sid)
                    dropped += 1
            if not ids:
                del self._spans_by_trace[tid]
                self.all_processes.pop(tid, None)
        # pending links whose parent span would already be past retention
        # can never resolve; let them go with the same horizon
        for pid in list(self._pending_children):
            if pid not in self.all_spans:
                kids = [k for k in self._pending_children[pid]
                        if k in self.all_spans]
                if not kids:
                    del self._pending_children[pid]
        self.n_pruned += dropped
        return dropped


class CarriedState:
    """Per-service statistics carried between windows."""

    def __init__(self) -> None:
        # service -> {edge key -> EdgeDist} from the last refit
        self.dists: Dict[str, Dict[Tuple[str, str], object]] = {}
        self.windows_seen: Dict[str, int] = {}

    def get(self, service: str):
        return self.dists.get(service)

    def update(self, service: str, dists) -> None:
        if dists:
            self.dists[service] = dists
        # twlint: disable=TW007 — warm-start solver state (rides the
        # checkpoint and seeds the next window's EM), not telemetry
        self.windows_seen[service] = self.windows_seen.get(service, 0) + 1


class StreamGrader:
    """Accumulates streamed outputs for end-of-stream batch-metric
    grading. Ground truth is used for GRADING ONLY — nothing here feeds
    back into the solve."""

    def __init__(self) -> None:
        # service -> in_ep -> [owned in spans]
        self._in_parts: Dict[str, Dict[str, List[Span]]] = {}
        # service -> out_ep -> {span id -> span} (deduped across windows)
        self._out_parts: Dict[str, Dict[str, Dict[SpanId, Span]]] = {}
        # service -> out_ep -> {in id -> out id}
        self.pred: Dict[str, Dict[str, Dict]] = {}
        self._seen_in: Dict[str, Set[SpanId]] = {}
        self.skipped_services: Set[str] = set()

    def accumulate(self, service: str, in_ep: str, owned_in: List[Span],
                   out_parts: Dict[str, List[Span]],
                   pred: Dict[str, Dict]) -> None:
        seen = self._seen_in.setdefault(service, set())
        dst_in = self._in_parts.setdefault(service, {}).setdefault(in_ep, [])
        fresh = [s for s in owned_in if s.GetId() not in seen]
        dst_in.extend(fresh)
        seen.update(s.GetId() for s in fresh)
        dst_out = self._out_parts.setdefault(service, {})
        for ep, spans in out_parts.items():
            d = dst_out.setdefault(ep, {})
            for s in spans:
                d.setdefault(s.GetId(), s)
        dst_pred = self.pred.setdefault(service, {})
        fresh_ids = {s.GetId() for s in fresh}
        for ep, amap in pred.items():
            d = dst_pred.setdefault(ep, {})
            for in_id, out_id in amap.items():
                if in_id in fresh_ids:
                    d[in_id] = out_id

    def finish(self) -> Dict:
        """Batch metrics over the merged streamed output."""
        from traceweaver_tpu.metrics import (
            accuracy_end_to_end,
            accuracy_for_service,
            get_ground_truth,
        )

        per_service: Dict[str, float] = {}
        true_by: Dict[str, Dict] = {}
        pred_by: Dict[str, Dict] = {}
        in_spans_by: Dict[str, List[Span]] = {}
        for svc, in_parts in self._in_parts.items():
            if len(in_parts) != 1:
                # the service saw different upstream endpoints in
                # different windows; the batch metrics cannot grade it
                self.skipped_services.add(svc)
                continue
            out_parts = {
                ep: sorted(d.values(),
                           key=lambda s: (s.start_mus, s.end_mus))
                for ep, d in self._out_parts.get(svc, {}).items()
            }
            if not out_parts:
                self.skipped_services.add(svc)
                continue
            (in_ep, in_spans), = in_parts.items()
            in_spans = sorted(in_spans, key=lambda s: (s.start_mus,
                                                       s.end_mus))
            if not in_spans:
                continue
            truth = get_ground_truth({in_ep: in_spans}, out_parts)
            pred = self.pred.get(svc, {})
            pred = {ep: dict(pred.get(ep, {})) for ep in out_parts}
            per_service[svc] = accuracy_for_service(
                pred, truth, {in_ep: in_spans})
            true_by[svc] = truth
            pred_by[svc] = pred
            in_spans_by[svc] = in_spans
        if true_by:
            _, e2e = accuracy_end_to_end(pred_by, true_by, in_spans_by)
        else:
            e2e = 0.0
        return dict(per_service=per_service, e2e=e2e * 100.0,
                    skipped_services=sorted(self.skipped_services))
