"""Write-ahead ingest log: durability between ack and checkpoint.

The serve tier acks ``POST /spans`` with 200 the moment the payload is
parsed into open window buffers — but those buffers live in memory until
the next checkpoint. A replica that dies hard (SIGKILL, OOM, power)
between ack and checkpoint silently loses every acked-but-unemitted
span, which defeats the whole premise of reconstructing traces nobody
else can recover. The WAL closes that gap: the raw accepted wire bytes
are appended here *before* the 200 goes out, and resume replays the
tail through the normal ingest path, so the emitted trace set equals an
uncrashed run's byte-for-byte.

Frame format (little-endian)::

    +------+-------+---------+---------+-----------------+
    | TWWL | crc32 | length  |   seq   | payload bytes   |
    | 4 B  | u32   | u32     | u64     | ``length`` B    |
    +------+-------+---------+---------+-----------------+

``crc32`` covers the packed seq + payload, so a corrupt/reused seq is
detected the same as payload rot. ``seq`` is the WAL's own monotonic
append counter — it orders replay and anchors the checkpoint low-water
mark (client-retry dedup uses a *separate* per-tenant client seq carried
inside the payload envelope, not this field).

Segments: appends go to ``wal-<first_seq:016d>.log`` files, rotated once
a segment reaches ``segment_bytes``. ``truncate_below(low)`` deletes
whole segments whose every record is ≤ ``low`` — the checkpoint records
its low-water mark (the last seq applied to checkpointed state), so
segments vanish as soon as their windows are durably checkpointed,
mirroring the sink's offset/truncate splice semantics.

Sync policies (``TW_WAL_SYNC``):

- ``always`` — write + flush + fsync per append; survives power loss.
- ``batch`` (default) — write + flush to the OS per append (survives
  process death: kill -9, OOM), fsync group-committed on the serve
  pump cadence via :meth:`WriteAheadLog.sync`.
- ``off`` — buffered write only; flushed at close/checkpoint. Documents
  a loss window; exists for the bench baseline.

Torn tails: a partial final frame (torn append, truncated file) is
TRUNCATED to the last CRC-valid frame boundary at open/replay — counted
(``torn_tails``/``torn_bytes``) and evented (``wal_torn_tail``), never
raised. Corruption can only be at the tail because frames are append-
only and truncate drops whole segments.

Fault injection: ``maybe_fail("wal")`` gates both the append (the
injected failure writes HALF the frame first — a real torn append whose
client never gets an ack and whose bytes the next replay truncates) and
the fsync path.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

#: frame header = MAGIC + u32 crc32(seq_bytes + payload) + u32 len + u64 seq
_MAGIC = b"TWWL"
_HEADER = struct.Struct("<4sIIQ")
_SEQ = struct.Struct("<Q")

SYNC_POLICIES = ("always", "batch", "off")

_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".log"


def _maybe_fail(site: str) -> None:
    # lazy import: wal.py stays importable without pulling the runtime
    # package (and jax) in at module-import time
    from traceweaver_tpu.runtime import faults

    faults.maybe_fail(site)


def _emit(event: str, **fields) -> None:
    from traceweaver_tpu.obs import events as _events

    _events.emit("serve", event, **fields)


def pack_frame(seq: int, payload: bytes) -> bytes:
    """One CRC-framed WAL record (also the unit torn-tail tests cut)."""
    seq_b = _SEQ.pack(seq)
    crc = zlib.crc32(seq_b + payload)
    return _HEADER.pack(_MAGIC, crc, len(payload), seq) + payload


def scan_frames(raw: bytes) -> Tuple[List[Tuple[int, int, bytes]], int]:
    """Walk ``raw`` frame by frame; returns ``([(offset, seq, payload)],
    valid_end)`` where ``valid_end`` is the byte offset of the first
    invalid frame (== ``len(raw)`` when the tail is clean). Never raises:
    a bad magic, short header, over-long length, or CRC mismatch simply
    ends the valid prefix — the caller truncates there."""
    frames: List[Tuple[int, int, bytes]] = []
    off = 0
    n = len(raw)
    while off + _HEADER.size <= n:
        magic, crc, length, seq = _HEADER.unpack_from(raw, off)
        if magic != _MAGIC:
            break
        end = off + _HEADER.size + length
        if end > n:
            break
        payload = raw[off + _HEADER.size:end]
        if zlib.crc32(_SEQ.pack(seq) + payload) != crc:
            break
        frames.append((off, seq, payload))
        off = end
    return frames, off


def segment_name(first_seq: int) -> str:
    return f"{_SEG_PREFIX}{first_seq:016d}{_SEG_SUFFIX}"


def list_segments(wal_dir: str) -> List[str]:
    """Segment file names in append order (name sorts by first seq)."""
    if not os.path.isdir(wal_dir):
        return []
    return sorted(
        f for f in os.listdir(wal_dir)
        if f.startswith(_SEG_PREFIX) and f.endswith(_SEG_SUFFIX))


def install_bytes(wal_dir: str, raw: bytes) -> int:
    """Install transferred WAL bytes (the failover ``migrate_in`` half):
    concatenated segment bytes from a crashed replica become one fresh
    segment named by the first frame's seq. A torn tail in the transfer
    is truncated here, same contract as open. Returns frames kept."""
    frames, valid_end = scan_frames(raw)
    if not frames:
        return 0
    os.makedirs(wal_dir, exist_ok=True)
    path = os.path.join(wal_dir, segment_name(frames[0][1]))
    with open(path, "wb") as f:
        f.write(raw[:valid_end])
        f.flush()
        os.fsync(f.fileno())
    return len(frames)


def read_all_bytes(wal_dir: str) -> bytes:
    """Concatenated raw segment bytes for transfer (frames are self-
    delimiting, so concatenation in name order is a valid stream)."""
    out = []
    for name in list_segments(wal_dir):
        with open(os.path.join(wal_dir, name), "rb") as f:
            out.append(f.read())
    return b"".join(out)


class WriteAheadLog:
    """Segment-rotated CRC-framed append log under one directory.

    Single-writer: the serve tier appends under the tenant-service lock.
    ``append`` returns the record's WAL seq; durability at return time
    follows the sync policy (see module docstring).
    """

    def __init__(self, wal_dir: str, segment_bytes: int = 16 << 20,
                 sync: str = "batch"):
        if sync not in SYNC_POLICIES:
            raise ValueError(
                f"wal sync policy {sync!r} not in {SYNC_POLICIES}")
        self.dir = wal_dir
        self.segment_bytes = int(segment_bytes)
        self.sync_policy = sync
        self._f = None  # open tail segment handle
        self._f_path: Optional[str] = None
        self._f_size = 0
        self._dirty = False  # bytes flushed to OS but not fsynced
        self._torn = False  # a faulted append left half a frame on disk
        self.last_seq = 0  # highest seq ever appended (or seen at open)
        self.appended = 0
        self.synced = 0
        self.torn_tails = 0
        self.torn_bytes = 0
        os.makedirs(wal_dir, exist_ok=True)
        self._recover_tail()

    # ------------------------------------------------------------- open

    def _recover_tail(self) -> None:
        """Scan the last segment, truncate a torn tail, position the
        append cursor. Older segments are trusted (they were complete
        when rotated); only the tail can be torn."""
        segs = list_segments(self.dir)
        if not segs:
            return
        tail = os.path.join(self.dir, segs[-1])
        with open(tail, "rb") as f:
            raw = f.read()
        frames, valid_end = scan_frames(raw)
        if valid_end < len(raw):
            dropped = len(raw) - valid_end
            self.torn_tails += 1
            self.torn_bytes += dropped
            with open(tail, "r+b") as f:
                f.truncate(valid_end)
            _emit("wal_torn_tail", dir=self.dir, segment=segs[-1],
                  dropped_bytes=dropped, valid_frames=len(frames))
        if frames:
            self.last_seq = frames[-1][1]
        elif valid_end == 0:
            # tail segment held nothing valid; recover last_seq from the
            # previous segment's name-embedded first seq if any remain
            os.unlink(tail)
            segs = list_segments(self.dir)
            if segs:
                prev = os.path.join(self.dir, segs[-1])
                with open(prev, "rb") as f:
                    pframes, _ = scan_frames(f.read())
                if pframes:
                    self.last_seq = pframes[-1][1]
            return
        self._f_path = tail
        self._f = open(tail, "ab")
        self._f_size = valid_end

    # ----------------------------------------------------------- append

    def _rotate(self, first_seq: int) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        self._f_path = os.path.join(self.dir, segment_name(first_seq))
        self._f = open(self._f_path, "ab")
        self._f_size = 0

    def append(self, payload: bytes) -> int:
        """Durably (per policy) append one payload; returns its WAL seq.
        On injected fault, half the frame is written before the raise —
        a genuine torn append the next open truncates."""
        seq = self.last_seq + 1
        frame = pack_frame(seq, payload)
        if self._f is None or self._f_size >= self.segment_bytes:
            self._rotate(seq)
        if self._torn:
            # a previous faulted append left half a frame past the valid
            # boundary; rewind so the log stays scannable if we live on
            # (if we had died, open-time recovery truncates the same way)
            self._f.flush()
            self._f.truncate(self._f_size)
            self._f.seek(self._f_size)
            self._torn = False
        try:
            _maybe_fail("wal")
        except Exception:
            # torn append: half a frame hits the disk, the client never
            # gets an ack, replay truncates the partial record
            self._f.write(frame[:max(1, len(frame) // 2)])
            self._f.flush()
            self._torn = True
            raise
        self._f.write(frame)
        if self.sync_policy != "off":
            self._f.flush()  # to the OS: survives kill -9
        if self.sync_policy == "always":
            self._fsync()
        else:
            self._dirty = True
        self._f_size += len(frame)
        self.last_seq = seq
        self.appended += 1
        return seq

    def _fsync(self) -> None:
        _maybe_fail("wal")
        os.fsync(self._f.fileno())
        self.synced += 1
        self._dirty = False

    def sync(self) -> None:
        """Group commit: flush + fsync pending appends (the ``batch``
        policy's durability point, called on the serve pump cadence)."""
        if self._f is None or not self._dirty:
            return
        self._f.flush()
        self._fsync()

    # ---------------------------------------------------------- cleanup

    def truncate_below(self, low_seq: int) -> int:
        """Drop whole segments whose every record seq is ≤ ``low_seq``
        (their windows are checkpointed — the WAL no longer owns them).
        Returns segments removed. The tail segment is never removed."""
        segs = list_segments(self.dir)
        removed = 0
        for i, name in enumerate(segs):
            if i + 1 < len(segs):
                # a segment's records all precede the next segment's
                # first seq (embedded in its name)
                nxt_first = int(segs[i + 1][len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
                last_in_seg = nxt_first - 1
            else:
                break  # keep the open tail
            if last_in_seg <= low_seq:
                path = os.path.join(self.dir, name)
                if path != self._f_path:
                    os.unlink(path)
                    removed += 1
            else:
                break
        return removed

    # ----------------------------------------------------------- replay

    def replay(self, start_seq: int = 0) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(seq, payload)`` for every record with seq >
        ``start_seq``, in append order, across segments. Torn tails were
        already truncated at open; a mid-stream scan stop (impossible in
        an untampered log) simply ends that segment's yield."""
        for name in list_segments(self.dir):
            with open(os.path.join(self.dir, name), "rb") as f:
                raw = f.read()
            frames, _ = scan_frames(raw)
            for _off, seq, payload in frames:
                if seq > start_seq:
                    yield seq, payload

    # ------------------------------------------------------------ misc

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()
            self._f = None

    def destroy(self) -> None:
        """Close and delete every segment (migrate_out: the checkpoint
        transferred at migrate time fully covers the log)."""
        self.close()
        for name in list_segments(self.dir):
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    def stats(self) -> dict:
        return dict(
            last_seq=self.last_seq,
            appended=self.appended,
            synced=self.synced,
            torn_tails=self.torn_tails,
            torn_bytes=self.torn_bytes,
            segments=len(list_segments(self.dir)),
            sync_policy=self.sync_policy,
        )
