"""Overlapping event-time windows with single-owner emission.

Windows are keyed by index ``k`` and cover the absolute event-time range
``[k*stride, k*stride + size)`` with ``stride = size - overlap``; with
``overlap = 0`` they are plain tumbling windows. A span at event time
``t`` joins *every* window covering ``t`` but is **owned** by exactly one
— ``k = floor(t / stride)``, the latest window starting at or before
``t``. Ownership decides emission: a sealed window's solve emits
assignments only for the incoming spans it owns, so overlapping windows
never double-emit. The overlap region gives spans near a boundary
candidate outgoing spans (and competing incoming rows) from the far side
— the cross-window candidates a hard cut would lose; the residual loss
is what the streamed-vs-batch accuracy delta measures (docs/STREAMING.md).

Sealing is watermark-driven: window ``k`` seals once the watermark passes
``end(k) + grace_us``. A span whose owner window has already sealed is
*late*; it is rerouted — owned — into the earliest window still open (its
assignment is then solved with that window's context, usually a weak one,
but it is emitted exactly once), or counted in ``late_dropped`` when
nothing is open. Both outcomes are quantified (``late_rerouted`` /
``late_dropped``). ``grace_us`` is the allowed lateness *before* this
kicks in: a window outlives its watermark crossing by ``grace_us``, so
spans up to that late still land in their own window.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from traceweaver_tpu.spans import Span


@dataclass
class WindowBuffer:
    """Spans buffered for one window, with the owned subset marked."""

    k: int
    start_us: float
    end_us: float
    spans: List[Span] = field(default_factory=list)
    owned_ids: Set[Tuple[str, str]] = field(default_factory=set)
    # owned server-side roots, collected at add() time so emission-side
    # consumers (trace stitching) never re-scan the whole buffer to find
    # them — the columnar-host-path rule: per-span Python work happens
    # once, where the span already is in hand
    roots: List[Span] = field(default_factory=list)
    # stamped at seal time by the engine: watermark delay when sealed
    seal_delay_us: float = 0.0
    # wall clock (time.monotonic) at seal time — the start of the
    # seal→emit latency the continuous-batching scheduler trades
    # against batch-fill efficiency (TW_SERVE_SLO_P99_MS); 0.0 on
    # buffers restored from pre-SLO checkpoints (their latency is
    # unknowable and is not counted)
    sealed_wall: float = 0.0

    def add(self, span: Span, owned: bool) -> None:
        self.spans.append(span)
        if owned:
            self.owned_ids.add(span.GetId())
            if span.span_kind == "server" and span.IsRoot():
                self.roots.append(span)

    @property
    def n_spans(self) -> int:
        return len(self.spans)

    @property
    def n_owned(self) -> int:
        return len(self.owned_ids)


class WindowingEngine:
    """Buckets spans into overlapping windows and seals them in order."""

    def __init__(self, size_us: float, overlap_us: float = 0.0,
                 grace_us: float = 0.0) -> None:
        if size_us <= 0:
            raise ValueError(f"window size_us must be > 0, got {size_us}")
        if not 0 <= overlap_us < size_us:
            raise ValueError(
                f"overlap_us must be in [0, size_us), got {overlap_us}")
        self.size_us = float(size_us)
        self.stride_us = float(size_us) - float(overlap_us)
        self.grace_us = float(grace_us)
        self.open: Dict[int, WindowBuffer] = {}
        # watermark as of the last poll: the sealing frontier. A window k
        # is sealed iff end(k) + grace <= this (empty windows never
        # materialize a buffer but still count as sealed by time).
        self.sealed_frontier_us: float = float("-inf")
        self.late_rerouted = 0
        self.late_dropped = 0

    # -- geometry ---------------------------------------------------------
    def owner_of(self, t: float) -> int:
        return int(math.floor(t / self.stride_us))

    def covering(self, t: float) -> List[int]:
        """All window indices whose range contains t, ascending."""
        k_hi = self.owner_of(t)
        # k*stride + size > t  <=>  k > (t - size)/stride
        k_lo = int(math.floor((t - self.size_us) / self.stride_us)) + 1
        return list(range(max(k_lo, 0), k_hi + 1))

    def window_range(self, k: int) -> Tuple[float, float]:
        return k * self.stride_us, k * self.stride_us + self.size_us

    def _is_sealed(self, k: int) -> bool:
        _, end = self.window_range(k)
        return end + self.grace_us <= self.sealed_frontier_us

    def _buffer(self, k: int) -> WindowBuffer:
        buf = self.open.get(k)
        if buf is None:
            start, end = self.window_range(k)
            buf = self.open[k] = WindowBuffer(k, start, end)
        return buf

    # -- ingest -----------------------------------------------------------
    def add(self, span: Span, event_us: float) -> str:
        """Route one span. Returns "ok", "late_rerouted", or
        "late_dropped"."""
        owner = self.owner_of(event_us)
        cover = self.covering(event_us)
        if self._is_sealed(owner):
            # late span: its owner (and, with it, every earlier covering
            # window) already sealed. Route it — owned — into the earliest
            # window still open, so it is emitted exactly once, just from
            # a later window than its event time nominally maps to; drop
            # with accounting when nothing is open to take it.
            open_ks = sorted(k for k in self.open if not self._is_sealed(k))
            if open_ks:
                self._buffer(open_ks[0]).add(span, owned=True)
                self.late_rerouted += 1
                return "late_rerouted"
            self.late_dropped += 1
            return "late_dropped"
        for k in cover:
            if not self._is_sealed(k):
                self._buffer(k).add(span, owned=(k == owner))
        return "ok"

    # -- sealing ----------------------------------------------------------
    def poll(self, watermark_us: float) -> List[WindowBuffer]:
        """Advance the sealing frontier to ``watermark_us`` and pop every
        window now sealed, in window order."""
        self.sealed_frontier_us = max(self.sealed_frontier_us, watermark_us)
        sealed = []
        now = time.monotonic()
        for k in sorted(self.open):
            if self._is_sealed(k):
                buf = self.open.pop(k)
                buf.seal_delay_us = max(
                    0.0, self.sealed_frontier_us - buf.end_us)
                buf.sealed_wall = now
                sealed.append(buf)
        return sealed

    def flush(self) -> List[WindowBuffer]:
        """End of stream: seal every remaining window in order."""
        self.sealed_frontier_us = float("inf")
        out = [self.open.pop(k) for k in sorted(self.open)]
        now = time.monotonic()
        for buf in out:
            buf.seal_delay_us = 0.0
            buf.sealed_wall = now
        return out
