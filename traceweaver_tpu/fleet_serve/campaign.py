"""Wire-level fleet load campaign: 1 vs N replicas through real HTTP.

The PR-15 campaign harness measures the solver fleet under a synthetic
drive that calls the service layer directly. This runner closes the
remaining gap to production: a CLOSED-LOOP load generator posts
Jaeger-JSON over the real ingestion wire — generator → fleet router →
consistent-hash → replica HTTP server → tenant windower — against a
1-replica and an N-replica fleet, and emits the same gated
``CAMPAIGN_*.json`` artifact shape the ledger/compare machinery
(:mod:`traceweaver_tpu.campaign`) already reviews and regression-gates.

Drive shape:

- one generator thread per tenant, **closed loop** (every POST waits
  for its response before the next — the generator sees real
  backpressure, honors 429 ``Retry-After``, and retries the SAME
  payload so nothing is double-ingested);
- **heavy-tailed tenant rates**: tenant *i* posts at rate ∝ 1/(i+1),
  so one hot tenant dominates — the Alibaba-shaped skew the hash ring
  and migration machinery exist for;
- each POST is one fresh event-time window (trace ids unique per
  window, spans placed in the window interior clear of the overlap
  region), so conservation is exact: every ingested trace must emit
  exactly once;
- each N>=2 rung runs TWO phases on one fleet: a measured **steady**
  phase (``spans_per_s`` = closed-loop ACCEPTED spans over the drive
  wall — the wire capacity replicas scale — gated by a flush + settle
  that makes every accepted span emit; placement is rebalanced first
  so a degenerate all-tenants-on-one hash split cannot measure a
  1-replica fleet twice), then a gated
  **chaos** phase where the generators resume and the hottest tenant
  is LIVE-MIGRATED mid-post — plus, subprocess mode, a ``kill -9`` of
  the replica serving the hot tenant (the crash supervisor must
  recover it: respawn + ingest-WAL replay, or survivor failover from
  the dead disk) and a rolling restart of every replica. The chaos
  wall (dominated by full process cold-starts) stays out of the
  throughput figure, but its spans ride the same rung-wide
  conservation gate: every acked span must emit exactly once, with
  dedup echoes (a router-retried POST whose ack died with the victim)
  counted from the replica's ledger — the failover machinery must be
  lossless under live load AND under SIGKILL.

Rung accounting (per ``fleet-<n>`` rung): sustained spans/s over the
steady phase wall, per-tenant seal→emit p99, migration/restart/
retry counters from the router, and a zero-loss assertion
(Σ ingested == Σ emitted, zero dropped/dead-lettered/late-dropped
windows, over BOTH phases) that FAILS the campaign rather than
shipping a lossy artifact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest

from traceweaver_tpu.campaign import ledger
from traceweaver_tpu.fleet_serve.manager import (
    FleetManager,
    InProcReplica,
    ReplicaProcess,
)
from traceweaver_tpu.fleet_serve.router import http_json

#: spans per handcrafted hotel trace (frontend -> search -> geo)
SPANS_PER_TRACE = 5

#: serve geometry the corpus is built against (matches the serve
#: defaults the subprocess replicas boot with)
WINDOW_US = 60e6


def fleet_trace(tid: str, base_us: float, i: int,
                spacing_us: float = 10_000.0) -> Dict:
    """One hotel-shaped Jaeger-JSON trace (same 5-span frontend →
    search → geo skeleton as the tier-1 serve corpus; every 6th trace
    plants its latency in ``search``)."""
    T = base_us + i * spacing_us
    slow = (i % 6) == 5
    s1_dur = 5000.0 if slow else 600.0
    c1_dur = s1_dur + 500.0
    root_dur = c1_dur + 400.0

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r} for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    spans = [
        span("root", T, root_dur, "HTTP GET /hotels", [], "p1", "server"),
        span("c1", T + 200, c1_dur, "call-search", ["root"], "p1", "client"),
        span("s1", T + 300, s1_dur, "search", ["c1"], "p2", "server"),
        span("c2", T + 400, 300.0, "call-geo", ["s1"], "p2", "client"),
        span("s2", T + 450, 200.0, "geo", ["c2"], "p3", "server"),
    ]
    return dict(traceID=tid, spans=spans,
                processes=dict(p1={"serviceName": "frontend"},
                               p2={"serviceName": "search"},
                               p3={"serviceName": "geo"}))


def fleet_payload(tenant: str, seq: int, n_traces: int) -> Dict:
    """One POST body = one fresh event-time window for this tenant.

    ``base_us`` advances a full window stride per seq and lands 10s into
    the window interior, clear of the 5s overlap region on both edges —
    so every trace belongs to exactly one window and the conservation
    check (ingested == emitted, exactly once) is strict."""
    base_us = seq * WINDOW_US + 10e6
    return {"data": [fleet_trace(f"{tenant}w{seq:05d}n{i:03d}",
                                 base_us, i)
                     for i in range(n_traces)]}


class _TenantDrive(threading.Thread):
    """Closed-loop generator for one tenant: POST, await response,
    honor 429 Retry-After (retrying the SAME window payload), pace by
    the tenant's heavy-tail period.

    Pacing is an ABSOLUTE schedule (send k at start + k*period), not
    post-then-sleep: sleeping a full period after each response adds
    the response latency to every cycle, silently under-driving the
    fleet by exactly the latency being measured (coordinated omission —
    the classic closed-loop generator bug). Falling behind schedule
    (a slow response, a 429 wait) is repaid by posting immediately
    until caught up, so the offered load over the phase is the plan's
    rate, and backpressure shows up as 429 counts and latency — never
    as silently reduced offer."""

    def __init__(self, base_url: str, tenant: str, period_s: float,
                 n_traces: int, stop_evt: threading.Event,
                 start_seq: int = 0) -> None:
        super().__init__(name=f"tw-drive-{tenant}", daemon=True)
        self.base_url = base_url
        self.tenant = tenant
        self.period_s = period_s
        self.n_traces = n_traces
        self.stop_evt = stop_evt
        # window sequence cursor: a later drive phase for the same
        # tenant resumes here so event time stays monotonic (a reused
        # seq would land in an already-sealed window as a late span)
        self.seq = start_seq
        self.posts = 0
        self.traces = 0
        self.retry_after_429s = 0
        self.retry_after_503s = 0
        self.deduped = 0
        self.errors: List[str] = []

    def _post(self, payload: Dict) -> Tuple[int, Dict, Dict]:
        data = json.dumps(payload).encode("utf-8")
        # the window seq doubles as the idempotency key: a retry of a
        # POST whose ack died with a killed replica carries the same
        # seq, and the replica's WAL dedup window answers it from the
        # ledger instead of double-ingesting
        req = urlrequest.Request(
            f"{self.base_url}/api/v1/tenants/{self.tenant}/spans",
            data=data, method="POST",
            headers={"Content-Type": "application/json",
                     "X-TW-Seq": str(self.seq)})
        try:
            with urlrequest.urlopen(req, timeout=120) as resp:
                return resp.status, dict(resp.headers), \
                    json.loads(resp.read() or b"{}")
        except urlerror.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                body = {}
            return e.code, dict(e.headers or {}), body

    def run(self) -> None:
        next_send = time.monotonic()
        while not self.stop_evt.is_set():
            payload = fleet_payload(self.tenant, self.seq, self.n_traces)
            while not self.stop_evt.is_set():
                try:
                    status, headers, body = self._post(payload)
                except (urlerror.URLError, OSError) as e:
                    # the router retries/fails internally; a transport
                    # error here means the ROUTER is gone — record, stop
                    self.errors.append(f"seq {self.seq}: {e}")
                    return
                if status == 200:
                    self.posts += 1
                    # count what the replica says it INGESTED, not what
                    # we offered: a dedup echo (the router retried a
                    # POST whose ack died with a crashed replica)
                    # reports the ORIGINAL apply exactly once, keeping
                    # Σ acked == Σ ingested exact under crash-retry
                    self.traces += int(body.get("ingested_traces",
                                                self.n_traces))
                    if body.get("deduped"):
                        self.deduped += 1
                    break
                if status in (429, 503):
                    # 429: replica backpressure. 503 + Retry-After:
                    # degraded mode — the fleet is recovering a crashed
                    # replica; same response either way, wait and retry
                    # the SAME window (the seq header makes it
                    # idempotent, so nothing double-ingests)
                    if status == 429:
                        self.retry_after_429s += 1
                    else:
                        self.retry_after_503s += 1
                    wait = float(headers.get("Retry-After", 1))
                    self.stop_evt.wait(min(wait, 5.0))
                    continue
                self.errors.append(f"seq {self.seq}: HTTP {status}")
                return
            else:
                return  # stopped mid-retry: this window never ingested
            self.seq += 1
            # absolute schedule: wait only until the next slot; if the
            # response (or a 429 wait) overran it, post again at once
            next_send += self.period_s
            delay = next_send - time.monotonic()
            if delay > 0:
                self.stop_evt.wait(delay)


def _build_fleet(n: int, mode: str, state_root: str,
                 serve_args: Optional[List[str]],
                 verbose: bool) -> FleetManager:
    names = [f"r{i}" for i in range(n)]
    if mode == "subprocess":
        replicas = [ReplicaProcess(
            name, os.path.join(state_root, f"fleet{n}", name),
            serve_args=serve_args or ["--fix", "2"]).start()
            for name in names]
    elif mode == "inproc":
        from traceweaver_tpu.serve import ServeConfig

        # continuous=True mirrors the production serve CLI default
        # (TW_SERVE_CONTINUOUS, on): the dispatcher + in-flight ring
        # drain windows WHILE the generators post, so the steady phase
        # measures a serving tier, not an ingest buffer. The pre-r19
        # config (pump_windows=10**9, no dispatcher) deferred every
        # solve to the final flush — backlog saturated mid-drive and
        # the 429 stalls capped the rung at ~58% of offered load.
        replicas = [InProcReplica(name, ServeConfig(
            fix=2, window_us=WINDOW_US, overlap_us=5e6, ooo_bound_us=1e6,
            verbose=False, continuous=True,
            state_dir=os.path.join(state_root, f"fleet{n}", name)))
            for name in names]
    else:
        raise ValueError(f"unknown fleet campaign mode {mode!r}")
    # subprocess fleets run supervised: the chaos phase kill -9s a
    # loaded replica and the crash supervisor must bring it back
    return FleetManager(replicas, router_port=0, verbose=verbose,
                        supervise=(mode == "subprocess"))


def _aggregate(fleet: FleetManager) -> Dict[str, object]:
    """Fleet-wide conservation ledger from the per-replica stats (each
    live tenant appears on exactly one replica — migration deletes it
    from the source and tombstones the id)."""
    stats = fleet.router.fleet_stats(include_replicas=True)
    agg = dict(ingested_traces=0, ingested_spans=0, traces_emitted=0,
               spans_emitted=0, shed_dropped_windows=0,
               deadletter_windows=0, late_dropped=0, quarantined=0,
               backlog=0, backpressure_429s=0,
               parse_s=0.0, stitch_s=0.0, emit_s=0.0,
               serve_busy_s=0.0, serve_union_s=0.0, serve_inflight=0)
    p99 = {}
    per_tenant = {}
    for name, st in stats["replica_stats"].items():
        if "error" in st:
            raise RuntimeError(f"replica {name} stats: {st['error']}")
        agg["backpressure_429s"] += int(
            st.get("dispatch", {}).get("backpressure_429s", 0))
        # dispatch-ring overlap ledger (ISSUE 19): replicas dispatch
        # independently, so busy/union seconds sum across the fleet
        ring = st.get("ring", {}) or {}
        agg["serve_busy_s"] += float(ring.get("busy_s", 0.0))
        agg["serve_union_s"] += float(ring.get("union_s", 0.0))
        agg["serve_inflight"] = max(agg["serve_inflight"],
                                    int(ring.get("inflight_limit", 0)))
        for tid, ts in st.get("tenants", {}).items():
            c = ts.get("counters", {})
            agg["ingested_traces"] += int(c.get("ingested_traces", 0))
            agg["ingested_spans"] += int(c.get("ingested_spans", 0))
            agg["traces_emitted"] += int(ts.get("traces_emitted", 0))
            agg["spans_emitted"] += int(ts.get("spans_emitted", 0))
            agg["shed_dropped_windows"] += int(
                ts.get("shed_dropped_windows", 0))
            agg["deadletter_windows"] += int(
                ts.get("deadletter_windows", 0))
            agg["late_dropped"] += int(ts.get("late_dropped", 0))
            agg["quarantined"] += int(ts.get("quarantined_windows", 0))
            agg["backlog"] += int(ts.get("backlog", 0))
            agg["parse_s"] += float(ts.get("parse_s", 0.0))
            agg["stitch_s"] += float(ts.get("stitch_s", 0.0))
            agg["emit_s"] += float(ts.get("emit_s", 0.0))
            p99[tid] = float(ts.get("seal_emit_p99_ms", 0.0))
            per_tenant[f"{name}/{tid}"] = dict(
                ingested=int(c.get("ingested_traces", 0)),
                emitted=int(ts.get("traces_emitted", 0)),
                backlog=int(ts.get("backlog", 0)),
                solved_windows=int(ts.get("solved_windows", 0)),
                spilled=int(ts.get("shed_spilled", 0)),
            )
    agg["per_tenant"] = per_tenant
    agg["seal_emit_p99_ms"] = p99
    agg["router"] = stats["router"]
    return agg


def _settle(fleet: FleetManager, timeout_s: float = 60.0) -> Dict:
    """Post-flush quiesce: a replica's continuous dispatcher may still
    be mid-solve when the flush response lands, so poll the aggregate
    until the conservation ledger balances (or stops moving)."""
    deadline = time.monotonic() + timeout_s
    agg = _aggregate(fleet)
    while time.monotonic() < deadline:
        if (agg["traces_emitted"] == agg["ingested_traces"]
                and agg["backlog"] == 0):
            break
        time.sleep(0.25)
        agg = _aggregate(fleet)
    return agg


def _rebalance(fleet: FleetManager, tenant_ids: List[str],
               verbose: bool) -> int:
    """Pre-measurement placement fix: the hash ring can land every
    tenant on one replica (3 ids, 2 replicas — a 3/0 split is a coin
    flip), which would measure a 1-replica fleet twice. Live-migrate
    the hottest tenant from the fullest replica onto each EMPTY one —
    the load-balancing use of the migration machinery."""
    moved = 0
    placement = {name: fleet.replica_tenants(name)
                 for name in sorted(fleet.router.replicas)}
    for name in sorted(placement):
        if placement[name]:
            continue
        donor = max(sorted(placement), key=lambda r: len(placement[r]))
        if len(placement[donor]) < 2:
            break
        # hottest tenant present on the donor (drive rate ∝ 1/(i+1))
        tid = next(t for t in tenant_ids if t in placement[donor])
        fleet.migrate(tid, name)
        placement[donor].remove(tid)
        placement[name] = [tid]
        moved += 1
        if verbose:
            print(f"[fleet-campaign] rebalance: {tid} -> {name}")
    return moved


def _flush_fleet(fleet: FleetManager, n: int) -> None:
    # the fan-out flush crosses every replica; a connection reset here
    # (a replica's listener mid-close from a just-finished restart) is
    # retryable — flush is idempotent, sealing is driven by event time
    last: Optional[BaseException] = None
    for _ in range(3):
        try:
            status, flush = http_json(
                "POST", fleet.base_url + "/api/v1/flush", None,
                timeout=300)
        except (urlerror.URLError, OSError) as e:
            last = e
            time.sleep(0.5)
            continue
        if status != 200:
            raise RuntimeError(f"fleet-{n} flush: HTTP {status} {flush}")
        return
    raise RuntimeError(f"fleet-{n} flush failed: {last}")


def run_fleet_rung(n: int, mode: str, state_root: str, tenants: int,
                   seconds: float, traces_per_post: int,
                   base_period_s: float, serve_args: Optional[List[str]],
                   verbose: bool) -> Dict[str, object]:
    """One campaign rung, two phases on one fresh n-replica fleet:

    - **steady** (measured): closed-loop drive through the router for
      ``seconds`` — ``spans_per_s`` is ACCEPTED spans (200-status
      POSTs) over the drive wall, the wire capacity the 1-vs-N
      comparison is about — followed by a flush + settle that forces
      every accepted span to emit before the phase may end;
    - **chaos** (n >= 2, gated not measured): the generators resume
      (continuing their window sequence) while the hot tenant is
      live-migrated, then — subprocess mode — the replica serving it
      is SIGKILLed mid-post (crash supervisor recovers; acked spans
      ride the ingest WAL) and every replica takes a rolling restart;
      a final flush + settle feeds the rung-wide zero-loss gate, so
      the failover machinery must be lossless under live load even
      though its wall cost (full process restarts) stays out of the
      throughput figure."""
    fleet = _build_fleet(n, mode, state_root, serve_args, verbose)
    tenant_ids = [f"ten{i}" for i in range(tenants)]

    def mk_drives(stop_evt: threading.Event,
                  seqs: Dict[str, int]) -> List[_TenantDrive]:
        return [_TenantDrive(fleet.base_url, tid,
                             period_s=base_period_s * (i + 1),
                             n_traces=traces_per_post, stop_evt=stop_evt,
                             start_seq=seqs.get(tid, 0))
                for i, tid in enumerate(tenant_ids)]

    def drain_drives(drives: List[_TenantDrive]) -> None:
        for d in drives:
            d.join(timeout=130.0)
        errors = [e for d in drives for e in d.errors]
        if errors:
            raise RuntimeError(f"fleet-{n} drive errors: {errors[:5]}")

    wall_t0 = time.monotonic()
    migrated = restarted = rebalanced = killed = 0
    all_drives: List[_TenantDrive] = []
    try:
        # -- warmup (untimed): first-contact EM + XLA compiles ------------
        # the steady figure is a steady-state claim: the cold solves the
        # first windows trigger (two-pass EM init + the per-bucket XLA
        # compiles) are startup cost, exactly like the cpu campaign's
        # warmup rounds — drive briefly, flush + settle so the
        # continuous dispatchers enter the measured phase warm, and fix
        # tenant placement before measurement (the pre-r19 mid-drive
        # rebalance put a migration wall inside the throughput figure)
        stop_w = threading.Event()
        drives_w = mk_drives(stop_w, {})
        all_drives += drives_w
        for d in drives_w:
            d.start()
        stop_w.wait(max(1.0, min(3.0, seconds / 4)))
        stop_w.set()
        drain_drives(drives_w)
        _flush_fleet(fleet, n)
        _settle(fleet)
        if n >= 2:
            rebalanced = _rebalance(fleet, tenant_ids, verbose)
        # warmup windows sat sealed until the flush above, so their
        # seal→emit samples measure the flush wait, not the drain —
        # start the p99 window fresh so the SLO gate sees steady only
        for rep in fleet.replicas.values():
            http_json("POST", rep.base_url + "/api/v1/reset_latency_window",
                      None, timeout=30)

        # -- steady phase (the measured one) ------------------------------
        t0 = time.monotonic()
        stop_a = threading.Event()
        drives_a = mk_drives(stop_a, {d.tenant: d.seq for d in drives_w})
        all_drives += drives_a
        for d in drives_a:
            d.start()
        while time.monotonic() < t0 + seconds:
            time.sleep(0.05)
        stop_a.set()
        drain_drives(drives_a)
        # the wire throughput figure: spans the closed-loop generators
        # got a 200 for, over the drive wall (including the last POSTs'
        # response tails). Acceptance is what adding replicas scales on
        # any host — emitted-spans/s is bounded by total solve cores,
        # which a 1-core CI host pins to the same ceiling for every N.
        # The flush + settle below still forces every accepted span to
        # EMIT exactly once before the rung may return (the zero-loss
        # gate), so acceptance is never credit for vapor.
        drive_wall_s = time.monotonic() - t0
        steady_spans = sum(d.traces for d in drives_a) * SPANS_PER_TRACE
        _flush_fleet(fleet, n)
        agg = _settle(fleet)
        steady_wall_s = time.monotonic() - t0

        # -- chaos phase (gated, unmeasured) ------------------------------
        chaos_t0 = time.monotonic()
        if n >= 2:
            stop_b = threading.Event()
            drives_b = mk_drives(stop_b, {d.tenant: d.seq
                                          for d in drives_a})
            all_drives += drives_b
            for d in drives_b:
                d.start()
            time.sleep(0.3)
            hot = tenant_ids[0]
            src = fleet.router.owner(hot)
            dst = next(name for name in sorted(fleet.router.replicas)
                       if name != src)
            fleet.migrate(hot, dst)
            migrated += 1
            if mode == "subprocess":
                # kill -9 the replica now serving the hot tenant while
                # its generator is mid-post: no drain, no checkpoint, no
                # goodbye. The crash supervisor must detect the corpse,
                # recover it (respawn + WAL replay, or survivor
                # failover from the dead disk), and the rung-wide
                # conservation gate below must still balance EXACTLY —
                # acked spans survive the kill or the campaign fails.
                victim = fleet.router.owner(hot)
                vrep = fleet.replicas[victim]
                vrep.proc.kill()
                killed += 1
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline:
                    c = fleet.router.counters
                    if c.get("respawns", 0) + c.get("failovers", 0) >= 1:
                        break
                    time.sleep(0.2)
                else:
                    raise RuntimeError(
                        f"fleet-{n} chaos: supervisor never recovered "
                        f"{victim} after kill -9")
                fleet.rolling_restart()
                restarted = len(fleet.replicas)
            # post-chaos burst: the fleet must still be ingesting after
            # the migration + restarts, not merely draining
            time.sleep(max(0.5, seconds / 8))
            stop_b.set()
            drain_drives(drives_b)
            _flush_fleet(fleet, n)
            agg = _settle(fleet)
        chaos_wall_s = time.monotonic() - chaos_t0
        wall_s = time.monotonic() - wall_t0
    finally:
        fleet.stop()

    # the zero-loss gate: a lossy fleet does not get an artifact
    lost = agg["ingested_traces"] - agg["traces_emitted"]
    if lost != 0 or agg["shed_dropped_windows"] or \
            agg["deadletter_windows"] or agg["late_dropped"] or \
            agg["backlog"]:
        raise RuntimeError(
            f"fleet-{n} lost traces: ingested {agg['ingested_traces']} "
            f"emitted {agg['traces_emitted']} (delta {lost}), dropped "
            f"windows {agg['shed_dropped_windows']}, deadletter "
            f"{agg['deadletter_windows']}, late_dropped "
            f"{agg['late_dropped']}, backlog {agg['backlog']}; "
            f"per-tenant {json.dumps(agg['per_tenant'], sort_keys=True)}")
    posted = sum(d.traces for d in all_drives)
    if posted != agg["ingested_traces"]:
        raise RuntimeError(
            f"fleet-{n} wire loss: generators got 200 for {posted} "
            f"traces, replicas ingested {agg['ingested_traces']}")
    e2e_pct = (100.0 * agg["traces_emitted"] / agg["ingested_traces"]
               if agg["ingested_traces"] else 0.0)
    spans_per_s = (steady_spans / drive_wall_s
                   if drive_wall_s > 0 else 0.0)
    return dict(
        rung=f"fleet-{n}",
        manifest=dict(
            spans=int(agg["ingested_spans"]),
            traces=int(agg["ingested_traces"]),
            tenants=tenants, replicas=n, mode=mode,
            posts=sum(d.posts for d in all_drives),
            regime_mix={},
        ),
        steady=dict(
            spans_per_s=round(spans_per_s, 2),
            backend_compiles=0,
            aot_misses=[],
            quarantined=int(agg["quarantined"]),
        ),
        accuracy=dict(e2e_pct=round(e2e_pct, 3), per_regime={}),
        fleet=dict(
            wall_s=round(wall_s, 3),
            drive_wall_s=round(drive_wall_s, 3),
            steady_wall_s=round(steady_wall_s, 3),
            chaos_wall_s=round(chaos_wall_s, 3),
            steady_accepted_spans=steady_spans,
            seal_emit_p99_ms=agg["seal_emit_p99_ms"],
            router=agg["router"],
            migrations=migrated + rebalanced,
            rebalance_migrations=rebalanced,
            replicas_restarted=restarted,
            backpressure_429s=int(agg["backpressure_429s"]),
            generator_429s=sum(d.retry_after_429s for d in all_drives),
            generator_503s=sum(d.retry_after_503s for d in all_drives),
            deduped_windows=sum(d.deduped for d in all_drives),
            crash_kills=killed,
            respawns=int(agg["router"]["counters"].get("respawns", 0)),
            crash_failovers=int(
                agg["router"]["counters"].get("failovers", 0)),
            reset_midbody=int(
                agg["router"]["counters"].get("reset_midbody", 0)),
            parse_s=round(float(agg["parse_s"]), 4),
            stitch_s=round(float(agg["stitch_s"]), 4),
            emit_s=round(float(agg["emit_s"]), 4),
            serve_inflight=int(agg["serve_inflight"]),
            serve_overlap_pct=round(
                max(0.0, 100.0 * (1.0 - float(agg["serve_union_s"])
                                  / float(agg["serve_busy_s"])))
                if float(agg["serve_busy_s"]) > 0 else 0.0, 2),
            zero_loss=True,
        ),
    )


def run_fleet_campaign(state_root: str,
                       replica_counts: Tuple[int, ...] = (1, 2),
                       tenants: int = 3,
                       seconds: float = 6.0,
                       traces_per_post: int = 6,
                       base_period_s: float = 0.05,
                       mode: str = "subprocess",
                       name: str = "fleet-wire",
                       out: Optional[str] = None,
                       serve_args: Optional[List[str]] = None,
                       verbose: bool = False) -> Dict[str, object]:
    """Drive the full campaign ladder (one rung per replica count) and
    return — optionally write — the gated ``CAMPAIGN_*`` artifact."""
    plan = dict(
        mode=mode, tenants=tenants, seconds=seconds,
        traces_per_post=traces_per_post, base_period_s=base_period_s,
        replica_counts=list(replica_counts),
        rungs=[dict(name=f"fleet-{n}") for n in replica_counts],
    )
    ledger.record_start(name, plan)
    t0 = time.monotonic()
    rungs = []
    for n in replica_counts:
        rung = run_fleet_rung(
            n, mode, state_root, tenants, seconds, traces_per_post,
            base_period_s, serve_args, verbose)
        ledger.record_rung(name, rung["rung"],
                           rung["steady"]["spans_per_s"],
                           rung["accuracy"]["e2e_pct"],
                           rung["steady"]["backend_compiles"],
                           len(rung["steady"]["aot_misses"]))
        if verbose:
            print(f"[fleet-campaign] {rung['rung']}: "
                  f"{rung['steady']['spans_per_s']:.1f} spans/s, "
                  f"e2e {rung['accuracy']['e2e_pct']:.1f}%, "
                  f"migrations {rung['fleet']['migrations']}, "
                  f"restarts {rung['fleet']['replicas_restarted']}")
        rungs.append(rung)
    wall_s = time.monotonic() - t0
    artifact = ledger.make_artifact(
        name=name, plan=plan, backend="wire", devices_visible=0,
        rungs=rungs, scrape=ledger.scrape_snapshot(), wall_s=wall_s)
    if out:
        ledger.write_artifact(out, artifact)
    ledger.record_finish(name, wall_s, out)
    return artifact
