"""Replica fleet tier: tenant-sharded scale-out serving.

One router process (:mod:`.router`) consistent-hashes tenant ids onto N
shared-nothing replica serve processes, health-checks them, breaks
circuits, retries in-flight POSTs onto the next replica in ring order,
and coordinates LIVE tenant migration (drain → checkpoint transfer →
resume, byte-identical sink output, zero span loss). The manager
(:mod:`.manager`) owns replica lifecycle — spawn, migrate, rolling
restart gated on ``/readyz``. The campaign runner (:mod:`.campaign`)
drives the whole thing through the real HTTP wire and emits the gated
``CAMPAIGN_*`` artifact the PR-15 ledger machinery reviews.

Processes stay in their lanes: the ROUTER process never imports JAX —
mesh, AOT warmup, and the persistent compile cache belong to each
replica's own interpreter (the ``cli serve`` bring-up). That is what
makes N replicas scale: N independent runtimes, not N threads behind
one GIL.

CLI (``python -m traceweaver_tpu.runtime.cli fleet ...``)::

    fleet serve    --replicas N --port P --state-dir D [serve flags...]
    fleet campaign --replicas 1,2 --seconds S --out CAMPAIGN_fleet.json

docs/SERVING.md (architecture + runbook), docs/CAMPAIGN.md (artifact).
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import List

from traceweaver_tpu.fleet_serve.manager import (
    FleetManager,
    InProcReplica,
    ReplicaError,
    ReplicaProcess,
)
from traceweaver_tpu.fleet_serve.router import (
    CircuitBreaker,
    FleetRouter,
    HashRing,
    ReplicaRef,
)

__all__ = [
    "CircuitBreaker",
    "FleetManager",
    "FleetRouter",
    "HashRing",
    "InProcReplica",
    "ReplicaError",
    "ReplicaProcess",
    "ReplicaRef",
    "main",
]


def _build_parser() -> argparse.ArgumentParser:
    from traceweaver_tpu.runtime import knobs

    p = argparse.ArgumentParser(
        prog="python -m traceweaver_tpu.runtime.cli fleet",
        description="Tenant-sharded replica fleet: router + N serve "
                    "replicas with live migration and rolling restarts "
                    "(docs/SERVING.md).")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser(
        "serve", help="spawn N replica serve processes behind one router "
                      "and serve until SIGTERM/SIGINT")
    s.add_argument("--replicas", type=int,
                   default=knobs.get_int("TW_FLEET_REPLICAS"))
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int,
                   default=knobs.get_int("TW_FLEET_ROUTER_PORT"),
                   help="router port (0 = ephemeral)")
    s.add_argument("--state-dir", required=True,
                   help="fleet state root; replica i keeps its tenants "
                        "under <state-dir>/r<i>/")
    s.add_argument("serve_args", nargs="*",
                   help="flags passed through to every replica's "
                        "`cli serve` (e.g. --fix 2 --window_s 60)")

    c = sub.add_parser(
        "campaign", help="wire-level load campaign: 1 vs N replicas "
                         "through the real HTTP path, gated artifact out")
    c.add_argument("--replicas", default="1,2",
                   help="comma-separated rung ladder (default 1,2)")
    c.add_argument("--tenants", type=int, default=3)
    c.add_argument("--seconds", type=float, default=6.0,
                   help="drive seconds per rung")
    c.add_argument("--traces-per-post", type=int, default=6)
    c.add_argument("--base-period-s", type=float, default=0.05,
                   help="hot tenant's closed-loop pacing; tenant i runs "
                        "at (i+1)x this period (heavy tail)")
    c.add_argument("--mode", choices=("subprocess", "inproc"),
                   default="subprocess",
                   help="subprocess = real replica processes (the "
                        "committed-artifact mode); inproc = same wire "
                        "path in one process (the fast test mode)")
    c.add_argument("--state-dir", required=True)
    c.add_argument("--out", default=None,
                   help="write the CAMPAIGN_*.json artifact here")
    c.add_argument("--quiet", action="store_true")
    return p


def _serve_main(args) -> int:
    replicas = []
    try:
        for i in range(args.replicas):
            replicas.append(ReplicaProcess(
                f"r{i}", os.path.join(args.state_dir, f"r{i}"),
                serve_args=list(args.serve_args)).start())
        fleet = FleetManager(replicas, router_port=args.port)
    except ReplicaError as e:
        for r in replicas:
            r.stop(timeout_s=10.0)
        print(f"[fleet] startup failed: {e}")
        return 1
    print(f"[fleet] router listening on {fleet.base_url} "
          f"({args.replicas} replicas: "
          + ", ".join(r.base_url for r in replicas) + ")")
    stop = threading.Event()

    def _signal(signum, _frame):
        print(f"[fleet] signal {signum}: stopping fleet")
        stop.set()

    signal.signal(signal.SIGTERM, _signal)
    signal.signal(signal.SIGINT, _signal)
    stop.wait()
    fleet.stop()
    print(f"[fleet] stopped: {args.replicas} replicas drained")
    return 0


def _campaign_main(args) -> int:
    from traceweaver_tpu.fleet_serve.campaign import run_fleet_campaign

    counts = tuple(int(x) for x in str(args.replicas).split(",") if x)
    artifact = run_fleet_campaign(
        state_root=args.state_dir,
        replica_counts=counts,
        tenants=args.tenants,
        seconds=args.seconds,
        traces_per_post=args.traces_per_post,
        base_period_s=args.base_period_s,
        mode=args.mode,
        out=args.out,
        verbose=not args.quiet,
    )
    if not args.quiet:
        from traceweaver_tpu.campaign.compare import format_report

        print(format_report(artifact))
    if args.out:
        print(f"[fleet-campaign] artifact: {args.out}")
    return 0


def main(argv: List[str]) -> int:
    """``cli fleet`` entry: pure host process (no JAX import here — the
    replicas own their backends)."""
    args = _build_parser().parse_args(argv)
    if args.cmd == "serve":
        return _serve_main(args)
    return _campaign_main(args)
