"""Tenant-sharded HTTP router: the fleet tier's front door.

Stdlib only, and deliberately JAX-free — the router is a pure host
process; every device decision (mesh, AOT warmup, persistent compile
cache) belongs to the replicas it fronts. One router process consistent-
hashes tenant ids onto N replica serve processes (each a full
:mod:`traceweaver_tpu.serve` server, shared-nothing: its own state dir,
its own mesh) and owns the fleet's availability story:

- **consistent hashing** (:class:`HashRing`): tenant -> replica via
  SHA-1 points with ``TW_FLEET_VNODES`` virtual nodes per replica, so
  adding/removing a replica remaps ~1/N of the tenants, not all of
  them. The ring also defines each tenant's *preference order* — the
  retry-on-next-replica sequence.
- **health-checked routing**: a background loop probes each replica's
  ``/readyz`` every ``TW_FLEET_HEALTH_S``; a draining or cold replica
  (503 — serve flips readiness the instant SIGTERM lands) drops out of
  routing before its socket does.
- **circuit breaking** (:class:`CircuitBreaker`): ``TW_FLEET_BREAKER_
  FAILS`` consecutive proxy failures open a replica's circuit for
  ``TW_FLEET_BREAKER_COOLDOWN_S``; an open circuit is skipped exactly
  like a failed health check.
- **counted retries**: a failed in-flight POST moves to the next
  replica in ring order, at most ``TW_FLEET_RETRY_MAX`` extra attempts,
  every hop counted (``tw_fleet_router_total{outcome=...}``) — and a
  tenant POST that lands on a fallback replica PINS the tenant there so
  its stream stays on one replica. The candidate list is re-resolved
  before EVERY attempt (never snapshotted): a crash failover or
  supervisor respawn landing mid-retry re-routes the very next hop. A
  connection reset *after* the request was accepted (replica killed
  mid-body) is classified separately (``outcome="reset_midbody"``) —
  that request may be half-applied on the dead replica, and only the
  WAL's client-seq dedup makes the retry that follows safe.
- **migration pins**: live tenant migration (:meth:`FleetRouter.
  migrate`) holds the tenant's requests, runs the replica-side
  ``migrate_out``/``migrate_in`` pair, then pins the tenant to its new
  home. A 410 from a replica ("tenant migrated out") re-resolves the
  pin instead of failing the client.

Router endpoints (everything else proxies to the owning replica)::

    GET  /healthz               router liveness + replica table
    GET  /readyz                200 while >=1 replica is routable
    GET  /metrics               router-process Prometheus exposition
    GET  /api/v1/stats          per-replica /api/v1/stats + router view
    GET  /api/v1/tenants        union of replica tenant lists
    POST /api/v1/flush          fan-out seal+solve on every replica
    GET  /api/v1/fleet/stats    ring, pins, breaker/health states
    POST /api/v1/fleet/migrate  {"tenant": ..., "to": "<replica>"}
"""

from __future__ import annotations

import bisect
import contextlib
import hashlib
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest
from urllib.parse import urlparse

from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs

_TENANT_PATH = re.compile(r"^/api/v1/tenants/([^/]+)(/.*)?$")

#: same runaway-POST cap as the replica front door
MAX_BODY_BYTES = 64 << 20

_OBS_ROUTER = _get_registry().counter(
    "tw_fleet_router_total",
    "router request outcomes (proxied/rerouted/retried/failed/held/"
    "rejected) and fleet operations (migrations/restarts)",
    labels=("outcome",))
_OBS_READY = _get_registry().gauge(
    "tw_fleet_replicas_ready",
    "replicas currently routable (ready, not draining, breaker closed)")


def _stable_hash(key: str) -> int:
    """Process-stable 64-bit hash (Python's ``hash()`` is salted per
    process — useless for a ring two processes must agree on)."""
    return int.from_bytes(hashlib.sha1(key.encode("utf-8")).digest()[:8],
                          "big")


def http_json(method: str, url: str, payload: Optional[dict] = None,
              timeout: float = 30.0) -> Tuple[int, dict]:
    """One JSON request/response round trip (4xx/5xx return, never
    raise — connection-level failures do raise ``URLError``/``OSError``,
    the retry/breaker signal)."""
    data = (json.dumps(payload).encode("utf-8")
            if payload is not None else None)
    headers = {"Content-Type": "application/json"} if data else {}
    req = urlrequest.Request(url, data=data, method=method,
                             headers=headers)
    try:
        with urlrequest.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urlerror.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except (ValueError, OSError):
            body = {}
        return e.code, body


def _http_raw(method: str, url: str, body: Optional[bytes],
              content_type: Optional[str], timeout: float,
              extra: Optional[Dict[str, str]] = None,
              ) -> Tuple[int, Dict[str, str], bytes]:
    """Proxy-side round trip preserving bytes and headers. HTTP errors
    are responses (forwarded as-is); only connection-level failures
    raise."""
    headers = dict(extra or {})
    if content_type:
        headers["Content-Type"] = content_type
    req = urlrequest.Request(url, data=body, method=method,
                             headers=headers)
    try:
        with urlrequest.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urlerror.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


class HashRing:
    """Consistent hash ring over replica names (SHA-1 points,
    ``vnodes`` virtual nodes per replica). ``preference(key)`` walks the
    ring clockwise from the key's point and yields each replica once —
    element 0 is the owner, the rest are the failover order."""

    def __init__(self, names: List[str],
                 vnodes: Optional[int] = None) -> None:
        self.vnodes = (vnodes if vnodes is not None
                       else knobs.get_int("TW_FLEET_VNODES"))
        self.names = sorted(set(names))
        self._points = sorted(
            (_stable_hash(f"{name}#{v}"), name)
            for name in self.names for v in range(self.vnodes))
        self._keys = [p[0] for p in self._points]

    def preference(self, key: str) -> List[str]:
        if not self._points:
            return []
        out: List[str] = []
        seen = set()
        start = bisect.bisect_right(self._keys, _stable_hash(key))
        for j in range(len(self._points)):
            name = self._points[(start + j) % len(self._points)][1]
            if name not in seen:
                seen.add(name)
                out.append(name)
                if len(out) == len(self.names):
                    break
        return out

    def lookup(self, key: str) -> str:
        return self.preference(key)[0]


class CircuitBreaker:
    """Consecutive-failure breaker: ``fail_max`` straight failures open
    the circuit for ``cooldown_s``; any success closes it."""

    def __init__(self, fail_max: Optional[int] = None,
                 cooldown_s: Optional[float] = None) -> None:
        self.fail_max = (fail_max if fail_max is not None
                         else knobs.get_int("TW_FLEET_BREAKER_FAILS"))
        self.cooldown_s = (cooldown_s if cooldown_s is not None else
                           knobs.get_float("TW_FLEET_BREAKER_COOLDOWN_S"))
        self.fails = 0
        self.opened = 0          # lifetime open transitions (stats)
        self._open_until = 0.0

    def record(self, ok: bool) -> None:
        if ok:
            self.fails = 0
            self._open_until = 0.0
            return
        self.fails += 1
        if self.fails >= self.fail_max:
            self._open_until = time.monotonic() + self.cooldown_s
            self.opened += 1

    @property
    def open(self) -> bool:
        return time.monotonic() < self._open_until


class ReplicaRef:
    """The router's view of one replica process."""

    def __init__(self, name: str, base_url: str) -> None:
        self.name = name
        self.base_url = base_url.rstrip("/")
        # optimistic until the first health probe answers — a fleet
        # boots routable, and the probe loop corrects within one period
        self.ready = True
        self.draining = False     # set during rolling restarts
        self.breaker = CircuitBreaker()
        self.requests = 0
        self.failures = 0

    @property
    def routable(self) -> bool:
        return self.ready and not self.draining and not self.breaker.open

    def view(self) -> Dict[str, object]:
        return dict(name=self.name, base_url=self.base_url,
                    ready=self.ready, draining=self.draining,
                    breaker_open=self.breaker.open,
                    breaker_opened=self.breaker.opened,
                    requests=self.requests, failures=self.failures)


class RouterHandler(BaseHTTPRequestHandler):
    """Routes requests onto the owning :class:`FleetRouter`."""

    server_version = "traceweaver-fleet-router/1.0"
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> "FleetRouter":
        return self.server  # type: ignore[return-value]

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by default
        if self.router.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, sort_keys=True).encode("utf-8")
        self._reply_bytes(code, body, "application/json", headers)

    def _reply_bytes(self, code: int, body: bytes, content_type: str,
                     headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str,
               headers: Optional[dict] = None) -> None:
        self._reply(code, {"error": message}, headers)

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            self._error(400, "bad Content-Length")
            return None
        if length > MAX_BODY_BYTES:
            self._error(413, f"body exceeds {MAX_BODY_BYTES} bytes")
            return None
        return self.rfile.read(length) if length else b""

    # -- verbs ------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        r = self.router
        path = urlparse(self.path).path
        m = _TENANT_PATH.match(path)
        try:
            if m:
                body = self._read_body()
                if body is None:
                    return
                self._proxy_tenant("POST", m.group(1), body)
            elif path == "/api/v1/flush":
                self._reply(200, r.flush_all())
            elif path == "/api/v1/fleet/migrate":
                body = self._read_body()
                if body is None:
                    return
                try:
                    req = json.loads(body or b"{}")
                except json.JSONDecodeError as e:
                    self._error(400, f"invalid JSON: {e}")
                    return
                tenant, dst = req.get("tenant"), req.get("to")
                if not tenant or not dst:
                    self._error(400, 'expected {"tenant": ..., "to": ...}')
                    return
                if dst not in r.replicas:
                    self._error(404, f"no such replica {dst!r}")
                    return
                self._reply(200, r.migrate(tenant, dst))
            else:
                self._error(404, f"no such endpoint: POST {path}")
        except (urlerror.URLError, OSError, RuntimeError) as e:
            self._error(502, f"{type(e).__name__}: {e}")

    def do_GET(self) -> None:  # noqa: N802
        r = self.router
        path = urlparse(self.path).path
        try:
            if path == "/healthz":
                self._reply(200, {"ok": True,
                                  "replicas": [ref.view()
                                               for ref in r.refs()]})
            elif path == "/readyz":
                n = sum(ref.routable for ref in r.refs())
                self._reply(200 if n else 503,
                            {"ready": n > 0, "routable_replicas": n})
            elif path == "/metrics":
                from traceweaver_tpu.obs.exposition import (
                    CONTENT_TYPE,
                    render_metrics,
                )

                self._reply_bytes(200,
                                  render_metrics().encode("utf-8"),
                                  CONTENT_TYPE)
            elif path == "/api/v1/stats":
                self._reply(200, r.fleet_stats(include_replicas=True))
            elif path == "/api/v1/tenants":
                self._reply(200, {"tenants": r.tenant_union()})
            elif path == "/api/v1/fleet/stats":
                self._reply(200, r.fleet_stats())
            else:
                m = _TENANT_PATH.match(path)
                if m:
                    self._proxy_tenant("GET", m.group(1), None)
                else:
                    self._error(404, f"no such endpoint: GET {path}")
        except (urlerror.URLError, OSError, RuntimeError) as e:
            self._error(502, f"{type(e).__name__}: {e}")

    # -- the proxy path ---------------------------------------------------
    def _proxy_tenant(self, method: str, tenant: str,
                      body: Optional[bytes]) -> None:
        """Forward one tenant request to its replica, walking the ring's
        preference order on connection failure (POSTs pin the tenant to
        a fallback replica so its stream stays in one place) and
        re-resolving the pin once on a 410 (migration landed between
        routing and dispatch)."""
        r = self.router
        target = self.path  # full path incl. query, verbatim
        content_type = self.headers.get("Content-Type")
        client_seq = self.headers.get("X-TW-Seq")
        extra = {"X-TW-Seq": client_seq} if client_seq else None
        r.wait_routable(tenant)
        budget = 1 + (r.retry_max if method == "POST" else 1)
        attempts_left = budget
        tried: set = set()
        saw_410 = False
        saw_candidates = False
        last_err: Optional[Exception] = None
        while attempts_left > 0:
            # re-resolve the ring EVERY attempt, not once per round: a
            # crash-failover or respawn landing mid-retry changes both
            # the routable set and the pin table, and a stale snapshot
            # would keep hammering a corpse while the tenant's new home
            # sits routable one lookup away
            cands = r.candidates(tenant)
            ref = next((c for c in cands if c.name not in tried), None)
            if ref is None:
                break
            saw_candidates = True
            attempts_left -= 1
            try:
                status, headers, payload = _http_raw(
                    method, ref.base_url + target, body, content_type,
                    timeout=r.proxy_timeout_s, extra=extra)
            except (urlerror.URLError, OSError) as e:
                reason = getattr(e, "reason", e)
                if isinstance(reason, (ConnectionResetError,
                                       BrokenPipeError)):
                    # the replica died AFTER accepting the connection
                    # (kill -9 mid-body) — distinct from never-reachable
                    # because the request may be half-applied; the WAL
                    # seq dedup is what makes the retry safe
                    r.bump("reset_midbody")
                ref.breaker.record(False)
                ref.failures += 1
                last_err = e
                tried.add(ref.name)
                r.bump("retried")
                if r.crash_grace_s > 0:
                    # a crash supervisor is attached: give it one
                    # detection period to notice the corpse, strike it
                    # from routing, and HOLD its tenants — then resolve
                    # from scratch. Falling straight through to the next
                    # ring candidate here would auto-create an empty
                    # forked twin of a tenant whose real state sits on
                    # the crashed disk, waiting to be recovered.
                    time.sleep(r.crash_grace_s)
                    r.wait_routable(tenant)
                    tried.clear()
                continue
            ref.breaker.record(True)
            ref.requests += 1
            if status == 410 and not saw_410:
                # the tenant migrated off this replica mid-flight: the
                # pin table already knows its new home — re-resolve with
                # a fresh budget (a second 410 forwards to the client)
                saw_410 = True
                tried.clear()
                tried.add(ref.name)
                attempts_left = budget
                r.bump("rerouted")
                continue
            if tried and method == "POST":
                # landed on a fallback replica: pin the tenant there
                # so its stream stays on ONE replica
                r.pin(tenant, ref.name)
                r.bump("rerouted")
            r.bump("proxied")
            fwd = {}
            if "Retry-After" in headers:
                fwd["Retry-After"] = headers["Retry-After"]
            self._reply_bytes(
                status, payload,
                headers.get("Content-Type", "application/json"), fwd)
            return
        if not saw_candidates:
            # degraded mode: nothing routable (replica down, supervisor
            # recovering it) — tell the client when to come back
            r.bump("rejected")
            self._error(503, "no routable replicas",
                        {"Retry-After": "1"})
            return
        r.bump("failed")
        if last_err is not None:
            # every attempt died at the connection level: the fleet is
            # recovering, not wrong — retryable, with a comeback hint
            self._error(503, f"all replicas failed for tenant {tenant!r}"
                             f": {type(last_err).__name__}: {last_err}",
                        {"Retry-After": "1"})
            return
        self._error(502, f"all replicas failed for tenant {tenant!r}"
                         " (migration loop)")


class FleetRouter(ThreadingHTTPServer):
    """The fleet front door: hash ring + pins + health loop + breaker
    state, bound to a :class:`RouterHandler` pool. ``start()`` spins the
    serve and health threads and returns self; ``stop()`` tears both
    down."""

    daemon_threads = True

    def __init__(self, replicas: Dict[str, str], host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 verbose: bool = False) -> None:
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas: Dict[str, ReplicaRef] = {
            name: ReplicaRef(name, url)
            for name, url in sorted(replicas.items())}
        self.ring = HashRing(list(self.replicas))
        self.pins: Dict[str, str] = {}
        self.verbose = verbose
        self.retry_max = knobs.get_int("TW_FLEET_RETRY_MAX")
        self.proxy_timeout_s = knobs.get_float("TW_FLEET_PROXY_TIMEOUT_S")
        self.health_period_s = knobs.get_float("TW_FLEET_HEALTH_S")
        self.migrate_timeout_s = knobs.get_float(
            "TW_FLEET_MIGRATE_TIMEOUT_S")
        self.counters: Dict[str, int] = dict(
            proxied=0, rerouted=0, retried=0, failed=0, rejected=0,
            held=0, migrations=0, restarts=0, reset_midbody=0,
            failovers=0, respawns=0)
        # >0 only when a crash supervisor is attached (FleetManager
        # supervise=True): how long a failed proxy attempt yields before
        # re-resolving, so crash detection + tenant holds win the race
        # against the retry
        self.crash_grace_s = 0.0
        self._lock = threading.RLock()
        self._migrating: Dict[str, threading.Event] = {}
        self._stop = threading.Event()
        self._own_threads: List[threading.Thread] = []
        if port is None:
            port = knobs.get_int("TW_FLEET_ROUTER_PORT")
        super().__init__((host, port), RouterHandler)

    # -- lifecycle --------------------------------------------------------
    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def base_url(self) -> str:
        host = self.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "FleetRouter":
        for name, fn in (("tw-fleet-router", self.serve_forever),
                         ("tw-fleet-health", self._health_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._own_threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.shutdown()
        self.server_close()

    # -- routing state ----------------------------------------------------
    def refs(self) -> List[ReplicaRef]:
        with self._lock:
            return list(self.replicas.values())

    def bump(self, outcome: str, n: int = 1) -> None:
        with self._lock:
            self.counters[outcome] = self.counters.get(outcome, 0) + n
        _OBS_ROUTER.inc(n, outcome=outcome)

    def candidates(self, tenant: str) -> List[ReplicaRef]:
        """Routable replicas for a tenant in preference order: its pin
        (if any) first, then the hash ring walk."""
        with self._lock:
            order = self.ring.preference(tenant)
            pin = self.pins.get(tenant)
            if pin and pin in self.replicas:
                order = [pin] + [n for n in order if n != pin]
            return [self.replicas[n] for n in order
                    if self.replicas[n].routable]

    def pin(self, tenant: str, replica: str) -> None:
        with self._lock:
            self.pins[tenant] = replica

    def owner(self, tenant: str) -> str:
        """The replica currently responsible for a tenant (pin wins,
        else the ring)."""
        with self._lock:
            return self.pins.get(tenant) or self.ring.lookup(tenant)

    def set_draining(self, name: str, flag: bool) -> None:
        with self._lock:
            self.replicas[name].draining = flag

    def update_replica(self, name: str, base_url: str) -> None:
        """Point a replica slot at a restarted process (new ephemeral
        port); resets its breaker — the fresh process owes no failures."""
        with self._lock:
            ref = self.replicas[name]
            ref.base_url = base_url.rstrip("/")
            ref.breaker = CircuitBreaker()
            ref.ready = True

    # -- migration --------------------------------------------------------
    @contextlib.contextmanager
    def hold_tenant(self, tenant: str):
        """Hold (don't fail) the tenant's requests while its state is in
        flight between replicas; released (and counted) on exit."""
        ev = threading.Event()
        with self._lock:
            self._migrating[tenant] = ev
        try:
            yield
        finally:
            with self._lock:
                self._migrating.pop(tenant, None)
            ev.set()

    def wait_routable(self, tenant: str) -> bool:
        """Block while the tenant's state is in flight between replicas
        (migration or crash recovery); True if a hold was waited on —
        the caller's routing snapshot is stale and must re-resolve."""
        with self._lock:
            ev = self._migrating.get(tenant)
        if ev is None:
            return False
        self.bump("held")
        ev.wait(timeout=self.migrate_timeout_s)
        return True

    def migrate(self, tenant: str, dst: str) -> Dict[str, object]:
        """Live tenant migration, router-coordinated: hold the tenant's
        requests, ``migrate_out`` on its current replica, ``migrate_in``
        on ``dst`` (checkpoint + sink bytes, CRC-verified at both ends),
        pin the tenant to its new home, release. Zero span loss: open
        windows ride the checkpoint, requests in the hold window proceed
        against the new home."""
        src = self.owner(tenant)
        if src == dst:
            return dict(tenant=tenant, src=src, dst=dst, noop=True)
        with self._lock:
            src_url = self.replicas[src].base_url
            dst_url = self.replicas[dst].base_url
        t0 = time.monotonic()
        with self.hold_tenant(tenant):
            status, out = http_json(
                "POST", f"{src_url}/api/v1/tenants/{tenant}/migrate_out",
                {}, timeout=self.migrate_timeout_s)
            if status != 200:
                raise RuntimeError(
                    f"migrate_out {tenant!r} on {src}: HTTP {status} "
                    f"{out.get('error', '')}")
            status, res = http_json(
                "POST", f"{dst_url}/api/v1/tenants/{tenant}/migrate_in",
                out, timeout=self.migrate_timeout_s)
            if status != 200:
                raise RuntimeError(
                    f"migrate_in {tenant!r} on {dst}: HTTP {status} "
                    f"{res.get('error', '')} — checkpoint bytes remain "
                    f"on {src}'s disk ({src_url})")
            self.pin(tenant, dst)
        self.bump("migrations")
        wall_s = time.monotonic() - t0
        _events.emit("fleet", "migrate", tenant=tenant, src=src, dst=dst,
                     wall_s=round(wall_s, 3),
                     backlog=res.get("backlog"))
        out = dict(res)
        out.update(tenant=tenant, src=src, dst=dst,
                   wall_s=round(wall_s, 3))
        return out

    # -- aggregate views --------------------------------------------------
    def fleet_stats(self, include_replicas: bool = False) -> Dict:
        with self._lock:
            out: Dict[str, object] = dict(
                router=dict(counters=dict(self.counters),
                            pins=dict(self.pins),
                            vnodes=self.ring.vnodes,
                            retry_max=self.retry_max),
                replicas={name: ref.view()
                          for name, ref in self.replicas.items()},
            )
            refs = list(self.replicas.items())
        if include_replicas:
            per_replica = {}
            for name, ref in refs:
                try:
                    status, st = http_json(
                        "GET", ref.base_url + "/api/v1/stats",
                        timeout=self.proxy_timeout_s)
                    per_replica[name] = st if status == 200 else dict(
                        error=f"HTTP {status}")
                except (urlerror.URLError, OSError) as e:
                    per_replica[name] = dict(error=str(e))
            out["replica_stats"] = per_replica
        return out

    def tenant_union(self) -> List[str]:
        tenants = set()
        for ref in self.refs():
            if not ref.routable:
                continue
            try:
                status, out = http_json(
                    "GET", ref.base_url + "/api/v1/tenants",
                    timeout=self.proxy_timeout_s)
            except (urlerror.URLError, OSError):
                continue
            if status == 200:
                tenants.update(out.get("tenants", []))
        return sorted(tenants)

    def flush_all(self) -> Dict[str, object]:
        """Fan-out seal+solve: POST /api/v1/flush on every routable
        replica, summed."""
        sealed = solved = 0
        per = {}
        for ref in self.refs():
            if not ref.routable:
                continue
            try:
                status, out = http_json(
                    "POST", ref.base_url + "/api/v1/flush", None,
                    timeout=self.proxy_timeout_s)
            except (urlerror.URLError, OSError) as e:
                per[ref.name] = dict(status=0, error=str(e))
                continue
            if status == 200:
                sealed += int(out.get("sealed_windows", 0))
                solved += int(out.get("solved_windows", 0))
            per[ref.name] = dict(status=status, **out)
        return dict(sealed_windows=sealed, solved_windows=solved,
                    replicas=per)

    # -- health loop ------------------------------------------------------
    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_period_s):
            for ref in self.refs():
                try:
                    status, _ = http_json(
                        "GET", ref.base_url + "/readyz",
                        timeout=max(0.5, self.health_period_s))
                    now_ready = status == 200
                except (urlerror.URLError, OSError):
                    now_ready = False
                if now_ready != ref.ready:
                    _events.emit("fleet", "replica_health",
                                 replica=ref.name, ready=now_ready)
                ref.ready = now_ready
            _OBS_READY.set(float(sum(r.routable for r in self.refs())))
