"""Replica fleet lifecycle: spawn, watch, migrate, rolling-restart.

Two replica flavors behind one duck-typed surface (``name``,
``base_url``, ``stop()``):

- :class:`ReplicaProcess` — the REAL thing: one
  ``python -m traceweaver_tpu.runtime.cli serve`` subprocess per
  replica, shared-nothing (own state dir, own interpreter, own
  mesh/AOT bring-up), port parsed from its startup line. This is what
  the committed campaign artifact and the tier-1 fleet smoke drive —
  true process parallelism, so N=2 replicas can actually out-ingest
  N=1 on a multi-core host.
- :class:`InProcReplica` — a full :class:`TenantService` behind a real
  ``ThreadingHTTPServer`` in this process. Same wire path, same
  handlers, no interpreter spawn — the fast harness for router unit
  tests where subprocess startup cost would dominate.

:class:`FleetManager` composes N replicas with a
:class:`~traceweaver_tpu.fleet_serve.router.FleetRouter` and owns the
two fleet-wide operations:

- ``migrate(tenant, dst)`` — delegates to the router (hold → out → in
  → re-pin), counted on both sides.
- ``rolling_restart()`` — the zero-downtime runbook, one replica at a
  time: migrate its tenants onto the survivors, mark it draining in the
  router (out of rotation BEFORE the kill), SIGTERM (serve checkpoints
  every remaining tenant in the drain budget), respawn with
  ``--resume``, poll ``/readyz`` until the new process answers 200,
  restore routing. The router keeps serving throughout — at most one
  replica is down at any instant.

With ``supervise=True`` the manager also runs a **crash supervisor**: a
watcher thread polls each subprocess replica's liveness (``waitpid``
via ``Popen.poll``), and a replica that exits WITHOUT being asked to
(kill -9, OOM, segfault — anything not flagged draining) is recovered
on one of two paths, both with the recovery wall ledgered in
``tw_failover_seconds{mode=...}``:

- **counted respawn** (under ``TW_FLEET_RESPAWN_MAX``): doubling
  backoff, then ``--resume`` on the same state dir — checkpoints
  restore the windows, the ingest WAL tail replays everything acked
  after the last checkpoint, so no acknowledged span is lost. The
  replica's tenants are HELD at the router for the respawn window
  (requests wait instead of forking empty twins on survivors).
- **survivor failover** (respawn budget exhausted, survivors exist):
  each tenant on the crashed disk is rebuilt from its checkpoint
  (``.prev`` fallback if the head generation tore) plus WAL tail via
  :func:`~traceweaver_tpu.serve.tenancy.read_crashed_transfer`,
  ``migrate_in``'d on the least-loaded survivor, pinned there, and
  tombstoned on the dead disk — the same zero-twin discipline as a
  live migration, driven entirely from post-mortem bytes.
"""

from __future__ import annotations

import contextlib
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from traceweaver_tpu.fleet_serve.router import FleetRouter, http_json
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import knobs

_LISTEN_RE = re.compile(r"listening on (http://[\d.]+:\d+)")

_OBS_FAILOVER = _get_registry().histogram(
    "tw_failover_seconds",
    "wall-clock seconds from replica-crash detection to restored "
    "routing, by recovery mode (respawn/failover)",
    labels=("mode",))


class ReplicaError(RuntimeError):
    """A replica process failed to start, stop, or come back ready."""


class ReplicaProcess:
    """One ``cli serve`` subprocess: spawn, parse the listen line, tail
    stdout on a thread (the log rides ``self.log`` for post-mortems),
    SIGTERM-stop, respawn with ``--resume``."""

    def __init__(self, name: str, state_dir: str,
                 serve_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 startup_timeout_s: float = 180.0) -> None:
        self.name = name
        self.state_dir = state_dir
        self.serve_args = list(serve_args or [])
        self.env = dict(env) if env is not None else dict(
            os.environ, JAX_PLATFORMS="cpu", TW_BACKEND="cpu")
        self.startup_timeout_s = startup_timeout_s
        self.base_url = ""
        self.log: List[str] = []
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._listen = threading.Event()

    def start(self, resume: bool = False) -> "ReplicaProcess":
        if self.proc is not None and self.proc.poll() is None:
            raise ReplicaError(f"replica {self.name} already running")
        cmd = [sys.executable, "-m", "traceweaver_tpu.runtime.cli",
               "serve", "--port", "0", "--state-dir", self.state_dir]
        if resume:
            cmd.append("--resume")
        cmd += self.serve_args
        self._listen.clear()
        self.proc = subprocess.Popen(
            cmd, env=self.env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self._reader = threading.Thread(
            target=self._tail, name=f"tw-replica-{self.name}-log",
            daemon=True)
        self._reader.start()
        if not self._listen.wait(timeout=self.startup_timeout_s):
            tail = "\n".join(self.log[-20:])
            self.stop(timeout_s=5.0)
            raise ReplicaError(
                f"replica {self.name} never printed its listen line "
                f"within {self.startup_timeout_s:.0f}s; log tail:\n{tail}")
        return self

    def _tail(self) -> None:
        proc = self.proc
        assert proc is not None and proc.stdout is not None
        for line in proc.stdout:
            self.log.append(line.rstrip("\n"))
            m = _LISTEN_RE.search(line)
            if m:
                self.base_url = m.group(1)
                self._listen.set()
        # EOF: the process exited. If it died before ever listening,
        # release the waiter so start() can report the log instead of
        # burning the whole startup timeout.
        self._listen.set()
        if not self.base_url:
            return

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self, timeout_s: float = 120.0) -> None:
        """SIGTERM → graceful drain (serve checkpoints every tenant) →
        wait; SIGKILL only if the drain budget blows."""
        proc = self.proc
        if proc is None:
            return
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        if self._reader is not None:
            self._reader.join(timeout=5.0)

    def restart(self, timeout_s: float = 120.0) -> str:
        """Graceful stop + ``--resume`` respawn; returns the NEW base
        url (port 0 means the port changes — the caller re-points the
        router slot)."""
        self.stop(timeout_s=timeout_s)
        self.base_url = ""
        self.start(resume=True)
        self.restarts += 1
        return self.base_url


class InProcReplica:
    """A full serve replica (TenantService + threaded HTTP server) in
    this process — the real wire path without the subprocess cost."""

    def __init__(self, name: str, cfg) -> None:
        # deferred import: the router process stays JAX-free; only
        # replica construction pulls the serve/stream stack in
        from traceweaver_tpu.serve import TenantService, make_server

        self.name = name
        self.service = TenantService(cfg)
        self.server = make_server(self.service, host="127.0.0.1", port=0)
        self.base_url = f"http://127.0.0.1:{self.server.port}"
        self._thread = threading.Thread(
            target=self.server.serve_forever,
            name=f"tw-replica-{name}", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 30.0) -> None:
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=timeout_s)
        self.service.drain()


class FleetManager:
    """N replicas + one router, started together, torn down together.

    ``supervise=True`` arms the crash supervisor (subprocess replicas
    only): unexpected exits are detected within ``watch_period_s`` and
    recovered by counted respawn or survivor failover — see the module
    docstring for the full protocol."""

    def __init__(self, replicas: List, router_port: Optional[int] = 0,
                 verbose: bool = False, supervise: bool = False,
                 watch_period_s: float = 0.2) -> None:
        self.replicas: Dict[str, object] = {r.name: r for r in replicas}
        self.router = FleetRouter(
            {r.name: r.base_url for r in replicas},
            port=router_port, verbose=verbose).start()
        self.verbose = verbose
        self.respawn_max = knobs.get_int("TW_FLEET_RESPAWN_MAX")
        self.respawns: Dict[str, int] = {}
        self.failovers: List[Dict[str, object]] = []
        self._watch_period_s = watch_period_s
        self._stop_ev = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if supervise:
            # failed proxy attempts yield one grace period so crash
            # detection + tenant holds beat the retry to the ring
            self.router.crash_grace_s = max(0.5, 3.0 * watch_period_s)
            self._watcher = threading.Thread(
                target=self._watch_loop, name="tw-fleet-supervisor",
                daemon=True)
            self._watcher.start()

    @property
    def base_url(self) -> str:
        return self.router.base_url

    def migrate(self, tenant: str, dst: str) -> Dict[str, object]:
        return self.router.migrate(tenant, dst)

    def replica_tenants(self, name: str) -> List[str]:
        ref = self.router.replicas[name]
        status, out = http_json("GET", ref.base_url + "/api/v1/tenants",
                                timeout=self.router.proxy_timeout_s)
        if status != 200:
            raise ReplicaError(
                f"replica {name}: /api/v1/tenants HTTP {status}")
        return list(out.get("tenants", []))

    def _drain_target(self, exclude: str) -> str:
        """Pick the migration destination for a draining replica's
        tenants: the routable survivor with the fewest tenants."""
        best, best_n = None, None
        for name, ref in self.router.replicas.items():
            if name == exclude or not ref.routable:
                continue
            n = len(self.replica_tenants(name))
            if best_n is None or n < best_n:
                best, best_n = name, n
        if best is None:
            raise ReplicaError(
                f"rolling restart of {exclude}: no routable survivor to "
                f"migrate its tenants to")
        return best

    def rolling_restart(self,
                        ready_timeout_s: float = 180.0) -> Dict[str, object]:
        """Restart every replica, one at a time, with zero request loss:
        tenants are migrated off FIRST, the replica leaves routing
        before its SIGTERM, and rotation only moves on once ``/readyz``
        answers 200 from the respawned process."""
        report: Dict[str, object] = {}
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if not isinstance(rep, ReplicaProcess):
                raise ReplicaError(
                    f"rolling restart needs subprocess replicas; "
                    f"{name} is {type(rep).__name__}")
            moved = []
            for tenant in self.replica_tenants(name):
                dst = self._drain_target(exclude=name)
                self.migrate(tenant, dst)
                moved.append((tenant, dst))
            # out of rotation BEFORE the kill: the router stops offering
            # this replica while the socket is still up, so no POST
            # races the teardown
            self.router.set_draining(name, True)
            try:
                new_url = rep.restart()
                self.router.update_replica(name, new_url)
                self._wait_ready(name, timeout_s=ready_timeout_s)
            finally:
                self.router.set_draining(name, False)
            self.router.bump("restarts")
            _events.emit("fleet", "rolling_restart", replica=name,
                         moved=len(moved), new_url=new_url)
            report[name] = dict(moved=moved, base_url=new_url)
        return report

    def _wait_ready(self, name: str, timeout_s: float) -> None:
        ref = self.router.replicas[name]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                status, _ = http_json("GET", ref.base_url + "/readyz",
                                      timeout=5.0)
            except OSError:
                status = None
            if status == 200:
                ref.ready = True
                return
            time.sleep(0.2)
        raise ReplicaError(
            f"replica {name} did not become ready within "
            f"{timeout_s:.0f}s after restart")

    # -- crash supervisor --------------------------------------------------
    def _watch_loop(self) -> None:
        """Liveness poll over the subprocess replicas. A replica that is
        dead but NOT draining (nobody asked it to stop) crashed; recover
        it. The loop itself must never die — recovery failures are
        evented and the replica is struck from further attempts rather
        than spinning."""
        gave_up: set = set()
        while not self._stop_ev.wait(self._watch_period_s):
            for name, rep in sorted(self.replicas.items()):
                if not isinstance(rep, ReplicaProcess) or name in gave_up:
                    continue
                ref = self.router.replicas.get(name)
                if rep.alive or ref is None or ref.draining:
                    continue
                if self._stop_ev.is_set():
                    return
                try:
                    done = self._recover_crashed(name, rep)
                except Exception as e:  # noqa: BLE001 — supervisor survives
                    done = True
                    _events.emit("fleet", "recover_failed", replica=name,
                                 error=f"{type(e).__name__}: {e}")
                if done:
                    gave_up.add(name)

    def _crashed_tenant_dirs(self, rep: ReplicaProcess) -> List[str]:
        """Tenant ids with recoverable state on a crashed replica's disk
        (mirrors the ``TenantService.resume`` scan: a checkpoint or a
        WAL segment, and no migration tombstone)."""
        out: List[str] = []
        try:
            names = sorted(os.listdir(rep.state_dir))
        except OSError:
            return out
        for n in names:
            tdir = os.path.join(rep.state_dir, n)
            if not os.path.isdir(tdir):
                continue
            if os.path.isfile(os.path.join(tdir, "migrated_out.json")):
                continue
            has_state = (
                os.path.isfile(os.path.join(tdir, "ckpt.pkl"))
                or os.path.isfile(os.path.join(tdir, "ckpt.pkl.prev"))
                or os.path.isdir(os.path.join(tdir, "wal")))
            if has_state:
                out.append(n)
        return out

    def _recover_crashed(self, name: str, rep: ReplicaProcess) -> bool:
        """One crash-recovery round. Returns True when the supervisor is
        DONE with this replica (failover ran, or nothing left to try);
        False keeps it under watch (a respawned process can crash
        again and draw from the remaining budget)."""
        t0 = time.monotonic()
        rc = rep.proc.returncode if rep.proc is not None else None
        ref = self.router.replicas[name]
        ref.ready = False  # out of routing before the health loop notices
        tenants = self._crashed_tenant_dirs(rep)
        _events.emit("fleet", "replica_crashed", replica=name,
                     returncode=rc, tenants=len(tenants),
                     respawns_used=self.respawns.get(name, 0))
        # hold the dead replica's tenants for the recovery window:
        # their POSTs wait at the router instead of auto-creating empty
        # forked twins on whichever survivor the ring offers next
        with contextlib.ExitStack() as stack:
            for t in tenants:
                stack.enter_context(self.router.hold_tenant(t))
            n = self.respawns.get(name, 0)
            if n < self.respawn_max:
                self.respawns[name] = n + 1
                self._respawn_crashed(name, rep, backoff_round=n, t0=t0)
                return False
            self._failover_crashed(name, rep, tenants, t0=t0)
        return True

    def _respawn_crashed(self, name: str, rep: ReplicaProcess,
                         backoff_round: int, t0: float) -> None:
        """Respawn a crashed replica in place: doubling backoff, then
        ``--resume`` on the same state dir — checkpoints restore the
        windows, the WAL tail replays every ack after them."""
        self._stop_ev.wait(min(5.0, 0.25 * (2 ** backoff_round)))
        if self._stop_ev.is_set():
            return
        if rep._reader is not None:
            rep._reader.join(timeout=5.0)
        rep.base_url = ""
        rep.start(resume=True)
        rep.restarts += 1
        self.router.update_replica(name, rep.base_url)
        self._wait_ready(name, timeout_s=rep.startup_timeout_s)
        wall_s = time.monotonic() - t0
        _OBS_FAILOVER.observe(wall_s, mode="respawn")
        self.router.bump("respawns")
        _events.emit("fleet", "replica_respawned", replica=name,
                     new_url=rep.base_url, wall_s=round(wall_s, 3),
                     respawns_used=self.respawns.get(name, 0))

    def _failover_crashed(self, name: str, rep: ReplicaProcess,
                          tenants: List[str], t0: float) -> None:
        """Respawn budget exhausted: rebuild each tenant from the
        crashed disk (checkpoint + WAL tail) on the least-loaded
        survivor, pin it there, tombstone the dead copy."""
        # deferred import — the manager stays serve-stack-free until a
        # failover actually runs (same rule as InProcReplica)
        from traceweaver_tpu.serve import tenancy as _tenancy

        moved, skipped = [], []
        for tenant in tenants:
            tdir = os.path.join(rep.state_dir, tenant)
            dst = self._drain_target(exclude=name)
            try:
                payload = _tenancy.read_crashed_transfer(tdir, tenant)
            except _tenancy.TenancyError as e:
                # nothing recoverable in this dir (e.g. empty WAL, no
                # checkpoint yet) — there is no acked state to lose
                skipped.append(tenant)
                _events.emit("fleet", "crash_failover_skipped",
                             replica=name, tenant=tenant, error=str(e))
                continue
            dst_url = self.router.replicas[dst].base_url
            status, res = http_json(
                "POST", f"{dst_url}/api/v1/tenants/{tenant}/migrate_in",
                payload, timeout=self.router.migrate_timeout_s)
            if status != 200:
                raise ReplicaError(
                    f"crash failover of {tenant!r} onto {dst}: HTTP "
                    f"{status} {res.get('error', '')} — state remains on "
                    f"{name}'s disk ({tdir})")
            self.router.pin(tenant, dst)
            _tenancy.tombstone_crashed_tenant(tdir, tenant)
            moved.append((tenant, dst))
        wall_s = time.monotonic() - t0
        _OBS_FAILOVER.observe(wall_s, mode="failover")
        self.router.bump("failovers")
        self.failovers.append(dict(
            replica=name, moved=moved, skipped=skipped,
            wall_s=round(wall_s, 3)))
        _events.emit("fleet", "crash_failover", replica=name,
                     moved=len(moved), skipped=len(skipped),
                     wall_s=round(wall_s, 3))

    def stop(self) -> None:
        # the supervisor goes first: the teardown that follows kills
        # replicas on purpose, and a live watcher would "recover" them
        self._stop_ev.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
        self.router.stop()
        for rep in self.replicas.values():
            rep.stop()  # type: ignore[attr-defined]
