"""Multi-host / multi-slice (DCN) scale-out: corpus-level data parallelism.

Design (SURVEY.md §2.8/§5 "Distributed communication backend"). The
reference is a single-node artifact; its only scale-out is backgrounded
shell jobs, one executor process per dataset (reference
exps/exp1/run_experiment.sh:74-79). The TPU-native scale-out has three
tiers, matched to the hardware's communication hierarchy:

1. **Within a chip** — vmap over windows/endpoints (weaver_tpu).
2. **Within a slice (ICI)** — the window axis sharded over the slice's
   devices (`parallel.mesh`): windows are independent subproblems, so the
   solve partitions with no cross-device traffic at all, and only the EM
   M-step reduces [Ne, K]-shaped sufficient statistics with `psum` —
   high-bandwidth ICI handles the (tiny) allreduce inline.
3. **Across slices / hosts (DCN)** — THIS module. The unit of work is a
   whole assignment problem (one call graph, or one service's span
   partitions): problems are range-partitioned across processes, each
   process solves its shard with the full single-slice stack, and the
   only cross-slice communication is (a) an optional allreduce of
   per-edge-family delay statistics when one set of distributions should
   be fit corpus-wide (the Alibaba regime: the same call-graph signature
   appears in many shards), and (b) result gather at the end. Both are
   O(edges × components) and O(results) — orders of magnitude below DCN
   bandwidth — so the design is DCN-friendly by construction: no solve
   state ever crosses a slice boundary.

The two communication paths degrade gracefully:

- With a JAX distributed runtime (`jax.distributed.initialize`, real
  multi-host TPU or multi-process CPU with gloo collectives),
  :func:`allreduce_stats_jax` reduces the stacked statistics with one
  XLA allreduce over a global mesh — DCN between slices, ICI within.
- Without one (plain OS processes, the reference's own process model),
  :func:`allreduce_stats_files` provides a filesystem barrier+reduce so
  the exp harness works on any box. Correctness is identical; only
  transport differs. tests/test_multislice.py proves the two-process
  case end-to-end through BOTH transports and asserts they agree.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

EdgeKey = Tuple[str, str]


def partition_problems(n_problems: int, n_processes: int,
                       process_id: int) -> List[int]:
    """Contiguous range partition of problem indices for one process.

    Call graphs are grouped by signature (alibaba/grouping.py), so
    neighbouring indices have similar sizes; contiguous ranges keep shard
    cost roughly balanced without a scheduler.
    """
    assert 0 <= process_id < n_processes
    base, extra = divmod(n_problems, n_processes)
    lo = process_id * base + min(process_id, extra)
    hi = lo + base + (1 if process_id < extra else 0)
    return list(range(lo, hi))


def merge_edge_stats(
    local: Dict[EdgeKey, Tuple[float, float, float]],
    others: Sequence[Dict[EdgeKey, Tuple[float, float, float]]],
) -> Dict[EdgeKey, Tuple[float, float, float]]:
    """Reduce per-edge (n, Σd, Σd²) sufficient statistics across shards.

    These are exactly the quantities the sharded EM M-step psums within a
    slice (`ops.gmm.fit_gmm_sharded`); across slices they are additive,
    so corpus-wide Gaussian parameters are recovered exactly:
    ``mean = Σd/n``, ``var = Σd²/n − mean²``.
    """
    out: Dict[EdgeKey, list] = {
        k: list(v) for k, v in local.items()
    }
    for d in others:
        for k, (n, s1, s2) in d.items():
            if k in out:
                out[k][0] += n
                out[k][1] += s1
                out[k][2] += s2
            else:
                out[k] = [n, s1, s2]
    return {k: (v[0], v[1], v[2]) for k, v in out.items()}


def edge_stats_from_samples(
    samples_by_edge: Dict[EdgeKey, Sequence[float]],
) -> Dict[EdgeKey, Tuple[float, float, float]]:
    """Local (n, Σd, Σd²) per edge from raw delay samples (f64 on host —
    same no-cancellation rule as ops/gmm.py's standardization)."""
    out = {}
    for k, v in samples_by_edge.items():
        a = np.asarray(v, dtype=np.float64)
        out[k] = (float(len(a)), float(a.sum()), float((a * a).sum()))
    return out


def stats_to_rows(
    stats: Dict[EdgeKey, Tuple[float, float, float]],
    edge_order: Sequence[EdgeKey],
) -> np.ndarray:
    """Dense [len(edge_order), 3] view of per-edge stats (absent edges are
    zero rows — the additive identity, so reductions stay exact)."""
    rows = np.zeros((len(edge_order), 3), dtype=np.float64)
    for i, k in enumerate(edge_order):
        if k in stats:
            rows[i] = stats[k]
    return rows


def allreduce_stats_jax(local_rows: np.ndarray) -> np.ndarray:
    """The JAX-distributed-runtime transport: one ``psum`` of the stacked
    per-edge sufficient statistics across every process's devices.

    Requires ``jax.distributed.initialize`` to have run (real multi-host
    TPU, or multi-process CPU with gloo collectives) and every process to
    call with a same-shaped ``[rows, 3]`` array. Each process contributes
    its local rows as one shard of a global ``[n_devices, rows, 3]`` array
    laid out over a 1-D "slices" mesh; the jitted sum over the sharded
    axis lowers to an XLA allreduce — DCN between slices, ICI within —
    and returns the identical merged rows on every process (the same
    numbers :func:`allreduce_stats_files` produces over the filesystem
    transport; tests/test_multislice.py asserts both).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("slices",))
    # f64 end-to-end: the stats are (n, Σd, Σd²) with Σd² ~ 1e13+ for
    # ms-scale delays over big corpora — f32 would silently destroy the
    # variance by cancellation, diverging from the filesystem transport
    with jax.enable_x64(True):
        local = jnp.asarray(local_rows, dtype=jnp.float64)
        # only the FIRST local device carries the process's rows; the rest
        # contribute exact-zero rows, so the global sum is correct for any
        # devices-per-process split (no replica-count division needed)
        zero = jnp.zeros_like(local)
        shards = [
            jax.device_put(local[None] if i == 0 else zero[None], d)
            for i, d in enumerate(jax.local_devices())
        ]
        arr = jax.make_array_from_single_device_arrays(
            (devs.size,) + local.shape,
            NamedSharding(mesh, PartitionSpec("slices")), shards)
        out = jax.jit(lambda x: jnp.sum(x, axis=0))(arr)
        return np.asarray(out, dtype=np.float64)


def allreduce_stats_files(
    stats: Dict[EdgeKey, Tuple[float, float, float]],
    rendezvous_dir: str,
    process_id: int,
    n_processes: int,
    timeout_s: float = 120.0,
    poll_s: float = 0.05,
    round_id: int = 0,
) -> Dict[EdgeKey, Tuple[float, float, float]]:
    """Filesystem allreduce: every process writes its local stats, waits
    for all peers, and computes the identical merged result.

    The DCN-transport stand-in for plain-process deployments (the
    reference's own process model); with a JAX distributed runtime the
    same reduction is one ``psum`` of the stacked [Ne, 3] tensor.

    ``round_id`` namespaces the barrier files: repeated reductions over
    the same rendezvous dir (one per EM iteration, or a restarted run)
    MUST pass distinct round ids, otherwise a peer's stale file from an
    earlier round would satisfy the barrier and merge wrong statistics.
    """
    os.makedirs(rendezvous_dir, exist_ok=True)
    payload = {json.dumps(list(k)): v for k, v in stats.items()}
    tmp = os.path.join(rendezvous_dir,
                       f".stats_r{round_id}_{process_id}.tmp")
    final = os.path.join(rendezvous_dir,
                         f"stats_r{round_id}_{process_id}.json")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, final)  # atomic publish

    deadline = time.time() + timeout_s
    paths = [os.path.join(rendezvous_dir, f"stats_r{round_id}_{p}.json")
             for p in range(n_processes)]
    while not all(os.path.exists(p) for p in paths):
        if time.time() > deadline:
            missing = [p for p in paths if not os.path.exists(p)]
            raise TimeoutError(f"allreduce barrier: missing {missing}")
        time.sleep(poll_s)

    shards = []
    for p in paths:
        with open(p) as f:
            raw = json.load(f)
        shards.append({tuple(json.loads(k)): tuple(v)
                       for k, v in raw.items()})
    merged = merge_edge_stats(shards[0], shards[1:])
    return merged
