"""Device mesh + sharding helpers."""

from traceweaver_tpu.parallel.mesh import (  # noqa: F401
    em_step_sharded,
    make_mesh,
    shard_solve_windows,
)
