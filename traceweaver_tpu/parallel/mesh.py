"""Device-mesh sharding for the windowed solver.

The reference has no distributed compute at all (SURVEY.md §2.8) — its
concurrency is a ThreadPool over services (reference executor.py:1015-1026)
and backgrounded shell jobs. Here the natural batch axis is the *window*
axis produced by perfect-cut segmentation: windows are independent
subproblems, so they shard cleanly across TPU cores over ICI:

- :func:`shard_solve_windows` — data-parallel inference: window tensors are
  placed with a ``NamedSharding`` over the ``data`` mesh axis and the jitted
  solve partitions automatically (XLA SPMD inserts any needed collectives).
- :func:`em_step_sharded` — one full *training* step of the EM loop under
  ``shard_map``: each shard solves its windows and computes plan-weighted
  sufficient statistics for every call-graph edge's delay distribution;
  ``jax.lax.psum`` over the mesh reduces the statistics, and every device
  computes the same updated (mean, std) — the distributed analogue of the
  reference's ``ComputeEpPairDistParams5`` refit (traceweaver_v3.py:706-818)
  fused with the solve.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax releases; resolve the spelling this build understands once
import inspect as _inspect

_CHECK_KW = ("check_vma"
             if "check_vma" in _inspect.signature(shard_map).parameters
             else "check_rep")

from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

BATCHED = ("in_start", "in_end", "in_valid", "out_start", "out_end",
           "out_valid", "skip_cap", "force_skip")
REPLICATED = ("pred_mask", "root_mask", "is_last",
              "edge_wt", "edge_mu", "edge_sd",
              "in_wt", "in_mu", "in_sd",
              "ret_wt", "ret_mu", "ret_sd")


def make_mesh(n_devices: Optional[int] = None, axis: str = "data",
              backend: Optional[str] = None) -> Mesh:
    """Mesh over the default backend's devices; falls back to virtual CPU
    devices when more devices are requested than the default backend has
    (single-chip dev box standing in for a slice)."""
    devices = jax.devices(backend) if backend else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        devices = jax.devices("cpu")
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"cannot assemble a {n_devices}-device mesh: default backend "
                f"and CPU fallback offer only {len(devices)} devices (start "
                "the process with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices})"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def bucket_rows_per_shard(n_rows: int, n_shards: int) -> int:
    """Sharding-aware padded batch size for a gathered redispatch: each
    shard's row count is rounded up to a power of two (so straggler
    counts — which vary run to run — cannot mint unbounded compiled
    variants, the same discipline as ``weaver_tpu._bucket``) and the
    total divides evenly across the mesh. ``n_shards=1`` degenerates to
    plain power-of-two bucketing (the single-device compaction path)."""
    from traceweaver_tpu.runtime.bucketing import pow2_bucket

    per_shard = -(-max(1, n_rows) // n_shards)  # ceil division
    return pow2_bucket(per_shard) * n_shards


def coalesce_to_device0(arr, mesh: Mesh):
    """Gather a mesh-sharded array onto the mesh's first device.

    The compaction flag fetch reads a ``[B]`` bool array the solve left
    sharded over the mesh; pulling it straight to host costs one D2H
    round trip PER SHARD (each ~100 ms through the sandbox's remote
    tunnel — N round trips to learn B bytes). Re-placing it on one
    device first turns the fan-in into a device-side gather over ICI,
    so the host pays exactly ONE ledgered transfer per dispatch group
    (``fleet._fetch_flags`` bills it under ``d2h_bytes_flags`` like the
    single-device path)."""
    from jax.sharding import SingleDeviceSharding

    return jax.device_put(arr, SingleDeviceSharding(mesh.devices.flat[0]))


def _pad_batch(arrays: Dict[str, np.ndarray], multiple: int) -> Tuple[Dict[str, np.ndarray], int]:
    b = arrays["in_start"].shape[0]
    pad = (-b) % multiple
    if pad == 0:
        return arrays, b
    out = dict(arrays)
    for k in BATCHED:
        a = arrays[k]
        out[k] = np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)], axis=0
        )
    return out, b


def put_sharded(arrays: Dict[str, np.ndarray], mesh: Mesh) -> Dict[str, jax.Array]:
    """Place packed window tensors on the mesh: batched (window-axis)
    tensors sharded over the mesh axis, distribution/DAG params replicated.
    The caller must have padded the batch to a multiple of the mesh size
    (``pack_problem(pad_b=mesh.devices.size)`` guarantees it). XLA SPMD
    then partitions any jitted solve over these inputs with collectives
    over ICI — no per-device loop on the host."""
    axis = mesh.axis_names[0]
    batched = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    out = {}
    for k, v in arrays.items():
        out[k] = jax.device_put(
            v, batched if k in BATCHED else replicated)
    return out


def shard_solve_windows(arrays: Dict[str, np.ndarray], mesh: Mesh,
                        **kwargs):
    """Run :func:`solve_windows` with the window axis sharded over ``mesh``.

    Pads the batch to a multiple of the mesh size, places the batched
    tensors with a window-axis ``NamedSharding``, and lets the jitted solve
    partition under XLA SPMD. Returns outputs trimmed to the true batch.
    """
    axis = mesh.axis_names[0]
    arrays, true_b = _pad_batch(arrays, mesh.devices.size)
    batched_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    args = {}
    for k in BATCHED:
        args[k] = jax.device_put(arrays[k], batched_sharding)
    for k in REPLICATED:
        args[k] = jax.device_put(arrays[k], replicated)
    out = solve_windows(
        args["in_start"], args["in_end"], args["in_valid"],
        args["out_start"], args["out_end"], args["out_valid"],
        args["skip_cap"], args["force_skip"],
        args["pred_mask"], args["root_mask"], args["is_last"],
        args["edge_wt"], args["edge_mu"], args["edge_sd"],
        args["in_wt"], args["in_mu"], args["in_sd"],
        args["ret_wt"], args["ret_mu"], args["ret_sd"],
        **kwargs,
    )
    return tuple(np.asarray(o)[:true_b] for o in out)


@lru_cache(maxsize=32)
def _build_em_step(mesh: Mesh, epsilon: float, n_sinkhorn: int):
    """Compile-once factory for the sharded EM step (jit caches by function
    identity, so the closure must be built once per (mesh, hypers))."""
    axis = mesh.axis_names[0]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(tuple(P(axis) for _ in BATCHED),
                  tuple(P() for _ in REPLICATED)),
        out_specs=(P(axis), P(), P(), P()),
        **{_CHECK_KW: False},
    )
    def step(batched, replicated):
        from traceweaver_tpu.ops.gmm import fit_gmm_sharded

        (in_start, in_end, in_valid, out_start, out_end, out_valid,
         skip_cap, force_skip) = batched
        (pred_mask, root_mask, is_last,
         edge_wt, edge_mu, edge_sd,
         in_wt, in_mu, in_sd,
         ret_wt, ret_mu, ret_sd) = replicated

        assign, _, _, _ = solve_windows(
            in_start, in_end, in_valid, out_start, out_end, out_valid,
            skip_cap, force_skip, pred_mask, root_mask, is_last,
            edge_wt, edge_mu, edge_sd, in_wt, in_mu, in_sd,
            ret_wt, ret_mu, ret_sd,
            epsilon=epsilon, n_sinkhorn=n_sinkhorn,
        )  # [b, E, W]

        # local slice of the three production refit families — the family
        # definitions live in ONE place (weaver_tpu.em_family_samples),
        # shared with the fused single-device EM
        from traceweaver_tpu.algorithms.weaver_tpu import em_family_samples

        samples, smask = em_family_samples(
            assign, in_start, in_end, in_valid, out_start, out_end,
            pred_mask, root_mask)                            # [Ne, n_local]

        w, mu, sd = fit_gmm_sharded(samples, smask, axis,
                                    max_k=in_wt.shape[1])
        return assign, w, mu, sd

    return jax.jit(step)


def em_step_sharded(arrays: Dict[str, np.ndarray], mesh: Mesh,
                    epsilon: float = 1.0, n_sinkhorn: int = 40):
    """One distributed EM step: sharded solve + psum'd BIC-GMM M-step.

    E-step: every shard solves its windows (hard assignments). M-step: each
    shard computes, for every edge of all three production families —
    root ``(in -> e)``, DAG ``(p -> e)``, return ``(e -> in)`` — the local
    slice of that edge's delay samples, and the BIC-selected GMMs are fit
    with EM whose moment sums ride ``jax.lax.psum`` over the mesh
    (:func:`traceweaver_tpu.ops.gmm.fit_gmm_sharded`); every device ends
    with identical mixtures. This is the same sufficient-statistics
    computation :func:`traceweaver_tpu.algorithms.timing.refit_from_assignments`
    performs on host (reference ``ComputeEpPairDistParams5``,
    traceweaver_v3.py:706-818), distributed.

    Returns ``(assign, dists)`` where ``dists`` maps family name to
    fixed-shape mixture params: ``"in"``/``"ret"`` -> (w, mu, sd) each
    [E, K]; ``"edge"`` -> (w, mu, sd) each [E, E, K] indexed [e, p].

    The compiled step is cached per (mesh, epsilon, n_sinkhorn) — repeated
    calls in a training loop reuse one XLA program per input shape.
    """
    arrays, true_b = _pad_batch(arrays, mesh.devices.size)
    step = _build_em_step(mesh, epsilon, n_sinkhorn)
    batched = tuple(jnp.asarray(arrays[k]) for k in BATCHED)
    replicated = tuple(jnp.asarray(arrays[k]) for k in REPLICATED)
    assign, w, mu, sd = step(batched, replicated)
    E = arrays["root_mask"].shape[0]
    K = arrays["in_wt"].shape[1]
    w, mu, sd = (np.asarray(a) for a in (w, mu, sd))

    def fam(lo, hi, shape):
        return (w[lo:hi].reshape(shape), mu[lo:hi].reshape(shape),
                sd[lo:hi].reshape(shape))

    dists = {
        "in": fam(0, E, (E, K)),
        "edge": fam(E, E + E * E, (E, E, K)),
        "ret": fam(E + E * E, E + E * E + E, (E, K)),
    }
    return np.asarray(assign)[:true_b], dists
