"""Jaeger-JSON ingestion, dataset repair, partitioning, DAG inference."""

from traceweaver_tpu.ingest.jaeger import (  # noqa: F401
    FIX_ROOT_OPS,
    MalformedSpan,
    load_corpus,
    parse_trace_file,
    parse_trace_payload,
    time_ordered_trace_files,
)
from traceweaver_tpu.ingest.partition import (  # noqa: F401
    ServiceProblem,
    build_service_problem,
    partition_spans_by_endpoint,
)
from traceweaver_tpu.ingest.order import (  # noqa: F401
    discover_invocation_dag, fit_invocation_dag, infer_dag_from_predictions,
    infer_invocation_dag, solver_misfit,
    topological_sort_grouped,
)
