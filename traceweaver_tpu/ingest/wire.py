"""Columnar wire ingest (``TW_WIRE_COLUMNAR``, r18).

The serve path's accepted-span POST bodies used to run the batch
loader's object pipeline: ``json.loads`` into per-span dicts, then one
:class:`~traceweaver_tpu.spans.Span` dataclass per record
(:func:`~traceweaver_tpu.ingest.jaeger.parse_trace_payload`). At fleet
wire rates that per-span Python tail dominates the whole serve path
(docs/PERF.md "Wire ingest (r18)").

This module parses an accepted Jaeger-JSON POST body straight into
per-trace column batches instead:

- **native front-end** (default): the payload bytes go to the C++
  loader's ``tw_parse_payload`` entry (``native/src/loader.cc``), which
  returns interned struct-of-arrays span data — no Python JSON parse,
  no per-span dicts. The native loader is fail-fast: any span missing a
  required field (or carrying non-numeric times) fails the whole
  payload, and the pure-Python front-end below takes over — so
  dead-letter accounting has exactly one implementation.
- **pure-Python front-end** (``TW_DISABLE_NATIVE=1``, native parse
  failure, or a dict payload): one ``json.loads`` plus the object
  parser's own ``_record_from_json`` per span — identical acceptance,
  identical skip-and-count malformed-span semantics by construction.

Both front-ends land in one shared assembler that replicates the object
pipeline's per-trace semantics (Alibaba ``.client`` rewrites, duplicate
span-id dict-insertion order, time-containment drops, rootless drops)
over plain index arrays, and defers Span materialization
(:meth:`WireTrace.materialize`, via :meth:`Span.fast`) to ACCEPTED
traces only — the lazy-object contract. Materialized spans carry
``tags=None``; nothing downstream of the serve path reads ``tags``.

Not every payload is columnar-eligible. :func:`parse_payload_wire`
returns ``None`` (caller falls back to the object parser, counted
``path=object``) when:

- ``fix`` is 0 or 1 (the nodejs/media repair shims walk Span objects);
- ``strict`` ingestion is requested (the raise-on-malformed contract
  belongs to the object parser);
- the payload carries Alibaba-converter records (any ``caller`` field):
  self-loop remapping mints RNG ids and must stay in one place;
- Alibaba mode with a non-empty ``self_loop_map``: earlier converter
  payloads may force descendant-client process rewrites on this one.

Counters are committed only when the wire parse is used (a fallback
must not double-count the object parser's dead letters).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from traceweaver_tpu import native as native_mod
from traceweaver_tpu.ingest.jaeger import (
    FIX_ROOT_OPS,
    MalformedSpan,
    RawSpan,
    _record_from_json,
)
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.spans import Span, SpanId

#: which parse engine handled a columnar-path payload — the "no silent
#: native skew" counter: tests assert that either the native loader
#: engaged or the Python fallback was COUNTED (docs/PERF.md).
_OBS_WIRE_ENGINE = _get_registry().counter(
    "tw_wire_parse_total",
    "columnar wire payloads parsed, by engine (native|python)",
    labels=("engine",))


class WireTrace:
    """One accepted-shape wire trace, assembled but not materialized.

    Holds the post-rewrite per-record data (final span ids, references,
    process ids) plus the duplicate-resolved key order — everything the
    serve admission filter needs (:attr:`has_root`, :attr:`root_op`)
    without constructing a single Span. :meth:`materialize` builds the
    ``(trace_id, spans, processes)`` tuple the object parser would have
    returned, and is called only for traces that pass the root-op
    filter."""

    __slots__ = ("trace_id", "has_root", "root_op", "n_spans",
                 "_recs", "_final", "_idx_of", "_processes")

    def __init__(self, trace_id: str, recs: List[RawSpan],
                 final: List[Tuple[str, str, List[SpanId], str]],
                 idx_of: Dict[SpanId, int],
                 processes: Dict[str, str]) -> None:
        self.trace_id = trace_id
        self._recs = recs
        self._final = final
        self._idx_of = idx_of
        self._processes = processes
        self.n_spans = len(idx_of)
        # first final span in dict-insertion order with no references —
        # the exact span `next((s for s in spans.values() if s.IsRoot()),
        # None)` finds on the object path
        self.has_root = False
        self.root_op: Optional[str] = None
        for i in idx_of.values():
            if not final[i][2]:
                self.has_root = True
                self.root_op = recs[i].op_name
                break

    def materialize(self) -> Tuple[str, Dict[SpanId, Span],
                                   Dict[str, str]]:
        """Build the object parser's ``(trace_id, spans, processes)``
        for this trace — Span objects minted here and only here, via
        :meth:`Span.fast` (``tags=None``)."""
        spans: Dict[SpanId, Span] = {}
        recs, final = self._recs, self._final
        for key, i in self._idx_of.items():
            tid, sid, refs, pid = final[i]
            rec = recs[i]
            spans[key] = Span.fast(tid, sid, rec.start_mus,
                                   rec.duration_mus, rec.op_name, refs,
                                   pid, rec.span_kind)
        return self.trace_id, spans, self._processes


class _CorpusCols:
    """Whole-corpus Python-list views of a :class:`NativeCorpus` — one
    ``tolist`` per column, shared by every :class:`WireTraceCols` slice
    of the payload — plus the lazily grouped per-trace processes table
    (only accepted traces ever need it)."""

    __slots__ = ("strings", "start", "dur", "trace", "sid", "op", "pid",
                 "kind", "ref_offsets", "ref_trace", "ref_sid", "_nc",
                 "_procs")

    def __init__(self, nc) -> None:
        self.strings = nc.strings
        self.start = nc.start.tolist()
        self.dur = nc.duration.tolist()
        self.trace = nc.trace.tolist()
        self.sid = nc.sid.tolist()
        self.op = nc.op.tolist()
        self.pid = nc.process.tolist()
        self.kind = nc.kind.tolist()
        self.ref_offsets = nc.ref_offsets.tolist()
        self.ref_trace = nc.ref_trace.tolist()
        self.ref_sid = nc.ref_sid.tolist()
        self._nc = nc
        self._procs: Optional[Dict[int, Dict[str, str]]] = None

    def processes(self, t: int) -> Dict[str, str]:
        if self._procs is None:
            self._procs = self._nc.processes_by_trace()
        return self._procs.get(t, {})


class WireTraceCols:
    """Fast-path wire trace: a ``[lo, hi)`` slice view over the shared
    corpus columns, minted only after the whole payload passed the
    vectorized anomaly sweep (uniform per-trace ids, unique span ids,
    no missing ``processID``, non-Alibaba fix) — so no per-span Python
    work happened to build it. Same accepted-trace surface as
    :class:`WireTrace`; only rooted traces are constructed at all."""

    __slots__ = ("trace_id", "has_root", "root_op", "n_spans",
                 "_cols", "_t", "_lo", "_hi")

    def __init__(self, cols: _CorpusCols, t: int, lo: int, hi: int,
                 trace_id: str, root_op: Optional[str]) -> None:
        self.trace_id = trace_id
        self.has_root = True
        self.root_op = root_op
        self.n_spans = hi - lo
        self._cols = cols
        self._t = t
        self._lo = lo
        self._hi = hi

    def materialize(self) -> Tuple[str, Dict[SpanId, Span],
                                   Dict[str, str]]:
        c = self._cols
        strings = c.strings
        trace, sid_c, op_c = c.trace, c.sid, c.op
        pid_c, kind_c = c.pid, c.kind
        start, dur = c.start, c.dur
        ro, rt, rs = c.ref_offsets, c.ref_trace, c.ref_sid
        fast = Span.fast
        spans: Dict[SpanId, Span] = {}
        for i in range(self._lo, self._hi):
            tid = strings[trace[i]]
            sid = strings[sid_c[i]]
            opx, kx = op_c[i], kind_c[i]
            refs = [(strings[rt[j]], strings[rs[j]])
                    for j in range(ro[i], ro[i + 1])]
            spans[(tid, sid)] = fast(
                tid, sid, start[i], dur[i],
                strings[opx] if opx >= 0 else None, refs,
                strings[pid_c[i]], strings[kx] if kx >= 0 else None)
        return self.trace_id, spans, c.processes(self._t)


def _bump(counters: Dict[str, int], key: str) -> None:
    counters[key] = counters.get(key, 0) + 1


def _assemble_wire(
    trace_id: str,
    recs: List[RawSpan],
    alibaba: bool,
    raw_processes: Dict[str, str],
) -> Optional[WireTrace]:
    """The shared per-trace assembler: Alibaba client/server rewrites,
    duplicate-key resolution, containment validation — the column-path
    mirror of ``_records_to_spans`` + ``_assemble_trace`` for
    caller-free traces (converter payloads never reach here). Returns
    None when the trace is dropped on a containment violation."""
    overall: Optional[str] = None
    # key -> record index: first-occurrence position, last record wins —
    # the dict-insertion semantics of the object path's spans dict
    idx_of: Dict[SpanId, int] = {}
    final: List[Tuple[str, str, List[SpanId], str]] = []
    for i, rec in enumerate(recs):
        tid, sid = rec.trace_id, rec.sid
        refs: List[SpanId] = list(rec.refs)
        if overall is None:
            overall = tid
        elif tid != overall:
            raise ValueError(
                "Different trace ids for spans in the same trace")
        if alibaba:
            if rec.span_kind == "client":
                sid = sid + ".client"
            if rec.span_kind == "server" and len(refs) == 1:
                refs[0] = (refs[0][0], sid + ".client")
        idx_of[(tid, sid)] = i
        final.append((tid, sid, refs, rec.process_id))

    if alibaba and idx_of:
        # parent ⊇ child time containment from the first root, over the
        # FINAL (duplicate-resolved) spans — iterative, same verdict as
        # the object path's recursion
        children: Dict[SpanId, List[SpanId]] = {}
        for key, i in idx_of.items():
            refs = final[i][2]
            if refs and refs[0] in idx_of:
                children.setdefault(refs[0], []).append(key)
        root_key = next((k for k, i in idx_of.items() if not final[i][2]),
                        None)

        def check_containment(key: SpanId) -> bool:
            # raw-value comparisons in the object path's exact order
            # (string-typed times that float()-coerce still TypeError
            # here, same as Span.start_mus comparisons would)
            i = idx_of[key]
            s_start = recs[i].start_mus
            s_dur = recs[i].duration_mus
            for child_key in children.get(key, ()):
                j = idx_of[child_key]
                c_start = recs[j].start_mus
                c_dur = recs[j].duration_mus
                if not (s_start <= c_start
                        and s_start + s_dur >= c_start + c_dur):
                    return False
                if not check_containment(child_key):
                    return False
            return True

        if root_key is not None and not check_containment(root_key):
            return None  # dropped trace

    return WireTrace(trace_id, recs, final, idx_of, raw_processes)


def _entries_native_fast(nc, counters: Dict[str, int]
                         ) -> Optional[List[Optional[WireTraceCols]]]:
    """The zero-object fast path over a natively parsed non-Alibaba
    payload: a handful of whole-corpus numpy sweeps decide eligibility
    and find every trace's root, then one tiny Python loop mints slice
    views (:class:`WireTraceCols`) for the rooted traces — no per-span
    Python touches at all. Returns None when the payload shows any
    anomaly the object pipeline handles record-by-record (a span with
    ``processID`` missing, duplicate span ids, mixed trace ids inside
    one entry); the careful per-record assembler then takes over with
    its exact skip/raise semantics."""
    t = nc.n_traces
    if t == 0:
        return []
    n = nc.n_spans
    if nc.process.size and int(nc.process.min()) < 0:
        return None  # missing processID somewhere: careful path counts it
    offs = nc.trace_offsets
    counts = np.diff(offs)
    if n:
        # per-entry trace-id uniformity: every span's traceID equals its
        # entry's first span's (the object path raises ValueError on the
        # first offending entry — the careful path owns that ordering)
        first = nc.trace[np.minimum(offs[:-1], n - 1)]
        if not np.array_equal(nc.trace,
                              np.repeat(first, counts)):
            return None
        # span-id uniqueness per entry: duplicates engage the object
        # path's dict-insertion (first position, last value wins) rules
        seg = np.repeat(np.arange(t, dtype=np.int64), counts)
        pair = seg * len(nc.strings) + nc.sid
        if np.unique(pair).size != n:
            return None
    # first reference-free span per entry, in record order — the exact
    # root the object path's next(s for s in spans.values() if IsRoot())
    # finds once ids are unique
    root_idx = np.full(t, -1, np.int64)
    if n:
        root_pos = np.flatnonzero(np.diff(nc.ref_offsets) == 0)
        seg_of_root = np.searchsorted(offs, root_pos, side="right") - 1
        segs, firsts = np.unique(seg_of_root, return_index=True)
        root_idx[segs] = root_pos[firsts]
        root_ops = np.where(root_idx >= 0,
                            nc.op[np.maximum(root_idx, 0)], -1).tolist()
    else:
        root_ops = [-1] * t
    root_idx_l = root_idx.tolist()
    offs_l = offs.tolist()
    tid_idx = nc.trace_id.tolist()
    strings = nc.strings
    cols = _CorpusCols(nc)
    entries: List[Optional[WireTraceCols]] = []
    n_rootless = 0
    for i in range(t):
        if root_idx_l[i] < 0:
            n_rootless += 1
            entries.append(None)
            continue
        ox = root_ops[i]
        entries.append(WireTraceCols(
            cols, i, offs_l[i], offs_l[i + 1], strings[tid_idx[i]],
            strings[ox] if ox >= 0 else None))
    if n_rootless:
        counters["rootless_traces"] = (
            counters.get("rootless_traces", 0) + n_rootless)
    return entries


def _entries_from_native(nc, fix: int, counters: Dict[str, int]
                         ) -> List[Optional[WireTrace]]:
    """Assemble every trace of a natively parsed payload. The native
    loader already enforced the required-field contract per span, so
    the only dead letters here are spans whose ``processID`` was absent
    (tolerated as -1 by the loader, skip-and-count like the object
    parser's ``MalformedSpan``)."""
    alibaba = FIX_ROOT_OPS[fix] is None
    if not alibaba:
        entries = _entries_native_fast(nc, counters)
        if entries is not None:
            return entries
    strings = nc.strings
    procs_by_trace = nc.processes_by_trace()
    entries: List[Optional[WireTrace]] = []
    ref_offsets = nc.ref_offsets.tolist()
    ref_trace = nc.ref_trace.tolist()
    ref_sid = nc.ref_sid.tolist()
    trace_offsets = nc.trace_offsets.tolist()
    for t in range(nc.n_traces):
        lo, hi = trace_offsets[t], trace_offsets[t + 1]
        starts = nc.start[lo:hi].tolist()
        durs = nc.duration[lo:hi].tolist()
        tids = nc.trace[lo:hi].tolist()
        sids = nc.sid[lo:hi].tolist()
        ops = nc.op[lo:hi].tolist()
        pids = nc.process[lo:hi].tolist()
        kinds = nc.kind[lo:hi].tolist()
        recs: List[RawSpan] = []
        for i in range(hi - lo):
            pidx = pids[i]
            if pidx < 0:
                # missing processID: the object parser raises
                # MalformedSpan and skips-and-counts; same dead letter
                _bump(counters, "malformed_spans")
                continue
            rlo, rhi = ref_offsets[lo + i], ref_offsets[lo + i + 1]
            opx, kx = ops[i], kinds[i]
            recs.append(RawSpan(
                trace_id=strings[tids[i]], sid=strings[sids[i]],
                start_mus=starts[i], duration_mus=durs[i],
                op_name=strings[opx] if opx >= 0 else None,
                refs=tuple((strings[ref_trace[j]], strings[ref_sid[j]])
                           for j in range(rlo, rhi)),
                process_id=strings[pidx],
                span_kind=strings[kx] if kx >= 0 else None,
                caller=None, callee=None))
        wt = _assemble_wire(strings[nc.trace_id[t]], recs, alibaba,
                            procs_by_trace.get(t, {}))
        if wt is None:
            _bump(counters, "dropped_traces")
            entries.append(None)
        elif not wt.has_root:
            _bump(counters, "rootless_traces")
            entries.append(None)
        else:
            entries.append(wt)
    return entries


def _entries_from_dict(payload: dict, fix: int,
                       counters: Dict[str, int]
                       ) -> Optional[List[Optional[WireTrace]]]:
    """The pure-Python front-end: same scaffolding as
    ``parse_trace_payload`` (shape check, per-trace malformed counters)
    but assembling :class:`WireTrace` columns instead of Span objects.
    Returns None (fall back to the object parser) when a converter
    record (``caller`` field) shows up."""
    if not isinstance(payload, dict) or not isinstance(
            payload.get("data"), list):
        raise MalformedSpan(
            "payload is not a Jaeger-JSON trace object "
            "({'data': [{traceID, spans, processes}]})")
    alibaba = FIX_ROOT_OPS[fix] is None
    entries: List[Optional[WireTrace]] = []
    for trace_json in payload["data"]:
        try:
            trace_id = trace_json["traceID"]
            span_records = trace_json["spans"]
        except (KeyError, TypeError):
            _bump(counters, "malformed_traces")
            entries.append(None)
            continue
        recs: List[RawSpan] = []
        for rec in span_records:
            try:
                recs.append(_record_from_json(rec))
            except MalformedSpan:
                _bump(counters, "malformed_spans")
        if any(r.caller is not None for r in recs):
            return None  # converter payload: object parser owns it
        raw_processes = {
            pid: entry["serviceName"]
            for pid, entry in trace_json.get("processes", {}).items()
        }
        wt = _assemble_wire(trace_id, recs, alibaba, raw_processes)
        if wt is None:
            _bump(counters, "dropped_traces")
            entries.append(None)
        elif not wt.has_root:
            _bump(counters, "rootless_traces")
            entries.append(None)
        else:
            entries.append(wt)
    return entries


def parse_payload_wire(
    payload,
    fix: int,
    self_loop_map: Dict[str, List[str]],
    strict: bool = False,
    counters: Optional[Dict[str, int]] = None,
) -> Optional[List[Optional[WireTrace]]]:
    """Parse one posted Jaeger-JSON payload (``bytes`` straight off the
    wire, or an already-decoded dict) into :class:`WireTrace` entries —
    one per ``data`` element, ``None`` for dropped/rootless/malformed
    traces, mirroring ``parse_trace_payload``'s result shape.

    Returns ``None`` when the payload is not columnar-eligible (see
    module docstring); the caller then runs the object parser. Dead
    letters are accumulated locally and committed into ``counters``
    only when the wire parse is actually used, so a fallback never
    double-counts."""
    if strict or fix in (0, 1):
        return None
    alibaba = FIX_ROOT_OPS[fix] is None
    if alibaba and self_loop_map:
        return None

    local: Dict[str, int] = {}
    entries: Optional[List[Optional[WireTrace]]] = None
    engine = "python"
    try:
        if isinstance(payload, (bytes, bytearray)):
            raw = bytes(payload)
            nc = native_mod.parse_payload(raw)
            if nc is not None:
                if nc.caller.size and int(nc.caller.max()) >= 0:
                    return None  # converter payload
                engine = "native"
                entries = _entries_from_native(nc, fix, local)
            else:
                try:
                    payload = json.loads(raw)
                except json.JSONDecodeError as e:
                    raise MalformedSpan(f"invalid JSON: {e}") from None
                entries = _entries_from_dict(payload, fix, local)
        else:
            entries = _entries_from_dict(payload, fix, local)
    except Exception:
        # mixed trace ids, a malformed shape, or untyped-garbage time
        # fields mid-assembly: the object path commits counters
        # incrementally, so the dead letters counted before the raise
        # must land even though the parse failed
        if counters is not None:
            for k, v in local.items():
                counters[k] = counters.get(k, 0) + v
        raise
    if entries is None:
        return None
    if counters is not None:
        for k, v in local.items():
            counters[k] = counters.get(k, 0) + v
    _OBS_WIRE_ENGINE.inc(1.0, engine=engine)
    return entries
