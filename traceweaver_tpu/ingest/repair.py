"""Per-dataset repair adapters.

Two of the recorded testbeds ship spans in a shape the reconstructor can't
consume directly; these adapters normalise them (reference:
src/trace_reconstructor/ports/python/executor.py:509-633):

- :func:`fix_nodejs` (FIX=0) — the nodejs testbed recorded only one span per
  call, tagged ``client``. Flip those to ``server`` and fabricate the missing
  client half on the caller using the testbed's known topology.
- :func:`fix_media` (FIX=1) — media_microservices traces are re-rooted at the
  ``ComposeReview`` span, same-process parent chains are collapsed, and the
  missing client halves are fabricated from the parent links.
"""

from __future__ import annotations

import copy
from typing import Dict, Tuple

from traceweaver_tpu.spans import Span, SpanId

# Caller service for each nodejs testbed service (reference executor.py:109-115).
NODEJS_CALLER = {
    "service5": "service3",
    "service4": "service2",
    "service2": "service1",
    "service3": "service1",
    "service1": "init-service",
}


def fix_nodejs(spans: Dict[SpanId, Span], processes: Dict[str, str]) -> Dict[SpanId, Span]:
    """FIX=0: flip client→server; fabricate caller-side client spans.

    Mirrors reference ``FixSpans`` (executor.py:509-538): the fabricated
    client span reuses the server span's timing, lives on the caller's
    process (resolved via the hardcoded topology), and the server span is
    re-parented onto it.
    """
    # service name -> a process id for it (last one seen wins, as in reference)
    service_to_pid: Dict[str, str] = {}
    for span in spans.values():
        service_to_pid[processes[span.process_id]] = span.process_id

    new_spans: Dict[SpanId, Span] = {}
    for span_id, span in spans.items():
        service = processes[span.process_id]
        if span.span_kind == "client":
            span.span_kind = "server"
        elif span.span_kind == "server":
            clone = copy.deepcopy(span)
            original_ref = copy.deepcopy(span.references)
            span.references[0] = (original_ref[0][0], span.sid + "_client")
            clone.sid = clone.sid + "_client"
            clone.process_id = service_to_pid[NODEJS_CALLER[service]]
            clone.span_kind = "client"
            clone.references = original_ref
            new_spans[(clone.trace_id, clone.sid)] = clone

    spans.update(new_spans)
    return spans


def fix_media(
    spans: Dict[SpanId, Span], processes: Dict[str, str]
) -> Tuple[Dict[SpanId, Span], Dict[str, str]]:
    """FIX=1: re-root at ComposeReview and fabricate client halves.

    Mirrors reference ``FixSpans2`` (executor.py:543-633):
    1. delete ComposeReview's ancestor chain; re-point its children at a new
       root id equal to the trace id;
    2. drop spans whose parent lives in the same process (internal spans);
    3. mark every remaining span ``server`` and fabricate a ``client`` copy
       on the parent's process for each non-root span.
    """

    def parent_pid(span_id: SpanId):
        return spans[span_id].process_id if span_id in spans else None

    new_spans = copy.deepcopy(spans)

    def delete_ancestors(span_id: SpanId) -> None:
        if spans[span_id].references:
            delete_ancestors(spans[span_id].references[0])
        del new_spans[span_id]

    for span_id, span in list(spans.items()):
        if span.op_name == "ComposeReview":
            delete_ancestors(span.references[0])
            # children of ComposeReview now reference (trace_id, trace_id)
            for other_id, other in spans.items():
                if other.references and other.references[0] == span_id:
                    new_spans[other_id].references[0] = (other.trace_id, other.trace_id)
            span.sid = span.trace_id
            span.references = []
            new_spans[(span.trace_id, span.sid)] = span
            del new_spans[span_id]

    spans = copy.deepcopy(new_spans)
    for span_id, span in list(spans.items()):
        if span.references:
            pid = parent_pid(span.references[0])
            if pid is not None and pid == span.process_id:
                del new_spans[span_id]

    spans = copy.deepcopy(new_spans)
    fabricated: Dict[SpanId, Span] = {}
    for span in spans.values():
        span.span_kind = "server"
        if span.references:
            clone = copy.deepcopy(span)
            original_ref = copy.deepcopy(span.references)
            span.references[0] = (original_ref[0][0], span.sid + "_client")
            clone.sid = clone.sid + "_client"
            clone.process_id = parent_pid(original_ref[0])
            clone.span_kind = "client"
            clone.references = original_ref
            fabricated[(clone.trace_id, clone.sid)] = clone

    spans.update(fabricated)
    return spans, processes
