"""Jaeger-JSON trace ingestion.

Replicates the ingestion semantics of the reference executor
(reference: src/trace_reconstructor/ports/python/executor.py:287-475,
755-849) as a library:

- per-file parsing of Jaeger's ``{"data": [{traceID, spans, processes}]}``
  shape into :class:`~traceweaver_tpu.spans.Span` objects;
- the per-dataset ``FIX`` repair modes (0=nodejs, 1=media, 2/3=hotel,
  4=todo-app, 5=Alibaba);
- Alibaba-mode client/server span-id rewriting, self-loop remapping to
  synthetic ``*-loop`` services, and parent⊇child time-containment
  validation (violating traces dropped);
- time-ordered directory listing with an on-disk cache;
- corpus assembly into a :class:`~traceweaver_tpu.spans.TraceStore`.

Two parsing front-ends feed one shared semantic core
(:func:`_records_to_spans`): the pure-Python ``json`` path, and the native
C++ streaming loader (``traceweaver_tpu.native``), which parses files in
parallel off the GIL and hands back interned struct-of-arrays data. The
repair shims and every RNG-dependent step stay in Python so both paths are
bit-identical.
"""

from __future__ import annotations

import json
import os
import pickle
import random
import string
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Tuple

from traceweaver_tpu.spans import Span, SpanId, TraceStore
from traceweaver_tpu.ingest import repair
from traceweaver_tpu import native as native_mod

# FIX mode -> required root-span operation name. ``None`` (Alibaba) means
# "ingest every trace" (reference executor.py:756-762). Mode 6 is the
# pipeline's OWN telemetry (traceweaver_tpu/obs/selftrace.py): each
# window's journey emitted as a one-level fan-out trace rooted at a
# ``tw:window`` span — no repair shims, no Alibaba remapping, so
# ``serve --fix 6`` ingests the reconstructor's self-trace payloads and
# the solver reconstructs its own pipeline (docs/OBSERVABILITY.md).
FIX_ROOT_OPS: Dict[int, Optional[str]] = {
    0: "init-span",
    1: "ComposeReview",
    2: "HTTP GET /hotels",
    3: "HTTP GET /recommendations",
    4: "[Todo] CompleteTodoCommandHandler",
    5: None,
    6: "tw:window",
}


def _random_id(n: int = 16, suffix: str = "") -> str:
    alphabet = string.ascii_letters + string.digits
    return "".join(random.choice(alphabet) for _ in range(n)) + suffix


class MalformedSpan(ValueError):
    """A span record that cannot be parsed (missing ids/timestamps/refs,
    non-numeric durations). By default malformed records are
    skipped-and-counted (``ingest_malformed_spans`` on the store — a
    dead-letter counter, so a flaky exporter cannot abort a whole corpus
    load mid-stream); ``strict=True`` (the CLI's ``--strict``) restores
    the raise."""


# ---------------------------------------------------------------------------
# Directory listing, time-ordered (reference executor.py:287-339)
# ---------------------------------------------------------------------------

def _root_start_time(path: str) -> float:
    native_t = native_mod.root_start_time(path)
    if native_t is not None:
        return native_t
    try:
        with open(path, "r") as f:
            data = json.load(f).get("data", [])
    except (json.JSONDecodeError, OSError):
        return float("inf")
    if not data:
        return float("inf")
    spans = data[0].get("spans", [])
    root = next((s for s in spans if len(s.get("references", [])) == 0), None)
    if root is None:
        return float("inf")
    return float(root["startTime"])


def time_ordered_trace_files(directory: str, clear_cache: bool = False,
                             cache: bool = True,
                             write_cache: bool = False) -> List[str]:
    """List ``*.json`` files in ``directory`` sorted by root-span start time.

    With ``cache=True`` an existing ``time_order_filenames.pickle`` alongside
    the data is reused if its entries resolve on this machine (same cache
    file name as the reference, executor.py:320-339, so either implementation
    can read the other's cache). Writing the cache is opt-in
    (``write_cache=True``) so loading never mutates a dataset directory.
    """
    cache_path = Path(directory) / "time_order_filenames.pickle"
    if clear_cache:
        # Honor the clear request without reading the stale cache; only
        # delete the file when we own cache writes for this directory.
        cache = False
        if write_cache and cache_path.exists():
            os.remove(cache_path)
    if cache and cache_path.exists():
        try:
            with open(cache_path, "rb") as f:
                files = pickle.load(f)
            # Shipped datasets carry caches with the original author's
            # absolute paths; only trust a cache whose entries exist here.
            if files and all(os.path.exists(f) for f in files[:3]):
                return files
        except (pickle.UnpicklingError, EOFError, OSError):
            pass

    files = sorted(
        os.path.join(os.path.abspath(directory), f)
        for f in os.listdir(directory)
        if f.endswith("json") and os.path.isfile(os.path.join(directory, f))
    )
    files.sort(key=_root_start_time)
    if write_cache:
        try:
            with open(cache_path, "wb") as f:
                pickle.dump(files, f)
        except OSError:
            pass  # read-only data dir: skip the cache
    return files


# ---------------------------------------------------------------------------
# Span-level parsing (reference executor.py:342-488)
# ---------------------------------------------------------------------------

class RawSpan(NamedTuple):
    """One span record, front-end neutral (built from a JSON dict or from
    the native loader's arrays)."""

    trace_id: str
    sid: str
    start_mus: float
    duration_mus: float
    op_name: Optional[str]
    refs: Tuple[SpanId, ...]    # full references list, in order
    process_id: str
    span_kind: Optional[str]    # "client" | "server" | None
    caller: Optional[str]       # Alibaba converter fields
    callee: Optional[str]
    tags: object = None


def _records_to_spans(
    records: List[RawSpan],
    self_loop_map: Dict[str, List[str]],
    service_loop_map: Dict[str, str],
    alibaba: bool,
) -> Optional[Tuple[Dict[SpanId, Span], List[str]]]:
    """Build Span objects from one trace's records. Returns
    ``(spans, final_process_ids)`` — the per-record process ids after
    Alibaba self-loop remapping (they seed the identity process table) —
    or None if the trace is dropped.

    In Alibaba mode: client span ids get a ``.client`` suffix and server
    spans are re-parented onto the suffixed client id (executor.py:377-384);
    self-calls (caller==callee) are remapped onto a synthetic
    ``<random>-loop`` service shared across traces via ``self_loop_map``
    (executor.py:386-399); parent⊇child time containment is validated from
    the root and the whole trace is dropped on violation
    (executor.py:433-448).
    """
    spans: Dict[SpanId, Span] = {}
    final_pids: List[str] = []
    overall_trace_id = None

    for rec in records:
        trace_id = rec.trace_id
        sid = rec.sid
        process_id = rec.process_id
        references: List[SpanId] = list(rec.refs)

        if overall_trace_id is None:
            overall_trace_id = trace_id
        elif trace_id != overall_trace_id:
            raise ValueError("Different trace ids for spans in the same trace")

        if alibaba:
            if rec.span_kind == "client":
                sid = sid + ".client"
            if rec.span_kind == "server" and len(references) == 1:
                # The Alibaba converter emits a server+client record pair per
                # call sharing one spanID: the server half's parent is its own
                # id's client half (executor.py:382-384).
                references[0] = (references[0][0], sid + ".client")
            # Self-loop calls: remap the callee (and the server span's
            # process) onto a stable synthetic "-loop" service.
            if rec.caller is not None and rec.caller == rec.callee:
                sanitized = sid[:-7] if sid.endswith(".client") else sid
                if sanitized not in self_loop_map:
                    new_callee = _random_id(suffix="-loop")
                    self_loop_map[sanitized] = [rec.callee, new_callee]
                    service_loop_map[new_callee] = rec.callee
                if rec.span_kind == "server":
                    process_id = self_loop_map[sanitized][1]

        final_pids.append(process_id)
        spans[(trace_id, sid)] = Span(
            trace_id=trace_id,
            sid=sid,
            start_mus=rec.start_mus,
            duration_mus=rec.duration_mus,
            op_name=rec.op_name,
            references=references,
            process_id=process_id,
            span_kind=rec.span_kind,
            tags=rec.tags,
        )

    if not alibaba:
        return spans, final_pids

    # Alibaba mode: link children temporarily, validate containment, and
    # propagate self-loop process ids down to descendant client spans.
    children: Dict[SpanId, List[SpanId]] = {}
    for span_id, span in spans.items():
        if not span.IsRoot():
            children.setdefault(span.references[0], []).append(span_id)
    for parent_id, kids in children.items():
        if parent_id in spans:
            for kid in kids:
                spans[parent_id].AddChild(kid)

    def check_containment(span: Span) -> bool:
        for child_id in span.children_spans:
            child = spans[child_id]
            if not (span.start_mus <= child.start_mus
                    and span.end_mus >= child.end_mus):
                return False
            if not check_containment(child):
                return False
        return True

    root = next((s for s in spans.values() if s.IsRoot()), None)
    if root is not None and not check_containment(root):
        return None

    def update_descendant_clients(span: Span) -> None:
        for child_id in span.children_spans:
            child = spans[child_id]
            if child.span_kind == "client":
                child.process_id = spans[(span.trace_id, span.sid)].process_id
            update_descendant_clients(child)

    def walk(span: Span) -> None:
        sanitized = span.sid[:-7] if span.sid.endswith(".client") else span.sid
        if sanitized in self_loop_map:
            update_descendant_clients(span)
        for child_id in span.children_spans:
            walk(spans[child_id])

    if root is not None:
        walk(root)

    for span in spans.values():
        span.children_spans = []
    return spans, final_pids


def _record_from_json(rec: dict) -> RawSpan:
    span_kind = None
    for tag in rec.get("tags", []):
        if tag.get("key") == "span.kind":
            span_kind = tag.get("value")
    try:
        refs = tuple(
            (ref["traceID"], ref["spanID"])
            for ref in rec.get("references", [])
        )
        trace_id = rec["traceID"]
        sid = rec["spanID"]
        start_mus = rec["startTime"]
        duration_mus = rec["duration"]
        process_id = rec["processID"]
    except (KeyError, TypeError) as e:
        raise MalformedSpan(
            f"span record missing required field: {e}") from None
    try:
        float(start_mus)
        float(duration_mus)
    except (TypeError, ValueError):
        raise MalformedSpan(
            f"span {sid!r}: non-numeric startTime/duration "
            f"({start_mus!r}, {duration_mus!r})") from None
    return RawSpan(
        trace_id=trace_id,
        sid=sid,
        start_mus=start_mus,
        duration_mus=duration_mus,
        op_name=rec.get("requestType", rec.get("operationName")),
        refs=refs,
        process_id=process_id,
        span_kind=span_kind,
        caller=rec.get("caller"),
        callee=rec.get("callee"),
        tags=rec.get("tags"),
    )


def _assemble_trace(
    records: List[RawSpan],
    fix: int,
    self_loop_map: Dict[str, List[str]],
    service_loop_map: Dict[str, str],
    raw_processes: Dict[str, str],
) -> Optional[Tuple[Dict[SpanId, Span], Dict[str, str], bool]]:
    """Shared post-parse pipeline for one trace, used by both front-ends:
    record→Span conversion, process-table construction, fix-mode repair,
    root detection. ``raw_processes`` is the file's pid→service table
    (ignored for Alibaba-format traces, whose process ids double as service
    names post self-loop remap, executor.py:484-488). Returns
    ``(spans, processes, has_root)`` or None when the trace is dropped.
    """
    alibaba = FIX_ROOT_OPS[fix] is None
    parsed = _records_to_spans(records, self_loop_map, service_loop_map,
                               alibaba)
    if parsed is None:
        return None
    spans, final_pids = parsed
    # The Alibaba converter emits caller/callee/requestType together
    # (reference real-parser.py:308-359), so caller presence detects the
    # converted format.
    alibaba_format = bool(records) and records[0].caller is not None
    if alibaba_format:
        processes = {pid: pid for pid in final_pids}
    else:
        processes = raw_processes
    if fix == 0:
        spans = repair.fix_nodejs(spans, processes)
    elif fix == 1:
        spans, processes = repair.fix_media(spans, processes)
    has_root = any(s.IsRoot() for s in spans.values())
    return spans, processes, has_root


# ---------------------------------------------------------------------------
# Trace-level parsing (reference executor.py:755-793)
# ---------------------------------------------------------------------------

def parse_trace_payload(
    payload: dict,
    fix: int,
    self_loop_map: Dict[str, List[str]],
    service_loop_map: Dict[str, str],
    strict: bool = False,
    counters: Optional[Dict[str, int]] = None,
) -> List[Optional[Tuple[str, Dict[SpanId, Span], Dict[str, str]]]]:
    """Parse one Jaeger-JSON payload (``{"data": [...]}``) — the shared
    core of :func:`parse_trace_file` and the serve layer's HTTP span
    ingestion (``POST /api/v1/tenants/<id>/spans`` posts exactly this
    shape, see docs/SERVING.md).

    Returns one entry per ``data`` element: ``(trace_id, spans,
    processes)`` for a rooted trace, or None when the trace was dropped
    (time-containment violation in Alibaba mode, or no root span).
    Malformed span records (missing ids/refs/timestamps, non-numeric
    durations) are skipped and counted under
    ``counters["malformed_spans"]`` — a dead-letter counter, never a
    mid-stream crash; ``strict=True`` restores the raise.
    """
    if not isinstance(payload, dict) or not isinstance(
            payload.get("data"), list):
        raise MalformedSpan(
            "payload is not a Jaeger-JSON trace object "
            "({'data': [{traceID, spans, processes}]})")
    results: List[Optional[Tuple[str, Dict[SpanId, Span],
                                 Dict[str, str]]]] = []
    for trace_json in payload["data"]:
        try:
            trace_id = trace_json["traceID"]
            span_records = trace_json["spans"]
        except (KeyError, TypeError):
            if strict:
                raise MalformedSpan(
                    "trace object missing traceID/spans") from None
            if counters is not None:
                counters["malformed_traces"] = (
                    counters.get("malformed_traces", 0) + 1)
            results.append(None)
            continue
        records = []
        for rec in span_records:
            try:
                records.append(_record_from_json(rec))
            except MalformedSpan:
                if strict:
                    raise
                if counters is not None:
                    counters["malformed_spans"] = (
                        counters.get("malformed_spans", 0) + 1)
        raw_processes = {
            pid: entry["serviceName"]
            for pid, entry in trace_json.get("processes", {}).items()
        }
        assembled = _assemble_trace(records, fix, self_loop_map,
                                    service_loop_map, raw_processes)
        if assembled is None:
            # Alibaba-mode time-containment violation: the trace is
            # dropped (counted separately from rootless traces — the
            # file loader treats a drop as poisoning its whole file)
            if counters is not None:
                counters["dropped_traces"] = (
                    counters.get("dropped_traces", 0) + 1)
            results.append(None)
            continue
        spans, processes, has_root = assembled
        if not has_root:
            if counters is not None:
                counters["rootless_traces"] = (
                    counters.get("rootless_traces", 0) + 1)
            results.append(None)
            continue
        results.append((trace_id, spans, processes))
    return results


def parse_trace_file(
    path: str,
    fix: int,
    self_loop_map: Dict[str, List[str]],
    service_loop_map: Dict[str, str],
    strict: bool = False,
    counters: Optional[Dict[str, int]] = None,
) -> Optional[Tuple[str, Dict[SpanId, Span], Dict[str, str]]]:
    """Parse one trace file. Returns (trace_id, spans, processes) or None
    if the trace was dropped (time-containment violation in Alibaba mode).

    Malformed span records (missing ids/refs/timestamps, non-numeric
    durations) are skipped and counted under ``counters["malformed_spans"]``
    — a dead-letter counter, never a mid-stream crash; ``strict=True``
    restores the raise (the CLI's ``--strict``).
    """
    with open(path, "r") as f:
        payload = json.load(f)

    c = counters if counters is not None else {}
    dropped_before = c.get("dropped_traces", 0)
    parsed = parse_trace_payload(payload, fix, self_loop_map,
                                 service_loop_map, strict=strict,
                                 counters=c)
    if c.get("dropped_traces", 0) > dropped_before:
        # a containment-dropped trace poisons its whole file (the
        # reference's per-file semantics, executor.py:433-448)
        return None
    results = [p for p in parsed if p is not None]
    assert len(results) == 1, f"expected exactly one rooted trace in {path}"
    return results[0]


# ---------------------------------------------------------------------------
# Corpus assembly (reference executor.py:798-874)
# ---------------------------------------------------------------------------

def ingest_trace(
    store: TraceStore,
    trace_id: str,
    spans: Dict[SpanId, Span],
    processes: Dict[str, str],
    fix: int,
) -> int:
    """Add one parsed trace to the store if its root matches the FIX mode's
    root operation. Returns 1 if ingested, else 0 (executor.py:798-849).
    """
    first_span = FIX_ROOT_OPS[fix]

    root_span_id = None
    for span_id, span in spans.items():
        if span.IsRoot():
            root_span_id = span_id
        for parent_id in span.references:
            spans[parent_id].AddChild(span.GetId())
    for span in spans.values():
        span.children_spans.sort(key=lambda cid: spans[cid].start_mus)

    if root_span_id is None:
        return 0
    if first_span is not None and spans[root_span_id].op_name != first_span:
        return 0

    def add_span(span_id: SpanId) -> None:
        span = spans[span_id]
        service = processes[span.process_id]
        if span.span_kind == "client":
            store.out_spans_by_process.setdefault(service, []).append(span)
        elif span.span_kind == "server":
            store.in_spans_by_process.setdefault(service, []).append(span)
        else:
            raise ValueError(f"span {span_id} has kind {span.span_kind!r}")
        for child in span.children_spans:
            add_span(child)

    add_span(root_span_id)
    store.all_spans.update(spans)
    store.all_processes[trace_id] = processes
    return 1


# Files parsed per native batch: bounds peak DOM/corpus memory while keeping
# the parse thread pool saturated.
_NATIVE_CHUNK = 512


def _native_file_traces(
    nc: "native_mod.NativeCorpus",
    fix: int,
    self_loop_map: Dict[str, List[str]],
    service_loop_map: Dict[str, str],
    strict: bool = False,
    counters: Optional[Dict[str, int]] = None,
):
    """Yield ``(trace_id, spans, processes)`` per input file of a native
    corpus — same semantics as :func:`parse_trace_file` (including the
    drop-on-containment-violation behavior, yielding None for such files).
    """
    strings = nc.strings
    procs_by_trace = nc.processes_by_trace()

    # Trace indices grouped by file, preserving file order (traces arrive
    # file-ordered from the native loader).
    per_file: List[List[int]] = [[] for _ in range(nc.n_files)]
    for t in range(nc.n_traces):
        per_file[int(nc.trace_file[t])].append(t)

    for file_idx in range(nc.n_files):
        results = []
        processes: Dict[str, str] = {}
        dropped = False
        for t in per_file[file_idx]:
            lo = int(nc.trace_offsets[t])
            hi = int(nc.trace_offsets[t + 1])
            trace_id = strings[nc.trace_id[t]]
            records = []
            for i in range(lo, hi):
                op = int(nc.op[i])
                pidx = int(nc.process[i])
                if pidx < 0:
                    # Match the Python front-end: skip-and-count the
                    # malformed record (raise under --strict).
                    if strict:
                        raise MalformedSpan(
                            f"span {strings[nc.sid[i]]!r} has no processID"
                        )
                    if counters is not None:
                        counters["malformed_spans"] = (
                            counters.get("malformed_spans", 0) + 1)
                    continue
                kind = int(nc.kind[i])
                caller = int(nc.caller[i])
                callee = int(nc.callee[i])
                records.append(RawSpan(
                    trace_id=strings[nc.trace[i]],
                    sid=strings[nc.sid[i]],
                    start_mus=int(nc.start[i]),
                    duration_mus=int(nc.duration[i]),
                    op_name=strings[op] if op >= 0 else None,
                    refs=tuple(nc.span_refs(i)),
                    process_id=strings[pidx],
                    span_kind=strings[kind] if kind >= 0 else None,
                    caller=strings[caller] if caller >= 0 else None,
                    callee=strings[callee] if callee >= 0 else None,
                ))
            assembled = _assemble_trace(records, fix, self_loop_map,
                                        service_loop_map,
                                        procs_by_trace.get(t, {}))
            if assembled is None:
                dropped = True
                break
            spans, processes, has_root = assembled
            if has_root:
                results.append((trace_id, spans))
        if dropped:
            yield None
            continue
        assert len(results) == 1, "expected exactly one rooted trace per file"
        trace_id, spans = results[0]
        yield trace_id, spans, processes


def load_corpus(
    directory: str,
    fix: int,
    max_traces: int = 1000,
    clear_cache: bool = False,
    cache: bool = True,
    write_cache: bool = False,
    native: str = "auto",
    strict: bool = False,
) -> TraceStore:
    """Load a directory of Jaeger-JSON traces into a TraceStore.

    ``max_traces`` mirrors the reference's hard cap (executor.py:873:
    ``if cnt > 1000: break`` — i.e. up to max_traces+1 ingested).

    ``native``: "auto" uses the C++ streaming loader when available,
    "never" forces the pure-Python parser. Both produce identical stores.

    ``strict``: malformed span records raise (:class:`MalformedSpan`)
    instead of the default skip-and-count; either way the dead-letter
    count lands on ``store.ingest_malformed_spans``.

    Every exit finalizes the store's COLUMNAR partitions
    (:meth:`~traceweaver_tpu.spans.TraceStore.build_columns`, under
    ``TW_COLUMNAR``): per-service SpanArray columns built once here at
    ingest, alongside the Span dicts the CPU baselines keep — both parse
    front-ends (pure-Python and native C++) land on the same Span
    objects, so their columns are identical by construction.
    """
    def finalize(store: TraceStore) -> TraceStore:
        from traceweaver_tpu.runtime import knobs as _knobs

        if _knobs.get_bool("TW_COLUMNAR"):
            store.build_columns()
        return store

    store = TraceStore()
    counters = store.ingest_counters
    self_loop_map: Dict[str, List[str]] = {}
    files = time_ordered_trace_files(directory, clear_cache=clear_cache,
                                     cache=cache, write_cache=write_cache)
    cnt = 0
    use_native = native != "never" and native_mod.available()
    if use_native:
        chunk_start = 0
        while chunk_start < len(files):
            # Each file holds one ingestible trace, so don't parse far past
            # the remaining trace budget (+ slack for dropped/filtered files).
            budget = max_traces + 1 - cnt
            size = min(_NATIVE_CHUNK, max(budget + 8, 16))
            chunk = files[chunk_start:chunk_start + size]
            nc = native_mod.parse_files(chunk)
            if nc is None:
                use_native = False  # fall through to Python for the rest
                files = files[chunk_start:]
                break
            chunk_start += size
            for parsed in _native_file_traces(
                nc, fix, self_loop_map, store.service_loop_map,
                strict=strict, counters=counters,
            ):
                if parsed is None:
                    continue
                trace_id, spans, processes = parsed
                cnt += ingest_trace(store, trace_id, spans, processes, fix)
                if cnt > max_traces:
                    return finalize(store)
        else:
            return finalize(store)
    for path in files:
        parsed = parse_trace_file(path, fix, self_loop_map,
                                  store.service_loop_map,
                                  strict=strict, counters=counters)
        if parsed is None:
            continue
        trace_id, spans, processes = parsed
        cnt += ingest_trace(store, trace_id, spans, processes, fix)
        if cnt > max_traces:
            break
    return finalize(store)
