"""Per-service span partitioning.

For one service: group its incoming (server) spans by upstream endpoint and
its outgoing (client) spans by downstream endpoint, sorted by
``(start, end)`` (reference: src/trace_reconstructor/ports/python/
executor.py:931-950). Services with more than one incoming partition are
skipped by the executor, matching the reference.

Columnar host path (``TW_COLUMNAR``, default): the partition sort keys
come from :class:`~traceweaver_tpu.spans.SpanArray` float columns (one
``lexsort`` per partition instead of a Python key tuple per span), and
:meth:`ServiceProblem.columns` hands the solver the columnar view of the
partitions — the ingest → solver handoff the packed path consumes
(docs/PERF.md "Columnar host path"). Deliberately import-light (no JAX):
the bench parent partitions corpora without touching a backend.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from traceweaver_tpu.spans import Span, SpanArray, TraceStore


def _columnar_on() -> bool:
    # lazy: importing the runtime package at module-import time would
    # cycle (runtime/__init__ -> executor -> ingest -> partition); at
    # call time everything is initialized
    from traceweaver_tpu.runtime import knobs

    return knobs.get_bool("TW_COLUMNAR")


def partition_spans_by_endpoint(
    spans: List[Span], endpoint_of: Callable[[Span], str]
) -> Dict[str, List[Span]]:
    partitions: Dict[str, List[Span]] = {}
    for span in spans:
        partitions.setdefault(endpoint_of(span), []).append(span)
    if _columnar_on():
        # sort each partition by its float columns: same (start, end)
        # stable order as the key-tuple sort below, computed by one
        # lexsort over the column pair instead of per-span key calls
        for ep, part in partitions.items():
            arr = SpanArray.from_spans(part)
            order = np.lexsort((arr.end, arr.start))
            if not np.array_equal(order, np.arange(len(part))):
                partitions[ep] = [part[i] for i in order]
        return partitions
    for part in partitions.values():
        part.sort(key=lambda s: (s.start_mus, s.start_mus + s.duration_mus))
    return partitions


@dataclass
class ServiceProblem:
    """One service's assignment problem, ready for a solver.

    ``in_span_partitions`` has exactly one key (the upstream endpoint);
    ``out_span_partitions`` one key per downstream endpoint.
    """

    process: str
    in_span_partitions: Dict[str, List[Span]]
    out_span_partitions: Dict[str, List[Span]]
    skipped: bool = False
    skip_reason: Optional[str] = None

    def columns(self) -> Dict[str, Dict[str, SpanArray]]:
        """Columnar view of the partitions, built fresh at call time —
        call AFTER any in-place span transform (load compression,
        cache-hit injection), since columns snapshot span times. Keys:
        ``in``/``out`` → per-endpoint :class:`SpanArray` in the
        partition lists' sort order."""
        return {
            "in": {ep: SpanArray.from_spans(part)
                   for ep, part in self.in_span_partitions.items()},
            "out": {ep: SpanArray.from_spans(part)
                    for ep, part in self.out_span_partitions.items()},
        }


def build_service_problem(store: TraceStore, process: str,
                          deepcopy: bool = True) -> ServiceProblem:
    """Partition one service's spans (reference executor.py:915-950).

    Deep-copies the span lists by default because downstream transforms
    (load compression, cache-hit injection) mutate spans in place.
    """
    in_spans = store.in_spans_by_process.get(process, [])
    out_spans = store.out_spans_by_process.get(process, [])
    if deepcopy:
        in_spans = copy.deepcopy(in_spans)
        out_spans = copy.deepcopy(out_spans)

    if len(out_spans) == 0:
        return ServiceProblem(process, {}, {}, skipped=True,
                              skip_reason="no outgoing spans")

    in_parts = partition_spans_by_endpoint(
        in_spans, lambda s: s.GetParentProcess(store.all_processes, store.all_spans)
    )
    out_parts = partition_spans_by_endpoint(
        out_spans, lambda s: s.GetChildProcess(store.all_processes, store.all_spans)
    )
    if len(in_parts) > 1:
        return ServiceProblem(process, in_parts, out_parts, skipped=True,
                              skip_reason="multiple incoming partitions")
    return ServiceProblem(process, in_parts, out_parts)
