"""Invocation-order (precedence DAG) inference over outgoing endpoints.

Given ground-truth assignments for a service, infer which downstream
endpoints are invoked strictly after which others: start from the complete
digraph over endpoints and delete every edge contradicted by a pair of
overlapping ground-truth spans (reference:
src/trace_reconstructor/ports/python/executor.py:214-285, the ``G1`` graph).

Also provides grouped topological sort (executor.py:136-150) used by
downstream solvers to process endpoints level by level.
"""

from __future__ import annotations

from typing import Dict, List

import networkx as nx

from traceweaver_tpu.spans import Span, TraceStore


def topological_sort_grouped(G: nx.DiGraph) -> List[List]:
    """Kahn's algorithm, yielding antichains (groups of zero in-degree)."""
    indegree = {v: d for v, d in G.in_degree() if d > 0}
    zero = [v for v, d in G.in_degree() if d == 0]
    groups = []
    while zero:
        groups.append(zero)
        nxt = []
        for v in zero:
            for _, child in G.edges(v):
                indegree[child] -= 1
                if not indegree[child]:
                    nxt.append(child)
        zero = nxt
    return groups


def _complete_digraph(out_eps: List[str]) -> nx.DiGraph:
    G = nx.DiGraph()
    G.add_nodes_from(out_eps)
    for a in out_eps:
        for b in out_eps:
            if a != b:
                G.add_edge(a, b)
    return G


def _prune_contradicted_edges(G: nx.DiGraph, per_request_rows) -> None:
    """Delete every edge (a, b) contradicted by a request in which a's
    span overlaps b's (a does not complete before b starts) — the core
    rule shared by the ground-truth and prediction-driven inferences
    (reference executor.py:214-285, the ``G1`` graph)."""
    for outgoing in per_request_rows:
        outgoing.sort(key=lambda x: x[0])
        for i, (xs, xd, xep) in enumerate(outgoing):
            for j, (ys, yd, yep) in enumerate(outgoing):
                if i == j:
                    continue
                if xs + xd > ys and G.has_edge(xep, yep):
                    G.remove_edge(xep, yep)
                if ys + yd > xs and G.has_edge(yep, xep):
                    G.remove_edge(yep, xep)


def infer_invocation_dag(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    true_assignments: Dict[str, Dict],
    store: TraceStore,
) -> nx.DiGraph:
    """Infer the endpoint precedence DAG from ground-truth assignments.

    Edge (a, b) survives iff in no request does endpoint a's span overlap
    endpoint b's span in a way contradicting "a completes before b starts".
    """
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    out_eps = list(out_span_partitions.keys())

    G = _complete_digraph(out_eps)
    rows = []
    for in_span in in_spans:
        outgoing = []
        for out_ep in out_eps:
            span = store.all_spans[true_assignments[out_ep][in_span.GetId()]]
            child = span.GetChildProcess(store.all_processes, store.all_spans)
            outgoing.append((span.start_mus, span.duration_mus, child))
        rows.append(outgoing)
    _prune_contradicted_edges(G, rows)
    return G


def _adaptive_tol(rates, tol: float,
                  min_gap: float = 0.3, max_low: float = 0.35) -> float:
    """Widen ``tol`` to the midpoint of the largest gap in the sorted
    contradiction-rate spectrum when the rates are clearly bimodal.

    Guards (see :func:`infer_dag_from_predictions` docstring): the gap
    must be at least ``min_gap`` wide and the low cluster's maximum must
    stay below ``max_low`` — otherwise the fixed ``tol`` stands. Never
    returns less than ``tol``.
    """
    finite = sorted(r for r in rates if r == r)
    if len(finite) < 2:
        return tol
    width, low_max, mid = max(
        (finite[i + 1] - finite[i], finite[i],
         0.5 * (finite[i] + finite[i + 1]))
        for i in range(len(finite) - 1))
    if width >= min_gap and low_max <= max_low and mid > tol:
        return mid
    return tol


def infer_dag_from_predictions(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    assignments: Dict[str, Dict],
    store: TraceStore,
    tol: float = 0.05,
) -> nx.DiGraph:
    """The same contradiction pruning, driven by PREDICTED assignments.

    Tolerates what predictions contain and truth never does: NA (span
    unassigned) and SKIP (cache-served) entries simply contribute no row
    for that endpoint. Endpoint labels come from the partition keys, not
    span child lookups, so wrong-but-real assignments still prune the
    intended endpoint pair.

    Unlike truth rows, prediction rows carry two kinds of noise the
    ground-truth variant never sees, each with its own guard:

    - rows can MISS endpoints (NA/SKIP): endpoint pairs that never
      co-occur in any row carry no ordering evidence and keep NEITHER
      direction (a surviving 2-cycle would crash the topological sort
      downstream); residual longer cycles (inconsistent orderings
      across different rows) are broken at their weakest-supported
      edge, deterministically;
    - individual assignments can be WRONG: one bad assignment must not
      delete a true precedence edge, so an edge is pruned only when
      contradicted in more than ``tol`` of its co-occurrence rows
      (truth uses strict any-contradiction; truly-parallel endpoint
      pairs overlap in far more rows than any plausible error rate, so
      false edges still die).

    ``tol`` is a floor, not the operative threshold: under heavy
    interleaving (the exp5/bench ×10 regime) prediction noise pushes even
    REAL edges' contradiction rates far above any fixed tolerance (hotel
    frontend at load150×10, measured: true edges 0.02/0.14/0.28 vs
    parallel pairs 0.78/0.88/0.99), while the two populations stay
    bimodal. :func:`_adaptive_tol` therefore widens ``tol`` to the
    midpoint of the largest gap in the sorted rate spectrum — but only
    when the gap is wide (≥ 0.3) and the low cluster sits below 0.35
    (margin above the worst measured true-edge rate, 0.28). The guard is
    deliberately tight because ordering statistics cannot distinguish a
    skewed-but-parallel pair (b merely TENDS to start after a finishes)
    from a true precedence edge once its contradiction rate climbs — a
    symmetric parallel pair overlaps in ≥ half its rows, but a skewed
    one can sit anywhere below that. Pairs in the ambiguous band above
    0.35 therefore fall back to the fixed ``tol`` and are pruned; this
    keeps edge-free and fan-out services edge-free at the price of
    missing hypothetical true edges noisier than any measured so far.

    The spectrum guard is population-level, so it is backed by a
    PER-PAIR check: a pair whose contradiction rate exceeds the fixed
    ``tol`` (i.e. it survives only because the tolerance widened) must
    also carry directional evidence — forward support well above an
    even split (support/cooccur ≥ 0.7) or a near-totally-contradicted
    reverse direction (≥ 0.98). A skewed-but-parallel pair that lands
    below the low cluster's 0.35 cap (say at 0.34) has neither and is
    pruned instead of minting a false precedence edge.
    """
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    out_eps = list(out_span_partitions.keys())

    G = _complete_digraph(out_eps)
    rows = []
    for in_span in in_spans:
        outgoing = []
        for out_ep in out_eps:
            out_id = assignments.get(out_ep, {}).get(in_span.GetId())
            if out_id is None or not isinstance(out_id, tuple):
                continue
            span = store.all_spans.get(out_id)
            if span is None:  # NA / SKIP sentinels are 2-tuples too
                continue
            outgoing.append((span.start_mus, span.duration_mus, out_ep))
        if len(outgoing) > 1:
            rows.append(outgoing)

    cooccur: Dict[tuple, int] = {}
    contra: Dict[tuple, int] = {}
    support: Dict[tuple, int] = {}
    for outgoing in rows:
        outgoing.sort(key=lambda x: x[0])
        for i, (xs, xd, xep) in enumerate(outgoing):
            for j, (ys, yd, yep) in enumerate(outgoing):
                if i == j:
                    continue
                # the full i != j cross product visits every ordered pair
                # once per row, so each directed key is counted exactly
                # once here — adding a symmetric reverse-direction branch
                # would double contra relative to cooccur and silently
                # halve the effective tolerance
                cooccur[(xep, yep)] = cooccur.get((xep, yep), 0) + 1
                if xs + xd <= ys:  # x completed before y started
                    support[(xep, yep)] = support.get((xep, yep), 0) + 1
                else:              # overlap contradicts edge (x -> y)
                    contra[(xep, yep)] = contra.get((xep, yep), 0) + 1

    # tol=0 is an explicit request for strict any-contradiction pruning
    # (the truth-equivalence contract) — never widened adaptively.
    # Low-support pairs (common under NA/SKIP-heavy predictions) carry
    # statistically worthless rates: a 3-row pair at 1/3 must neither
    # anchor the bimodality spectrum nor enjoy the widened tolerance, so
    # pairs under MIN_SUPPORT rows are judged at the fixed tol only.
    MIN_SUPPORT = 20
    # Directional-evidence bars for pairs that survive ONLY through the
    # widened tolerance (contra rate above the fixed tol). The spectrum
    # guard is population-level; these are per-pair: a true precedence
    # edge at the worst measured noise (0.28) still supports a-before-b
    # in >= 0.72 of its rows, and its reverse direction is contradicted
    # in essentially every row (b is invoked only after a completes —
    # prediction noise puts the measured reverse rates at ~0.99). A
    # skewed-but-parallel pair at 0.34 fails both: forward support 0.66
    # and a reverse direction that b's occasional early completion keeps
    # below the near-1 bar. Without this check such a pair becomes a
    # false precedence edge whenever the spectrum happens to be bimodal
    # around it.
    MIN_DIR_SUPPORT = 0.7
    MIN_REVERSE_CONTRA = 0.98
    rates = [contra.get(k, 0) / n
             for k, n in cooccur.items() if n >= MIN_SUPPORT]
    tol_eff = _adaptive_tol(rates, tol) if tol > 0 else 0.0
    for a in out_eps:
        for b in out_eps:
            if a == b or not G.has_edge(a, b):
                continue
            n = cooccur.get((a, b), 0)
            t_ab = tol_eff if n >= MIN_SUPPORT else tol
            c_ab = contra.get((a, b), 0)
            if n == 0 or c_ab > t_ab * n:
                G.remove_edge(a, b)
                continue
            if c_ab > tol * n:
                # surviving only under the widened tolerance: demand
                # per-pair directional evidence
                sup_rate = support.get((a, b), 0) / n
                n_rev = cooccur.get((b, a), 0)
                rev_rate = (contra.get((b, a), 0) / n_rev) if n_rev else 0.0
                if (sup_rate < MIN_DIR_SUPPORT
                        and rev_rate < MIN_REVERSE_CONTRA):
                    G.remove_edge(a, b)
    while True:
        try:
            cycle = nx.find_cycle(G)
        except nx.NetworkXNoCycle:
            break
        weakest = min(cycle, key=lambda e: (support.get(e, 0), e))
        G.remove_edge(*weakest)
    return G


def discover_invocation_dag(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
    store: TraceStore,
    solver,
    method: str = "MaxScoreBatchSubsetWithSkips",
    max_iters: int = 3,
) -> nx.DiGraph:
    """GROUND-TRUTH-FREE invocation-DAG discovery (the capability the
    reference sketches but never wires: ``FindConstraintsUsingFit``,
    executor.py:152-212 — dead code there, production here).

    EM over structure: solve once with the unconstrained DAG (every
    endpoint scored from the incoming span), prune precedence edges
    contradicted by the PREDICTED assignments
    (:func:`infer_dag_from_predictions`), re-solve under the pruned DAG,
    and repeat until the edge set reaches a fixed point (typically one
    refinement). No step reads ``true_assignments`` — the empty dict is
    passed where the plugin signature demands one (the flagship only
    dereferences it for the true-skips/true-dist oracles).
    """
    import copy

    out_eps = list(out_span_partitions)
    empty_truth = {ep: {} for ep in out_eps}
    dag = nx.DiGraph()
    dag.add_nodes_from(out_eps)

    prev_edges = None
    for _ in range(max_iters):
        out = solver.FindAssignments(
            method, "gt-free-dag",
            copy.deepcopy(in_span_partitions),
            copy.deepcopy(out_span_partitions),
            False, [], empty_truth, dag,
        )
        pred = out[0] if isinstance(out, tuple) else out
        new_dag = infer_dag_from_predictions(
            in_span_partitions, out_span_partitions, pred, store)
        edges = frozenset(new_dag.edges())
        if edges == prev_edges:
            break
        prev_edges = edges
        dag = new_dag
    return dag


def fit_invocation_dag(out_span_partitions: Dict[str, List[Span]], evaluate,
                       max_edges: int = None):
    """Ground-truth-free constraint search (the reference's
    ``FindConstraintsUsingFit``, executor.py:152-212): starting from the
    unconstrained (empty) precedence DAG, greedily add the single edge whose
    addition most reduces the solver's unassigned-span count, keeping the
    graph acyclic; stop when no candidate edge improves the fit.

    ``evaluate(dag) -> int`` runs a reconstruction under the candidate DAG
    and returns its cost (the reference uses the solver's unassigned count;
    any monotone misfit measure works). Returns ``(dag, best_cost)``.
    Pair with :func:`solver_misfit` for a DAG-aware plugin solver.
    """
    out_eps = list(out_span_partitions)
    G = nx.DiGraph()
    G.add_nodes_from(out_eps)
    best = evaluate(G)
    limit = max_edges if max_edges is not None else len(out_eps) ** 2

    while G.number_of_edges() < limit:
        best_edge = None
        for a in out_eps:
            for b in out_eps:
                if a == b or G.has_edge(a, b):
                    continue
                G.add_edge(a, b)
                if nx.is_directed_acyclic_graph(G):
                    cost = evaluate(G)
                    if cost < best:
                        best, best_edge = cost, (a, b)
                G.remove_edge(a, b)
        if best_edge is None:
            break
        G.add_edge(*best_edge)
    return G, best


def solver_misfit(solver, method: str, process: str, in_span_partitions,
                  out_span_partitions, true_assignments):
    """Adapter producing an ``evaluate`` for :func:`fit_invocation_dag` from
    a DAG-aware plugin solver (one whose ``FindAssignments`` accepts an
    ``invocation_graph``, i.e. the WeaverTPU/WeaverExact V3-contract
    signature) that reports unassigned spans (tuple position 5, the
    reference solver-output convention, traceweaver_v3.py:1229)."""
    import copy as _copy
    import inspect

    params = inspect.signature(solver.FindAssignments).parameters
    if "invocation_graph" not in params:
        raise TypeError(
            f"{type(solver).__name__}.FindAssignments takes no "
            "invocation_graph — constraint search needs a DAG-aware solver"
        )

    def evaluate(dag) -> int:
        out = solver.FindAssignments(
            method, process,
            _copy.deepcopy(in_span_partitions),
            _copy.deepcopy(out_span_partitions),
            False, [], _copy.deepcopy(true_assignments),
            invocation_graph=dag,
        )
        return int(out[5]) if isinstance(out, tuple) and len(out) > 5 else 0

    return evaluate
