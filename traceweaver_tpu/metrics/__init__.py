"""Ground truth + accuracy metrics."""

from traceweaver_tpu.metrics.accuracy import (  # noqa: F401
    accuracy_end_to_end,
    accuracy_for_service,
    bin_accuracy_by_response_times,
    construct_end_to_end_traces,
    get_ground_truth,
    get_out_eps_in_order,
    topk_accuracy_end_to_end,
    topk_accuracy_for_service,
)
