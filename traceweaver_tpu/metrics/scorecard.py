"""Per-regime baseline scorecard + confidence calibration harness
(ISSUE 10, ROADMAP item 5b).

One blended accuracy number hides exactly what the paper concedes: the
statistical assignment is regime-dependent (media/nginx — high fan-out —
sits at 0.36 vs exact in BENCH_r05 while sequential services are ~1.0).
This harness makes the regime structure first-class: it runs ALL FIVE
in-repo baselines (vpath, wap5, fcfs, arrival_order, weaver_exact) plus
the TPU solver over a synthetic LABELED corpus whose services are
constructed one-per-regime (sequential / async-overlap / fan-out), and
reports accuracy per (method, regime) — the scorecard — plus the TPU
solver's confidence-decile calibration table, which is what proves
``tw.confidence`` *predicts* correctness rather than decorates it
(:func:`traceweaver_tpu.metrics.accuracy.accuracy_by_confidence_decile`).

The corpus is synthesized in-process (no datasets required — the
reference corpora are absent in CI containers), with ground truth free
by construction: spans carry their trace ids, so the exact-match join
(:func:`~traceweaver_tpu.metrics.accuracy.get_ground_truth`) labels every
span. Regime knobs (overlap burst width, delay jitter, fan-out degree)
are chosen so the difficulty ordering is structural, not sampled:
sequential requests never interleave, async bursts always do, and the
fan-out service multiplies the per-endpoint error.

Three surfaces share this module:

- the ``scorecard`` CLI subcommand (``runtime/cli.py``) — artifact +
  human table;
- the bench ``--scorecard`` leg (``bench.py``) — report fields,
  warn-flagged calibration;
- ``tests/test_quality.py`` — the tier-1 pin that the table exists, the
  regimes order sanely, and top-decile accuracy >= bottom-decile.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from traceweaver_tpu.metrics.accuracy import (
    accuracy_by_confidence_decile,
    accuracy_for_service,
    calibration_monotone,
    get_ground_truth,
    service_regime,
    span_correctness,
)
from traceweaver_tpu.spans import Span

#: method key -> how to run it (the five in-repo baselines + the solver)
BASELINE_METHODS = ("vpath", "wap5", "fcfs", "arrival_order",
                    "weaver_exact")
ALL_METHODS = BASELINE_METHODS + ("weaver_tpu",)


# ---------------------------------------------------------------------------
# synthetic labeled corpus, one service per regime
# ---------------------------------------------------------------------------

def _make_service(svc: str, n_traces: int, n_eps: int, rng,
                  spacing_us: float, burst: int,
                  jitter_us: float) -> Dict:
    """One service problem: ``n_traces`` requests on a burst/gap arrival
    pattern, each calling ``n_eps`` downstream endpoints at jittered
    offsets. ``burst`` requests share one arrival cluster (cluster
    spacing is small vs span duration, so their candidate sets overlap);
    clusters are separated by ``spacing_us`` (a perfect-cut gap)."""
    in_spans: List[Span] = []
    out_parts: Dict[str, List[Span]] = {f"{svc}-ep{e}": []
                                        for e in range(n_eps)}
    t = 0.0
    dur = 900.0
    for i in range(n_traces):
        t += 40.0 if (burst > 1 and i % burst) else spacing_us
        tid = f"{svc}-{i:04d}"
        s_in = Span(tid, "in", t, dur, "op", [], svc, "server")
        in_spans.append(s_in)
        for e in range(n_eps):
            base = 30.0 + 90.0 * e
            start = t + base + float(rng.normal(0.0, jitter_us))
            out = Span(tid, f"c{e}", max(start, t + 1.0), 40.0,
                       f"call{e}", [(tid, "in")], svc, "client")
            out_parts[f"{svc}-ep{e}"].append(out)
    # partitions arrive time-ordered (the ingest layer's contract) — NOT
    # construction order: with jittered delays this is what makes
    # order-based baselines (fcfs/arrival_order) actually pay for
    # interleaving instead of free-riding on synthetic list order
    for ep in out_parts:
        out_parts[ep].sort(key=lambda s: (s.start_mus, s.sid))
    in_parts = {f"client_{svc}": in_spans}
    truth = get_ground_truth(in_parts, out_parts)
    import networkx as nx

    dag = nx.DiGraph()
    dag.add_nodes_from(out_parts.keys())
    return dict(service=svc, in_parts=in_parts, out_parts=out_parts,
                truth=truth, dag=dag)


def synth_labeled_corpus(seed: int = 0, n_traces: int = 48) -> List[Dict]:
    """The three-regime labeled corpus (one service per regime):

    - ``seq``    — sequential: cluster size 1, arrivals spaced far past
      the span duration (windows are singletons — near-deterministic);
    - ``async``  — async-overlap: bursts of 6 requests 40 µs apart over
      900 µs durations, delay jitter comparable to the endpoint offsets
      (candidate sets overlap, margins thin);
    - ``fanout`` — the async arrival pattern times 5 endpoints (the
      exact-match bar compounds per endpoint — the media/nginx shape).
    """
    rng = np.random.default_rng(seed)
    return [
        _make_service("seq", n_traces, 2, rng,
                      spacing_us=5000.0, burst=1, jitter_us=2.0),
        _make_service("async", n_traces, 2, rng,
                      spacing_us=6000.0, burst=6, jitter_us=35.0),
        _make_service("fanout", n_traces, 5, rng,
                      spacing_us=6000.0, burst=6, jitter_us=35.0),
    ]


def _corpus_tables(corpus: List[Dict]) -> Tuple[Dict, Dict]:
    """(all_spans, all_processes) over the whole corpus — the
    constructor arguments every plugin algorithm takes."""
    all_spans: Dict = {}
    all_processes: Dict = {}
    for prob in corpus:
        for spans in list(prob["in_parts"].values()) \
                + list(prob["out_parts"].values()):
            for s in spans:
                all_spans[s.GetId()] = s
                all_processes.setdefault(s.trace_id, {})[s.process_id] = \
                    s.process_id
    return all_spans, all_processes


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

def _subset(prob: Dict, k: int) -> Tuple[Dict, Dict]:
    """First-``k`` incoming spans of a service problem with their own
    ground truth — the same identical-inputs subset device the bench's
    exact leg uses (``bench.subset_problem``): the exact DFS+MWIS path
    explodes combinatorially on overlapping regimes (that combinatorial
    wall is the paper's whole motivation), so it is graded on a capped
    slice, flagged in the artifact."""
    in_ep = next(iter(prob["in_parts"]))
    spans = sorted(prob["in_parts"][in_ep],
                   key=lambda s: (s.start_mus, s.end_mus))[:k]
    sub_in = {in_ep: spans}
    return sub_in, get_ground_truth(sub_in, prob["out_parts"])


def _run_baseline(key: str, prob: Dict, all_spans, all_processes,
                  exact_traces: Optional[int] = None):
    """Run one baseline; returns ``(pred, in_parts, truth)`` — the exact
    path solves (and is graded on) its capped subset, everything else
    the full problem."""
    from traceweaver_tpu.algorithms import FCFS, WAP5, ArrivalOrder, VPath
    from traceweaver_tpu.algorithms.weaver_exact import WeaverExact

    cls, method = {
        "vpath": (VPath, "VPath"),
        "wap5": (WAP5, "WAP5"),
        "fcfs": (FCFS, "FCFS"),
        "arrival_order": (ArrivalOrder, "ArrivalOrder"),
        "weaver_exact": (WeaverExact, "MaxScoreBatch"),
    }[key]
    in_parts, truth = prob["in_parts"], prob["truth"]
    if key == "weaver_exact" and exact_traces is not None:
        in_parts, truth = _subset(prob, exact_traces)
    algo = cls(all_spans, all_processes)
    out = algo.FindAssignments(
        method, prob["service"], in_parts, prob["out_parts"],
        False, [], truth)
    return (out[0] if isinstance(out, tuple) else out), in_parts, truth


def run_scorecard(seed: int = 0, n_traces: int = 48,
                  methods: Tuple[str, ...] = ALL_METHODS,
                  nbins: int = 10, exact_traces: int = 12) -> Dict:
    """Run the scorecard: every method over every regime service, plus
    the TPU solver's confidence calibration. Returns the artifact dict
    (JSON-serializable; :func:`write_scorecard` persists it).

    ``exact_traces`` caps the weaver_exact leg's incoming spans per
    service (its DFS+MWIS cost explodes on the overlapping regimes —
    measured 0.4 s at 8 spans vs 10 s at 16 on the async service); the
    cap ships in the artifact as ``weaver_exact_subset_spans``."""
    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet

    corpus = synth_labeled_corpus(seed=seed, n_traces=n_traces)
    all_spans, all_processes = _corpus_tables(corpus)

    per_service: Dict[str, Dict] = {}
    for prob in corpus:
        per_service[prob["service"]] = dict(
            **service_regime(prob["in_parts"], prob["out_parts"]),
            n_spans=len(next(iter(prob["in_parts"].values()))),
            methods={},
        )

    for key in methods:
        if key == "weaver_tpu":
            continue
        for prob in corpus:
            pred, in_parts, truth = _run_baseline(
                key, prob, all_spans, all_processes,
                exact_traces=exact_traces)
            acc = accuracy_for_service(pred, truth, in_parts)
            per_service[prob["service"]]["methods"][key] = round(acc, 4)

    # the TPU solver rides the REAL fleet path (shared dispatch,
    # confidence records from the packed block — obs/quality.py), so the
    # scorecard grades the production flow, not a lab re-derivation
    confidence: Dict = {}
    correct: Dict = {}
    if "weaver_tpu" in methods:
        items = [FleetItem(prob["service"], prob["in_parts"],
                           prob["out_parts"], prob["truth"], prob["dag"])
                 for prob in corpus]
        confs: List[Optional[Dict]] = [None] * len(items)
        outs = solve_fleet(items, all_spans=all_spans,
                           all_processes=all_processes,
                           confidences=confs)
        for prob, out, conf in zip(corpus, outs, confs):
            pred = out[0]
            acc = accuracy_for_service(pred, prob["truth"],
                                       prob["in_parts"])
            per_service[prob["service"]]["methods"]["weaver_tpu"] = \
                round(acc, 4)
            correct.update(span_correctness(pred, prob["truth"],
                                            prob["in_parts"]))
            for sid, rec in (conf or {}).items():
                confidence[sid] = rec["conf"]

    # per-regime means over the services in each bucket
    per_regime: Dict[str, Dict] = {}
    for svc, row in per_service.items():
        bucket = per_regime.setdefault(
            row["regime"], {m: [] for m in row["methods"]})
        for m, acc in row["methods"].items():
            bucket.setdefault(m, []).append(acc)
    per_regime = {
        regime: {m: round(sum(v) / len(v), 4)
                 for m, v in sorted(accs.items()) if v}
        for regime, accs in sorted(per_regime.items())
    }

    calibration = accuracy_by_confidence_decile(confidence, correct,
                                                nbins=nbins)
    monotone_ok, violations = calibration_monotone(calibration)
    return dict(
        seed=seed,
        n_traces=n_traces,
        weaver_exact_subset_spans=(exact_traces
                                   if "weaver_exact" in methods else None),
        methods=sorted(methods),
        per_service=per_service,
        per_regime=per_regime,
        calibration=calibration,
        calibration_monotone_ok=monotone_ok,
        calibration_violations=violations,
    )


def write_scorecard(card: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(card, f, indent=2, sort_keys=True)
        f.write("\n")


def format_scorecard(card: Dict) -> str:
    """Human table: one row per regime, one column per method, plus the
    calibration deciles."""
    methods = card["methods"]
    lines = ["scorecard (exact-match accuracy per regime; seed %d, %d "
             "traces/service)" % (card["seed"], card["n_traces"])]
    head = "%-12s" % "regime" + "".join("%14s" % m for m in methods)
    lines.append(head)
    for regime, accs in card["per_regime"].items():
        lines.append("%-12s" % regime + "".join(
            "%14s" % (("%.3f" % accs[m]) if m in accs else "-")
            for m in methods))
    if card["calibration"]:
        lines.append("confidence calibration (weaver_tpu, %d bins):"
                     % len(card["calibration"]))
        for row in card["calibration"]:
            lines.append(
                "  decile %2d  conf [%.3f, %.3f]  n=%-4d  acc %.3f"
                % (row["decile"], row["conf_lo"], row["conf_hi"],
                   row["n"], row["accuracy"]))
        lines.append("calibration monotone-ish: %s"
                     % ("OK" if card["calibration_monotone_ok"]
                        else "WARNING — " + "; ".join(
                            card["calibration_violations"])))
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m traceweaver_tpu.runtime.cli scorecard`` — run the
    per-regime baseline scorecard + calibration check and (optionally)
    persist the artifact."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m traceweaver_tpu.runtime.cli scorecard",
        description="Per-regime accuracy scorecard: all five baselines + "
                    "the TPU solver over a synthetic labeled corpus, "
                    "plus the confidence-decile calibration table "
                    "(docs/OBSERVABILITY.md 'Quality telemetry').")
    p.add_argument("--traces", type=int, default=48,
                   help="traces per regime service (default 48)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bins", type=int, default=10,
                   help="confidence calibration buckets (default 10)")
    p.add_argument("--exact-traces", type=int, default=12,
                   help="incoming-span cap for the weaver_exact leg "
                        "(its DFS+MWIS cost explodes on overlapping "
                        "regimes; the cap ships in the artifact)")
    p.add_argument("--out", default=None,
                   help="write the scorecard artifact JSON here")
    args = p.parse_args(argv)

    card = run_scorecard(seed=args.seed, n_traces=args.traces,
                         nbins=args.bins, exact_traces=args.exact_traces)
    print(format_scorecard(card))
    if args.out:
        write_scorecard(card, args.out)
        print(f"scorecard artifact -> {args.out}")
    # calibration breakage is a WARNING surface (the table says so),
    # not an exit failure — the scorecard's job is to report
    return 0
