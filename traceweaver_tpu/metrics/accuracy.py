"""Ground truth extraction and accuracy metrics.

Semantics match the reference exactly (reference:
src/trace_reconstructor/ports/python/helpers/utils.py) so that accuracy
numbers are directly comparable:

- ground truth by trace-ID join (utils.py:22-32);
- exact-match per-service accuracy — an incoming span counts only if its
  prediction is correct at *every* outgoing endpoint (utils.py:62-79);
- top-K variants (utils.py:81-97, 119-145);
- end-to-end accuracy — a trace counts only if every service got every hop
  right (utils.py:99-117);
- accuracy binned into 10 response-time percentile bins (utils.py:187-214);
- end-to-end trace assembly for the query engine (utils.py:216-252).

Plus the reconstruction-quality additions (ISSUE 10, ROADMAP item 5b):

- **regime bucketing** (:func:`service_regime`) — classify a service
  problem by the structural features that drive assignment difficulty
  (fan-out degree, async-overlap fraction), so accuracy can be reported
  per regime instead of as one blended number (PAPER.md concedes the
  blend hides 0.36-vs-exact services);
- **confidence calibration** (:func:`accuracy_by_confidence_decile` /
  :func:`calibration_monotone`) — bucket exact-match correctness by the
  solver's own confidence deciles: monotone-ish accuracy over deciles is
  what makes ``tw.confidence`` *predictive* rather than decorative.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from traceweaver_tpu.spans import NA, SKIP, Span, SpanId


def _truth(true_assignments: Dict, ep: str, in_span_id: SpanId):
    """A span missing from the ground-truth join means its trace has NO
    outgoing span on this endpoint — the correct prediction is SKIP (the
    cache-hit transform encodes exactly this state as ('Skip','Skip'),
    reference transforms.py:224). Defaulting the truth to NA instead would
    silently score "solver returned nothing" as correct; the reference
    avoids the question by indexing strictly (utils.py:62-79) under a
    GT-completeness invariant our dynamism workloads don't satisfy."""
    return true_assignments[ep].get(in_span_id, SKIP)


def get_out_eps_in_order(out_span_partitions: Dict[str, List[Span]]) -> List[str]:
    """Endpoints ordered by their first span's start time (utils.py:14-20)."""
    eps = []
    for ep, spans in out_span_partitions.items():
        assert len(spans) > 0
        eps.append((ep, spans[0].start_mus))
    eps.sort(key=lambda x: x[1])
    return [ep for ep, _ in eps]


def get_ground_truth(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
) -> Dict[str, Dict[SpanId, SpanId]]:
    """Per-endpoint truth via trace-ID join (first match wins)."""
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    true_assignments: Dict[str, Dict[SpanId, SpanId]] = {
        ep: {} for ep in out_span_partitions
    }
    # Index once instead of the reference's quadratic scan; first occurrence
    # per trace id is kept, matching the reference's "break on first match".
    for ep, out_spans in out_span_partitions.items():
        by_trace: Dict[str, SpanId] = {}
        for span in out_spans:
            by_trace.setdefault(span.trace_id, span.GetId())
        for in_span in in_spans:
            if in_span.trace_id in by_trace:
                true_assignments[ep][in_span.GetId()] = by_trace[in_span.trace_id]
    return true_assignments


def _normalize_pred(pred_assignments: Dict, ep: str, in_span_id: SpanId) -> Tuple[bool, object]:
    """Unwrap single-element list predictions (WAP5 emits lists); a
    multi-element list counts as incorrect (utils.py:37-41). A missing
    entry normalizes to NA — solvers that drop unassigned spans from their
    output (e.g. the reference's batch-MIS V2 path, which its own
    AccuracyForService would KeyError on) just score those spans wrong."""
    val = pred_assignments[ep].get(in_span_id, NA)
    if isinstance(val, list):
        if len(val) > 1:
            return False, val
        val = val[0]
        pred_assignments[ep][in_span_id] = val
    return True, val


def accuracy_for_service(
    pred_assignments: Dict,
    true_assignments: Dict,
    in_span_partitions: Dict[str, List[Span]],
) -> float:
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    cnt = 0
    for in_span in in_spans:
        correct = True
        for ep in true_assignments:
            ok, val = _normalize_pred(pred_assignments, ep, in_span.GetId())
            correct = correct and ok and val == _truth(true_assignments, ep, in_span.GetId())
        cnt += int(correct)
    return float(cnt) / len(in_spans)


def topk_accuracy_for_service(
    pred_topk_assignments: Dict,
    true_assignments: Dict,
    in_span_partitions: Dict[str, List[Span]],
) -> float:
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    ep0 = next(iter(true_assignments))
    cnt = 0
    for in_span in in_spans:
        sid = in_span.GetId()
        opts0 = pred_topk_assignments[ep0].get(sid) or [NA]
        for i in range(len(opts0)):
            correct = all(
                (pred_topk_assignments[ep].get(sid) or [NA])[i:i + 1] == [_truth(true_assignments, ep, sid)]
                for ep in true_assignments
            )
            if correct:
                cnt += 1
                break
    return float(cnt) / len(in_spans)


def accuracy_end_to_end(
    pred_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
) -> Tuple[Dict[str, bool], float]:
    trace_acc: Dict[str, bool] = {}
    for process in true_assignments_by_process:
        true_assignments = true_assignments_by_process[process]
        pred_assignments = pred_assignments_by_process[process]
        for in_span in in_spans_by_process[process]:
            trace_acc.setdefault(in_span.trace_id, True)
            for ep in true_assignments:
                if _truth(true_assignments, ep, in_span.GetId()) != pred_assignments[ep].get(in_span.GetId(), NA):
                    trace_acc[in_span.trace_id] = False
    correct = sum(trace_acc.values())
    return trace_acc, float(correct) / len(trace_acc)


def topk_accuracy_end_to_end(
    pred_topk_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
) -> Tuple[Dict[str, bool], float]:
    trace_acc: Dict[str, bool] = {}
    for i, process in enumerate(true_assignments_by_process):
        true_assignments = true_assignments_by_process[process]
        pred_topk = pred_topk_assignments_by_process[process]
        ep0 = next(iter(true_assignments))
        for in_span in in_spans_by_process[process]:
            sid = in_span.GetId()
            if i != 0 and trace_acc.get(in_span.trace_id) is False:
                continue
            options = pred_topk[ep0].get(sid) or []
            if len(options) < 1:
                trace_acc[in_span.trace_id] = False
                continue
            for j in range(len(options)):
                trace_acc[in_span.trace_id] = all(
                    [_truth(true_assignments, ep, sid)]
                    == (pred_topk[ep].get(sid) or [NA])[j:j + 1]
                    for ep in true_assignments
                )
                if trace_acc[in_span.trace_id]:
                    break
    correct = sum(trace_acc.values())
    return trace_acc, float(correct) / len(trace_acc)


def bin_accuracy_by_response_times(
    trace_acc: Dict[str, bool], all_spans: Dict[SpanId, Span], nbins: int = 10
) -> List[Tuple[float, float, float]]:
    """Accuracy per response-time percentile bin: (percentile, acc, ms)."""
    all_traces = []
    for span in all_spans.values():
        if span.IsRoot():
            all_traces.append(
                (span.duration_mus, span.trace_id, int(trace_acc[span.trace_id]), 1)
            )
    all_traces.sort()
    for i in range(1, len(all_traces)):
        _, _, c, n = all_traces[i - 1]
        t0, s0, c0, n0 = all_traces[i]
        all_traces[i] = (t0, s0, c + c0, n + n0)
    prev_c, prev_n = 0, 0
    out = []
    for b in range(nbins):
        d, _, c, n = all_traces[int((len(all_traces) * (b + 1)) / nbins - 1)]
        c, n = c - prev_c, n - prev_n
        prev_c, prev_n = prev_c + c, prev_n + n
        out.append(((b + 1) * 100 / nbins, c / n, d / 1000.0))
    return out


# ---------------------------------------------------------------------------
# regime bucketing + confidence calibration (ISSUE 10)
# ---------------------------------------------------------------------------

#: regime thresholds: fan-out at/above this is the "fanout" regime
#: (media/nginx — the paper's hard case — has 6 outgoing endpoints)
FANOUT_DEGREE = 4
#: fraction of consecutive incoming spans whose intervals overlap at/above
#: which a non-fanout service counts as "async"
ASYNC_OVERLAP_FRAC = 0.25


def overlap_fraction(in_spans: List[Span]) -> float:
    """Async-overlap fraction of a sorted-or-not incoming partition: the
    share of consecutive (by start time) spans whose [start, end)
    intervals overlap. 0.0 = strictly sequential requests (each finishes
    before the next starts — every window is one span, the easy case);
    near 1.0 = heavily interleaved traffic where candidate sets share
    members (the statistically hard case)."""
    if len(in_spans) < 2:
        return 0.0
    ordered = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
    n_overlap = sum(
        1 for a, b in zip(ordered[:-1], ordered[1:])
        if float(b.start_mus) < float(a.start_mus) + float(a.duration_mus)
    )
    return n_overlap / (len(ordered) - 1)


def service_regime(in_span_partitions: Dict[str, List[Span]],
                   out_span_partitions: Dict[str, List[Span]],
                   fanout_degree: int = FANOUT_DEGREE,
                   overlap_frac: float = ASYNC_OVERLAP_FRAC) -> Dict:
    """Classify one service problem into the scorecard's regimes.

    - ``"fanout"``     — ``fan_out >= fanout_degree`` outgoing endpoints
      (the media/nginx shape PAPER.md measures at 0.36 vs exact);
    - ``"async"``      — below the fan-out bar but with an incoming
      overlap fraction at/above ``overlap_frac`` (interleaved requests:
      candidate sets overlap, timing alone cannot separate them);
    - ``"sequential"`` — neither: requests barely interleave and
      assignment is near-deterministic.

    Returns ``{"regime", "fan_out", "overlap_frac"}`` so scorecards can
    report the raw features alongside the bucket.
    """
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    fan_out = len(out_span_partitions)
    frac = overlap_fraction(in_spans)
    if fan_out >= fanout_degree:
        regime = "fanout"
    elif frac >= overlap_frac:
        regime = "async"
    else:
        regime = "sequential"
    return dict(regime=regime, fan_out=fan_out,
                overlap_frac=round(frac, 4))


def span_correctness(pred_assignments: Dict, true_assignments: Dict,
                     in_span_partitions: Dict[str, List[Span]],
                     ) -> Dict[SpanId, bool]:
    """Per-span exact-match correctness — the per-span form of
    :func:`accuracy_for_service` (same truth/normalization rules), keyed
    by incoming span id. This is the calibration table's ground-truth
    column: a span is correct only if EVERY endpoint matched."""
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    out: Dict[SpanId, bool] = {}
    for in_span in in_spans:
        correct = True
        for ep in true_assignments:
            ok, val = _normalize_pred(pred_assignments, ep, in_span.GetId())
            correct = correct and ok and \
                val == _truth(true_assignments, ep, in_span.GetId())
        out[in_span.GetId()] = correct
    return out


def accuracy_by_confidence_decile(
    confidence: Dict[SpanId, float],
    correct: Dict[SpanId, bool],
    nbins: int = 10,
) -> List[Dict]:
    """Exact-match accuracy bucketed by the solver's OWN confidence.

    Spans are sorted by (confidence, id) and split into ``nbins``
    near-equal contiguous buckets (deciles by default); each row carries
    the bucket's confidence range, population, and accuracy. Sorting —
    rather than fixed value edges — keeps every bucket populated even
    though the base-tier score is discrete-valued.

    The table is the calibration evidence: confidence *predicts*
    correctness exactly when accuracy is (tolerantly) non-decreasing
    over the rows (:func:`calibration_monotone`).
    """
    sids = [sid for sid in confidence if sid in correct]
    sids.sort(key=lambda sid: (confidence[sid], repr(sid)))
    n = len(sids)
    table: List[Dict] = []
    if n == 0:
        return table
    for b in range(nbins):
        lo = n * b // nbins
        hi = n * (b + 1) // nbins
        chunk = sids[lo:hi]
        if not chunk:
            continue
        accs = [correct[sid] for sid in chunk]
        table.append(dict(
            decile=b + 1,
            conf_lo=round(confidence[chunk[0]], 4),
            conf_hi=round(confidence[chunk[-1]], 4),
            n=len(chunk),
            accuracy=round(sum(accs) / len(accs), 4),
        ))
    return table


def calibration_monotone(table: Sequence[Dict],
                         tol: float = 0.05) -> Tuple[bool, List[str]]:
    """Monotone-ish check over a decile table: every row's accuracy must
    be at least the running maximum of earlier rows minus a slack of
    ``tol`` plus one binomial standard error of the difference — deciles
    hold only n/10 spans each, so two buckets at the same true accuracy
    routinely differ by ~sqrt(p(1-p)/n), and a fixed tolerance would
    flap on exactly the corpora small enough for CI. A REAL inversion
    (confidently wrong at scale) still fails: the noise term vanishes as
    bucket populations grow. Returns ``(ok, violations)`` with
    human-readable violation strings for the warn path."""
    import math

    ok = True
    violations: List[str] = []
    run_max: Optional[float] = None
    run_row = 0
    run_n = 1
    for row in table:
        acc, n = row["accuracy"], max(1, row["n"])
        if run_max is not None:
            noise = math.sqrt(run_max * (1.0 - run_max) / run_n
                              + acc * (1.0 - acc) / n)
            if acc < run_max - tol - noise:
                ok = False
                violations.append(
                    "decile %d accuracy %.3f < decile %d accuracy %.3f "
                    "- tol %.2f - noise %.3f"
                    % (row["decile"], acc, run_row, run_max, tol, noise))
        if run_max is None or acc > run_max:
            run_max, run_row, run_n = acc, row["decile"], n
    return ok, violations


def construct_end_to_end_traces(
    pred_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
    all_spans: Dict[SpanId, Span],
) -> Tuple[Dict[str, List], Dict[str, List]]:
    """Assemble per-trace lists of (true, predicted) spans for the query
    engine; missing predictions become None entries (utils.py:216-252)."""
    true_traces: Dict[str, List] = {}
    pred_traces: Dict[str, List] = {}
    for process in true_assignments_by_process:
        true_assignments = true_assignments_by_process[process]
        pred_assignments = pred_assignments_by_process[process]
        for in_span in in_spans_by_process[process]:
            tid = in_span.trace_id
            if tid not in pred_traces:
                true_traces[tid] = []
                pred_traces[tid] = []
            for ep in true_assignments:
                true_traces[tid].append(all_spans.get(true_assignments[ep][in_span.GetId()]))
                options = pred_assignments[ep].get(in_span.GetId())
                if isinstance(options, list):
                    for option in options:
                        pred_traces[tid].append(all_spans.get(option))
                else:
                    pred_traces[tid].append(all_spans.get(options))
    for traces in (true_traces, pred_traces):
        for tid in traces:
            traces[tid].sort(key=lambda s: float("inf") if s is None else s.start_mus)
    return true_traces, pred_traces
