"""Ground truth extraction and accuracy metrics.

Semantics match the reference exactly (reference:
src/trace_reconstructor/ports/python/helpers/utils.py) so that accuracy
numbers are directly comparable:

- ground truth by trace-ID join (utils.py:22-32);
- exact-match per-service accuracy — an incoming span counts only if its
  prediction is correct at *every* outgoing endpoint (utils.py:62-79);
- top-K variants (utils.py:81-97, 119-145);
- end-to-end accuracy — a trace counts only if every service got every hop
  right (utils.py:99-117);
- accuracy binned into 10 response-time percentile bins (utils.py:187-214);
- end-to-end trace assembly for the query engine (utils.py:216-252).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceweaver_tpu.spans import NA, SKIP, Span, SpanId


def _truth(true_assignments: Dict, ep: str, in_span_id: SpanId):
    """A span missing from the ground-truth join means its trace has NO
    outgoing span on this endpoint — the correct prediction is SKIP (the
    cache-hit transform encodes exactly this state as ('Skip','Skip'),
    reference transforms.py:224). Defaulting the truth to NA instead would
    silently score "solver returned nothing" as correct; the reference
    avoids the question by indexing strictly (utils.py:62-79) under a
    GT-completeness invariant our dynamism workloads don't satisfy."""
    return true_assignments[ep].get(in_span_id, SKIP)


def get_out_eps_in_order(out_span_partitions: Dict[str, List[Span]]) -> List[str]:
    """Endpoints ordered by their first span's start time (utils.py:14-20)."""
    eps = []
    for ep, spans in out_span_partitions.items():
        assert len(spans) > 0
        eps.append((ep, spans[0].start_mus))
    eps.sort(key=lambda x: x[1])
    return [ep for ep, _ in eps]


def get_ground_truth(
    in_span_partitions: Dict[str, List[Span]],
    out_span_partitions: Dict[str, List[Span]],
) -> Dict[str, Dict[SpanId, SpanId]]:
    """Per-endpoint truth via trace-ID join (first match wins)."""
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    true_assignments: Dict[str, Dict[SpanId, SpanId]] = {
        ep: {} for ep in out_span_partitions
    }
    # Index once instead of the reference's quadratic scan; first occurrence
    # per trace id is kept, matching the reference's "break on first match".
    for ep, out_spans in out_span_partitions.items():
        by_trace: Dict[str, SpanId] = {}
        for span in out_spans:
            by_trace.setdefault(span.trace_id, span.GetId())
        for in_span in in_spans:
            if in_span.trace_id in by_trace:
                true_assignments[ep][in_span.GetId()] = by_trace[in_span.trace_id]
    return true_assignments


def _normalize_pred(pred_assignments: Dict, ep: str, in_span_id: SpanId) -> Tuple[bool, object]:
    """Unwrap single-element list predictions (WAP5 emits lists); a
    multi-element list counts as incorrect (utils.py:37-41). A missing
    entry normalizes to NA — solvers that drop unassigned spans from their
    output (e.g. the reference's batch-MIS V2 path, which its own
    AccuracyForService would KeyError on) just score those spans wrong."""
    val = pred_assignments[ep].get(in_span_id, NA)
    if isinstance(val, list):
        if len(val) > 1:
            return False, val
        val = val[0]
        pred_assignments[ep][in_span_id] = val
    return True, val


def accuracy_for_service(
    pred_assignments: Dict,
    true_assignments: Dict,
    in_span_partitions: Dict[str, List[Span]],
) -> float:
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    cnt = 0
    for in_span in in_spans:
        correct = True
        for ep in true_assignments:
            ok, val = _normalize_pred(pred_assignments, ep, in_span.GetId())
            correct = correct and ok and val == _truth(true_assignments, ep, in_span.GetId())
        cnt += int(correct)
    return float(cnt) / len(in_spans)


def topk_accuracy_for_service(
    pred_topk_assignments: Dict,
    true_assignments: Dict,
    in_span_partitions: Dict[str, List[Span]],
) -> float:
    assert len(in_span_partitions) == 1
    _, in_spans = next(iter(in_span_partitions.items()))
    ep0 = next(iter(true_assignments))
    cnt = 0
    for in_span in in_spans:
        sid = in_span.GetId()
        opts0 = pred_topk_assignments[ep0].get(sid) or [NA]
        for i in range(len(opts0)):
            correct = all(
                (pred_topk_assignments[ep].get(sid) or [NA])[i:i + 1] == [_truth(true_assignments, ep, sid)]
                for ep in true_assignments
            )
            if correct:
                cnt += 1
                break
    return float(cnt) / len(in_spans)


def accuracy_end_to_end(
    pred_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
) -> Tuple[Dict[str, bool], float]:
    trace_acc: Dict[str, bool] = {}
    for process in true_assignments_by_process:
        true_assignments = true_assignments_by_process[process]
        pred_assignments = pred_assignments_by_process[process]
        for in_span in in_spans_by_process[process]:
            trace_acc.setdefault(in_span.trace_id, True)
            for ep in true_assignments:
                if _truth(true_assignments, ep, in_span.GetId()) != pred_assignments[ep].get(in_span.GetId(), NA):
                    trace_acc[in_span.trace_id] = False
    correct = sum(trace_acc.values())
    return trace_acc, float(correct) / len(trace_acc)


def topk_accuracy_end_to_end(
    pred_topk_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
) -> Tuple[Dict[str, bool], float]:
    trace_acc: Dict[str, bool] = {}
    for i, process in enumerate(true_assignments_by_process):
        true_assignments = true_assignments_by_process[process]
        pred_topk = pred_topk_assignments_by_process[process]
        ep0 = next(iter(true_assignments))
        for in_span in in_spans_by_process[process]:
            sid = in_span.GetId()
            if i != 0 and trace_acc.get(in_span.trace_id) is False:
                continue
            options = pred_topk[ep0].get(sid) or []
            if len(options) < 1:
                trace_acc[in_span.trace_id] = False
                continue
            for j in range(len(options)):
                trace_acc[in_span.trace_id] = all(
                    [_truth(true_assignments, ep, sid)]
                    == (pred_topk[ep].get(sid) or [NA])[j:j + 1]
                    for ep in true_assignments
                )
                if trace_acc[in_span.trace_id]:
                    break
    correct = sum(trace_acc.values())
    return trace_acc, float(correct) / len(trace_acc)


def bin_accuracy_by_response_times(
    trace_acc: Dict[str, bool], all_spans: Dict[SpanId, Span], nbins: int = 10
) -> List[Tuple[float, float, float]]:
    """Accuracy per response-time percentile bin: (percentile, acc, ms)."""
    all_traces = []
    for span in all_spans.values():
        if span.IsRoot():
            all_traces.append(
                (span.duration_mus, span.trace_id, int(trace_acc[span.trace_id]), 1)
            )
    all_traces.sort()
    for i in range(1, len(all_traces)):
        _, _, c, n = all_traces[i - 1]
        t0, s0, c0, n0 = all_traces[i]
        all_traces[i] = (t0, s0, c + c0, n + n0)
    prev_c, prev_n = 0, 0
    out = []
    for b in range(nbins):
        d, _, c, n = all_traces[int((len(all_traces) * (b + 1)) / nbins - 1)]
        c, n = c - prev_c, n - prev_n
        prev_c, prev_n = prev_c + c, prev_n + n
        out.append(((b + 1) * 100 / nbins, c / n, d / 1000.0))
    return out


def construct_end_to_end_traces(
    pred_assignments_by_process: Dict[str, Dict],
    true_assignments_by_process: Dict[str, Dict],
    in_spans_by_process: Dict[str, List[Span]],
    all_spans: Dict[SpanId, Span],
) -> Tuple[Dict[str, List], Dict[str, List]]:
    """Assemble per-trace lists of (true, predicted) spans for the query
    engine; missing predictions become None entries (utils.py:216-252)."""
    true_traces: Dict[str, List] = {}
    pred_traces: Dict[str, List] = {}
    for process in true_assignments_by_process:
        true_assignments = true_assignments_by_process[process]
        pred_assignments = pred_assignments_by_process[process]
        for in_span in in_spans_by_process[process]:
            tid = in_span.trace_id
            if tid not in pred_traces:
                true_traces[tid] = []
                pred_traces[tid] = []
            for ep in true_assignments:
                true_traces[tid].append(all_spans.get(true_assignments[ep][in_span.GetId()]))
                options = pred_assignments[ep].get(in_span.GetId())
                if isinstance(options, list):
                    for option in options:
                        pred_traces[tid].append(all_spans.get(option))
                else:
                    pred_traces[tid].append(all_spans.get(options))
    for traces in (true_traces, pred_traces):
        for tid in traces:
            traces[tid].sort(key=lambda s: float("inf") if s is None else s.start_mus)
    return true_traces, pred_traces
