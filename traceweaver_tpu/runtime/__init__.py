"""Executor runtime: library API + reference-compatible CLI."""

from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment  # noqa: F401
