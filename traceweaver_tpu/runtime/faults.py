"""Deterministic fault injection for the solve supervisor (``TW_FAULTS``).

The reconstructor's value proposition — reconstruction without touching
the application — only holds if the reconstructor itself survives
production conditions: a transient ``XlaRuntimeError`` or
``RESOURCE_EXHAUSTED`` inside a fused fleet dispatch must degrade, not
abort the solve, and a truncated checkpoint must resume from the
previous one, not crash the stream. This module is the *test stimulus*
for that machinery: a seeded, spec-driven injector whose failure draws
are woven into the real production code paths (device dispatch, D2H
fetches, the per-service host fallback, checkpoint I/O, source reads),
so the degradation ladder in :mod:`traceweaver_tpu.algorithms.fleet`
and the stream's dead-letter/integrity consumers can be exercised
deterministically on any backend — chaos testing without a chaotic
environment.

Spec grammar (``TW_FAULTS``)::

    TW_FAULTS="dispatch:0.2,fetch:0.05"          # site:probability
    TW_FAULTS="dispatch:1.0:max=3"               # cap injections per site
    TW_FAULTS_SEED=7                             # RNG seed (default 0)

Sites (anything else raises — the ops/precision.py raise-on-typo rule):

- ``dispatch``   — fused fleet device dispatch (fleet supervisor);
- ``fetch``      — blocking D2H fetches (``fleet._fetch``);
- ``host``       — the per-service host-fallback solve (the ladder's
  last compute rung; injecting here is how tests force quarantine);
- ``checkpoint`` — checkpoint save/load I/O (``stream/checkpoint.py``);
- ``source``     — span-source reads (``stream/service.py`` run loop);
- ``devcols``    — device-resident column-ring operations (ring append
  at group resolve + resident window assembly, ``ops/devcols.py``);
  unlike the transient sites above, a faulted ring would poison every
  LATER dispatch that gathers from it, so the supervisor answers with
  the ring-invalidate-and-rebuild rung (``devcols_ring_rebuilds``)
  before retrying;
- ``capture``    — capture ingress payload chunks
  (``collector/source.py``): a drawn chunk is DROPPED, not retried (a
  collector cannot re-read bytes the kernel already discarded), and the
  rest of that connection direction is discarded with it — you cannot
  resynchronize an HTTP/2 byte stream after a gap — all counted in
  ``tw_capture_loss_total`` and absorbed by the partial-capture policy;
- ``skew``       — per-capture-source clock skew: a drawn source's raw
  timestamps are offset by ``TW_SKEW_CHAOS_US`` before the ingress sees
  them, the stimulus the skew estimator must detect and correct. Like
  ``capture``, this site is consumed via ``plan.should_fail`` (a state
  perturbation, not a raised error), so :func:`maybe_fail` never fires
  for it inside the solve supervisor;
- ``wal``       — write-ahead-log I/O (``stream/wal.py``): a drawn
  append writes HALF a frame before raising (a genuine torn append —
  the client never gets an ack and the next open truncates the partial
  record, counted in ``wal_torn_tail``); the same site gates the fsync
  path, standing in for a full disk or yanked volume.

Determinism: one seeded RNG shared across sites, so a given
``(spec, seed)`` produces one fixed draw sequence. Under the pipelined
dispatcher several threads draw concurrently and the *interleaving* may
vary run to run; tests that need exact reproducibility pin
``TW_PIPELINE=0`` or use probability 0/1. With ``TW_FAULTS`` unset every
hook is a no-op returning immediately — the default solve runs the
HEAD program bit-identically (pinned by ``tests/test_faults.py``).
"""

from __future__ import annotations

import os
import random
import threading
from contextlib import contextmanager
from typing import Dict, Optional

#: every legal injection site, in ladder order of first appearance
SITES = ("dispatch", "fetch", "host", "checkpoint", "source", "devcols",
         "capture", "skew", "wal")


class FaultError(RuntimeError):
    """An injected fault (stands in for ``XlaRuntimeError`` and friends).

    Raised by :func:`maybe_fail`; classified as a device/transient fault
    by :func:`is_transient_fault`, so it walks the same supervisor
    ladder a real runtime error would."""


class FaultPlan:
    """One parsed ``TW_FAULTS`` spec plus its live injection state."""

    def __init__(self, sites: Dict[str, "SiteSpec"], seed: int = 0) -> None:
        self.sites = sites
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = {s: 0 for s in sites}
        self.draws = {s: 0 for s in sites}

    def should_fail(self, site: str) -> bool:
        spec = self.sites.get(site)
        if spec is None:
            return False
        with self._lock:
            self.draws[site] += 1
            if spec.max is not None and self.injected[site] >= spec.max:
                return False
            if self._rng.random() < spec.p:
                self.injected[site] += 1
                return True
        return False

    def total_injected(self) -> int:
        with self._lock:
            return sum(self.injected.values())


class SiteSpec:
    __slots__ = ("p", "max")

    def __init__(self, p: float, max: Optional[int] = None) -> None:
        self.p = p
        self.max = max


def parse_faults(spec: str, seed: int = 0) -> Optional[FaultPlan]:
    """Parse a ``TW_FAULTS`` spec string into a :class:`FaultPlan`.

    Empty/blank specs mean "no injection" (None). Unknown sites, bad
    probabilities, and malformed options raise ``ValueError`` — a typo'd
    chaos spec must fail loudly, never silently run an unfaulted solve
    that then "passes" the chaos leg.
    """
    spec = (spec or "").strip()
    if not spec:
        return None
    sites: Dict[str, SiteSpec] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"TW_FAULTS entry {entry!r}: expected site:probability")
        site = parts[0].strip()
        if site not in SITES:
            raise ValueError(
                f"TW_FAULTS entry {entry!r}: unknown site {site!r}; "
                f"expected one of {SITES}")
        try:
            p = float(parts[1])
        except ValueError:
            raise ValueError(
                f"TW_FAULTS entry {entry!r}: probability {parts[1]!r} "
                "is not a number") from None
        if not 0.0 <= p <= 1.0:
            raise ValueError(
                f"TW_FAULTS entry {entry!r}: probability {p} not in [0, 1]")
        max_n: Optional[int] = None
        for opt in parts[2:]:
            key, _, val = opt.partition("=")
            if key.strip() != "max":
                raise ValueError(
                    f"TW_FAULTS entry {entry!r}: unknown option {opt!r}; "
                    "expected max=N")
            try:
                max_n = int(val)
            except ValueError:
                raise ValueError(
                    f"TW_FAULTS entry {entry!r}: max={val!r} is not an "
                    "integer") from None
            if max_n < 0:
                raise ValueError(
                    f"TW_FAULTS entry {entry!r}: max must be >= 0")
        if site in sites:
            raise ValueError(f"TW_FAULTS: duplicate site {site!r}")
        sites[site] = SiteSpec(p, max_n)
    return FaultPlan(sites, seed=seed)


# the active plan is cached per (spec, seed) env value so injection state
# (RNG sequence, per-site counters) persists across calls within one run;
# changing the env (tests: monkeypatch) transparently rebuilds it
_ACTIVE: Optional[FaultPlan] = None
_ACTIVE_KEY: Optional[tuple] = None
_OVERRIDE: Optional[FaultPlan] = None
_STATE_LOCK = threading.Lock()


def active() -> Optional[FaultPlan]:
    """The live fault plan: a programmatic :func:`override` if one is in
    force, else the (cached) ``TW_FAULTS``/``TW_FAULTS_SEED`` env plan,
    else None. Read at call time, like every other ``TW_*`` knob."""
    global _ACTIVE, _ACTIVE_KEY
    if _OVERRIDE is not None:
        return _OVERRIDE
    spec = os.environ.get("TW_FAULTS", "")
    seed_raw = os.environ.get("TW_FAULTS_SEED", "0")
    try:
        seed = int(seed_raw)
    except ValueError:
        raise ValueError(
            f"TW_FAULTS_SEED={seed_raw!r} is not an integer") from None
    key = (spec, seed)
    with _STATE_LOCK:
        if key != _ACTIVE_KEY:
            _ACTIVE = parse_faults(spec, seed=seed)
            _ACTIVE_KEY = key
        return _ACTIVE


def reset() -> None:
    """Drop all injection state (tests: a fresh plan re-seeds the RNG)."""
    global _ACTIVE, _ACTIVE_KEY, _OVERRIDE
    with _STATE_LOCK:
        _ACTIVE = None
        _ACTIVE_KEY = None
        _OVERRIDE = None


@contextmanager
def override_plan(plan: Optional["FaultPlan"]):
    """Force an EXISTING fault plan for the duration of the context.

    Unlike :func:`override` (which parses a fresh plan, re-seeding the
    RNG), this keeps the plan's draw position and injection counters
    across entries — the serve layer's per-tenant fault storms re-enter
    every pump with ONE persistent plan, so a ``p=0.5`` storm actually
    fires on roughly half its draws instead of replaying the same first
    draw forever."""
    global _OVERRIDE
    prev = _OVERRIDE
    _OVERRIDE = plan
    try:
        yield plan
    finally:
        _OVERRIDE = prev


@contextmanager
def override(spec: str, seed: int = 0):
    """Force a fault plan for the duration of the context, regardless of
    the env (the bench chaos leg uses this so one process can run a
    faulted and an unfaulted leg side by side). Yields the plan so the
    caller can read its injection counters afterwards."""
    with override_plan(parse_faults(spec, seed=seed)) as plan:
        yield plan


def maybe_fail(site: str) -> None:
    """Raise :class:`FaultError` if the active plan draws a failure for
    ``site``. No-op (one dict lookup) when no plan is active — the
    TW_FAULTS-unset production path stays bit-identical to HEAD. Every
    injection also lands in the structured event sink when one is
    installed (``TW_EVENTS``, obs/events.py) so a chaos run's stimulus
    is tail-able next to the ladder rungs it provoked."""
    plan = active()
    if plan is not None and plan.should_fail(site):
        from traceweaver_tpu.obs import events as _events

        _events.emit("fault_injected", site, n=plan.injected[site],
                     seed=plan.seed)
        raise FaultError(f"injected fault at site {site!r} "
                         f"(#{plan.injected[site]}, seed {plan.seed})")


# message fragments that mark a *transient* runtime failure — the kinds a
# retry/degrade ladder can meaningfully absorb (OOM, preemption, relay
# flake), per the jax/XLA status taxonomy
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "DATA_LOSS",
                      "INTERNAL", "ABORTED", "DEADLINE_EXCEEDED",
                      "CANCELLED")


def is_transient_fault(exc: BaseException) -> bool:
    """Should the solve supervisor walk its degradation ladder for this
    exception? True for injected faults, ``XlaRuntimeError`` (any
    status — a device program that died is retryable by redispatch), and
    runtime/OS errors carrying a transient XLA status marker. Everything
    else (TypeError, ValueError, assertion failures ...) is a *bug* and
    must propagate unchanged — retrying a deterministic error would loop
    the ladder for nothing and bury the traceback."""
    if isinstance(exc, FaultError):
        return True
    if type(exc).__name__ == "XlaRuntimeError":
        return True
    if isinstance(exc, (RuntimeError, OSError)):
        msg = str(exc)
        return any(marker in msg for marker in _TRANSIENT_MARKERS)
    return False
